"""Benchmark: Llama training throughput (tokens/sec) on the local chip.

Compares the framework's compiled train step against a hand-written "naive
JAX" Llama trainer (the BASELINE.json data-parallel baseline, scaled to the
available chip count) at identical config/batch/dtype/optimizer. The LAST
stdout line is the result JSON: {"metric", "value", "unit", "vs_baseline"}.

Resilience (the tunneled TPU backend is known to hang `jax.devices()`
indefinitely inside backend init — observed r03):
  * the parent NEVER touches jax; device facts come from child JSON
  * backend init is probed in a bounded, retried subprocess before any
    real work
  * each side runs under its own deadline and is retried once
  * the proven 200m config runs FIRST and its result line is printed
    immediately; 1b runs after, and on success prints a superseding line
    (so an outer kill mid-1b still leaves a parsed 200m line)
  * on unrecoverable failure a diagnostic JSON line is printed
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_T0 = time.time()


def _log(msg: str) -> None:
    """Phase progress on stderr (stdout carries only the JSON line)."""
    print(f"[bench +{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def _bench_profile() -> str:
    """"smoke" | "200m" | "1b" — the SINGLE source of truth for which bench
    configuration this process runs. Every fairness knob (remat policy,
    optimizer state dtype, metric name) keys off this one function so the
    two sides can never drift apart."""
    if os.environ.get("FLEXFLOW_BENCH_SMOKE"):
        return "smoke"
    cfg = os.environ.get("FLEXFLOW_BENCH_CONFIG", "1b")
    if cfg not in ("1b", "200m"):
        sys.exit(f"unknown FLEXFLOW_BENCH_CONFIG={cfg!r} (want 1b|200m)")
    return cfg


def _llama_cfg(profile: str | None = None):
    from flexflow_tpu.models.llama import LlamaConfig

    prof = profile or _bench_profile()
    if prof == "smoke":
        return LlamaConfig.tiny()
    if prof == "200m":
        # ~200M params (rounds 1-2 continuity config)
        return LlamaConfig(vocab_size=32000, dim=1024, layers=12, heads=16,
                           kv_heads=8, hidden=2816)
    # default: ~0.9B params — the largest Llama that fits one v5e chip with
    # fp32 master weights + Adam state (BASELINE's Llama-3-8B shape, scaled)
    return LlamaConfig.bench_1b()


BATCH = int(os.environ.get("FLEXFLOW_BENCH_BATCH", "8"))
SEQ = 1024
WARMUP, ITERS = 3, 10


def _sync(out):
    # NOTE: on tunneled TPU backends block_until_ready may not synchronize;
    # fetching a scalar to host always does (and forces the whole dependency
    # chain of sequential steps behind it)
    return float(np.asarray(out))


def _time_steps(step_fn, *, iters=None, warmup=None):
    iters = ITERS if iters is None else iters      # read at call time so
    warmup = WARMUP if warmup is None else warmup  # --smoke overrides apply
    _log("warmup/compile start")
    for _ in range(warmup):
        out = step_fn()
    _sync(out)
    _log("warmup done; timing")
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step_fn()
    _sync(out)
    dt = (time.perf_counter() - t0) / iters
    _log(f"timed {iters} steps @ {dt * 1e3:.1f} ms/step")
    return dt


def _flops_per_token(cfg, seq: int) -> float:
    """Analytic matmul FLOPs per trained token (fwd+bwd = 3× fwd matmul
    FLOPs; causal attention counted at half density). Mirrors the
    reference's measure-everything discipline (simulator.cc:537) as a model."""
    hd = cfg.dim // cfg.heads
    per_layer = (
        cfg.dim * cfg.heads * hd          # wq
        + 2 * cfg.dim * cfg.kv_heads * hd  # wk, wv
        + cfg.heads * hd * cfg.dim         # wo
        + 3 * cfg.dim * cfg.hidden         # gate, up, down
    )
    n_matmul = cfg.layers * per_layer + cfg.dim * cfg.vocab_size  # + lm_head
    # per token: 2 flops/MAC × 3 (fwd+bwd) = 6 × params touched by matmuls
    dense = 6.0 * n_matmul
    # attention: QK^T + PV are each seq×dim MACs/token; ×2 flops ×3 fwd+bwd
    # ×0.5 causal
    attn = 6.0 * cfg.layers * seq * cfg.dim
    return dense + attn


def _peak_flops(device_kind: str, n_devices: int) -> float:
    """Best-effort bf16 peak of the whole local machine (all chips — the
    bench throughput spans every device the framework uses). Pure function
    of child-reported device facts: the parent never touches jax."""
    kind = device_kind.lower()
    table = {
        "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12,
        "v6 lite": 918e12, "v6e": 918e12,
    }
    per_chip = 197e12
    for k, v in table.items():
        if k in kind:
            per_chip = v
            break
    return per_chip * n_devices


def bench_framework(x, y) -> float:
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, LossType
    from flexflow_tpu.models.llama import build_llama

    import jax

    _log("framework: building model")
    if _bench_profile() == "1b":
        # ~0.9B params: fp32 masters + Adam state alone are ~7 GB, so the
        # framework uses its selective MLP-hidden remat (~2% extra FLOPs)
        # and bf16 moment STORAGE (update math stays fp32; the naive
        # baseline gets the identical optimizer numerics — see bench_naive)
        cfg = FFConfig(batch_size=BATCH, remat="hidden")
        opt = AdamOptimizer(lr=1e-4, state_dtype="bfloat16")
    else:
        # 200M: everything fits with no remat; both sides run fp32 Adam
        cfg = FFConfig(batch_size=BATCH, remat="none")
        opt = AdamOptimizer(lr=1e-4)
    ff = FFModel(cfg)
    build_llama(ff, _llama_cfg(), seq_len=SEQ)
    ff.compile(optimizer=opt,
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    _log("framework: compiled model/params")
    step = ff.executor.train_step()
    tr, ntr = ff._params
    opt = ff._opt_state
    rng = jax.random.key(0)
    xb, yb = jax.device_put(x), jax.device_put(y)

    state = {"tr": tr, "ntr": ntr, "opt": opt}

    def run():
        state["tr"], state["ntr"], state["opt"], m = step(
            state["tr"], state["ntr"], state["opt"], rng, yb, xb
        )
        return m["loss"]

    dt = _time_steps(run)
    return BATCH * SEQ / dt


def bench_naive(x, y) -> float:
    """Hand-written JAX Llama train step: straightforward per-layer code,
    jit + grad + Adam, bf16 activations / fp32 params — what a user would
    write without the framework."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    cfg = _llama_cfg()
    hd = cfg.dim // cfg.heads

    def init(rng):
        keys = iter(jax.random.split(rng, 8 * cfg.layers + 4))
        p = {"emb": jax.random.normal(next(keys), (cfg.vocab_size, cfg.dim)) * 0.02}
        for i in range(cfg.layers):
            g = 1.0 / np.sqrt(cfg.dim)
            p[f"l{i}"] = {
                "wq": jax.random.normal(next(keys), (cfg.dim, cfg.heads, hd)) * g,
                "wk": jax.random.normal(next(keys), (cfg.dim, cfg.kv_heads, hd)) * g,
                "wv": jax.random.normal(next(keys), (cfg.dim, cfg.kv_heads, hd)) * g,
                "wo": jax.random.normal(next(keys), (cfg.heads, hd, cfg.dim)) * g,
                "ln1": jnp.ones(cfg.dim), "ln2": jnp.ones(cfg.dim),
                "gate": jax.random.normal(next(keys), (cfg.dim, cfg.hidden)) * g,
                "up": jax.random.normal(next(keys), (cfg.dim, cfg.hidden)) * g,
                "down": jax.random.normal(next(keys), (cfg.hidden, cfg.dim))
                * (1.0 / np.sqrt(cfg.hidden)),
            }
        p["lnf"] = jnp.ones(cfg.dim)
        p["head"] = jax.random.normal(next(keys), (cfg.dim, cfg.vocab_size)) * 0.02
        return p

    def rms(x, w):
        xf = x.astype(jnp.float32)
        return (xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-5)
                * w).astype(x.dtype)

    def rope(x):
        B, S, H, D = x.shape
        fr = 500000.0 ** (-jnp.arange(D // 2, dtype=jnp.float32) / (D // 2))
        ang = jnp.arange(S, dtype=jnp.float32)[:, None] * fr[None]
        cos, sin = jnp.cos(ang)[None, :, None, :], jnp.sin(ang)[None, :, None, :]
        xf = x.astype(jnp.float32)
        x1, x2 = xf[..., : D // 2], xf[..., D // 2 :]
        return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                               -1).astype(x.dtype)

    def layer(L, h):
        a = rms(h, L["ln1"])
        q = rope(jnp.einsum("bse,ehd->bshd", a, L["wq"].astype(jnp.bfloat16)))
        k = rope(jnp.einsum("bse,ehd->bshd", a, L["wk"].astype(jnp.bfloat16)))
        v = jnp.einsum("bse,ehd->bshd", a, L["wv"].astype(jnp.bfloat16))
        k = jnp.repeat(k, cfg.heads // cfg.kv_heads, 2)
        v = jnp.repeat(v, cfg.heads // cfg.kv_heads, 2)
        logits = jnp.einsum("bshd,bthd->bhst", q, k,
                            preferred_element_type=jnp.float32) / np.sqrt(hd)
        S = h.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, jnp.finfo(jnp.float32).min)
        pr = jax.nn.softmax(logits, -1).astype(jnp.bfloat16)
        o = jnp.einsum("bhst,bthd->bshd", pr, v)
        h = h + jnp.einsum("bshd,hde->bse", o, L["wo"].astype(jnp.bfloat16))
        m = rms(h, L["ln2"])
        g = jnp.einsum("bse,eh->bsh", m, L["gate"].astype(jnp.bfloat16))
        u = jnp.einsum("bse,eh->bsh", m, L["up"].astype(jnp.bfloat16))
        return h + jnp.einsum("bsh,he->bse", jax.nn.silu(g) * u,
                              L["down"].astype(jnp.bfloat16))

    # Best feasible baseline config on a 16GB chip: no-remat OOMs (the S^2
    # fp32 attention residuals alone are ~3GB), so the baseline gets the
    # standard best-practice policy — save projection matmul outputs,
    # recompute attention internals. At the ~0.9B config even that OOMs
    # (fp32 p+m+v is 10.6 GB, saved matmul outputs ~7 GB), so the baseline
    # falls back to the standard full per-layer remat a user reaches for
    # next. The framework side needs no remat at 200M and only the ~2%
    # selective MLP-hidden remat at 1b (Pallas flash attention keeps
    # memory O(S)); that asymmetry is a real framework win, not a
    # baseline handicap.
    naive_remat = os.environ.get("FLEXFLOW_BENCH_NAIVE_REMAT")
    if naive_remat is None:
        naive_remat = "full" if _bench_profile() == "1b" else "dots"
    if naive_remat == "dots":
        layer_ckpt = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    else:
        layer_ckpt = jax.checkpoint(layer)

    def fwd(p, ids):
        h = p["emb"].astype(jnp.bfloat16)[ids]
        for i in range(cfg.layers):
            h = layer_ckpt(p[f"l{i}"], h)
        h = rms(h, p["lnf"])
        return jnp.einsum("bse,ev->bsv", h, p["head"].astype(jnp.bfloat16))

    def loss_fn(p, ids, tgt):
        lg = fwd(p, ids).astype(jnp.float32)
        lp = jax.nn.log_softmax(lg, -1)
        ll = jnp.take_along_axis(lp, tgt[..., None], -1)
        return -jnp.mean(ll)

    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-4
    # at the 1B config BOTH sides store Adam moments in bf16 (update math
    # fp32) — identical optimizer numerics to the framework side
    state_dt = jnp.bfloat16 if _bench_profile() == "1b" else jnp.float32

    # donate p/m/v so the update aliases the old buffers in place — without
    # this, old+new fp32 state coexists (~21 GB at the 0.9B config) and no
    # remat policy can fit the step on a 16 GB chip
    @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def step(p, m, v, t, ids, tgt):
        g = jax.grad(loss_fn)(p, ids, tgt)
        t = t + 1
        m = jax.tree.map(
            lambda m_, g_: (b1 * m_.astype(jnp.float32)
                            + (1 - b1) * g_).astype(state_dt), m, g)
        v = jax.tree.map(
            lambda v_, g_: (b2 * v_.astype(jnp.float32)
                            + (1 - b2) * g_ * g_).astype(state_dt), v, g)
        bc1 = 1 - b1 ** t.astype(jnp.float32)
        bc2 = 1 - b2 ** t.astype(jnp.float32)
        p = jax.tree.map(
            lambda p_, m_, v_: p_ - lr * (m_.astype(jnp.float32) / bc1)
            / (jnp.sqrt(v_.astype(jnp.float32) / bc2) + eps),
            p, m, v,
        )
        return p, m, v, t

    _log("naive: init params")
    rng = jax.random.key(0)
    p = jax.jit(init)(rng)
    m = jax.tree.map(lambda x: jnp.zeros_like(x, dtype=state_dt), p)
    v = jax.tree.map(lambda x: jnp.zeros_like(x, dtype=state_dt), p)
    t = jnp.zeros((), jnp.int32)
    ids, tgt = jax.device_put(x), jax.device_put(y)

    state = {"p": p, "m": m, "v": v, "t": t}

    def run():
        state["p"], state["m"], state["v"], state["t"] = step(
            state["p"], state["m"], state["v"], state["t"], ids, tgt
        )
        return state["t"]

    dt = _time_steps(run)
    return BATCH * SEQ / dt


def bench_decode() -> dict:
    """Serving-side benchmark (bench.py --decode): paged continuous-
    batching decode throughput, then speculative decoding on a
    repetitive-prompt fixture (a token-cyclic model, so the n-gram
    drafter's acceptance is exercised for real). Runs in-process — CPU
    under --smoke, any backend otherwise — and reports decode tokens/sec
    plus the speculation acceptance metrics, so BENCH json covers
    serving, not just training step time."""
    from flexflow_tpu import FFConfig, FFModel, LossType
    from flexflow_tpu.ffconst import DataType
    from flexflow_tpu.models.llama import LlamaConfig, build_llama
    from flexflow_tpu.spec import SpecConfig

    smoke = bool(os.environ.get("FLEXFLOW_BENCH_SMOKE"))
    if smoke:
        lcfg = LlamaConfig.tiny(vocab=128)
        n_req, max_new, max_len, page = 6, 16, 64, 8
    else:
        lcfg = LlamaConfig(vocab_size=8192, dim=512, layers=6, heads=8,
                           kv_heads=4, hidden=1408, rope_theta=10000.0)
        n_req, max_new, max_len, page = 16, 128, 512, 64
    _log(f"decode bench: building model (vocab={lcfg.vocab_size}, "
         f"dim={lcfg.dim}, layers={lcfg.layers})")
    ff = FFModel(FFConfig(batch_size=1, seed=0))
    build_llama(ff, lcfg, batch_size=1, seq_len=8, dtype=DataType.FLOAT)
    ff.compile(loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)

    def run_server(prompts, speculate=None, max_new_tokens=None):
        mn = max_new if max_new_tokens is None else int(max_new_tokens)
        server = ff.serve_generation(slots=4, max_len=max_len, paged=True,
                                     page_size=page, speculate=speculate)
        try:
            # warm every compile off the clock: both prefill buckets the
            # 4..16-token prompts can hit (8 and 16) plus the decode step
            server.generate(prompts[0][:3], max_new_tokens=2)
            server.generate(np.tile(prompts[0], 4)[:16], max_new_tokens=2)
            warm = server.metrics().get("speculative", {})
            t0 = time.perf_counter()
            futs = [server.submit(p, max_new_tokens=mn)
                    for p in prompts]
            outs = [f.result(timeout=1200) for f in futs]
            dt = time.perf_counter() - t0
            metrics = server.metrics()
            sm = metrics.get("speculative")
            if sm:
                # report the TIMED window only: subtract the warm-up
                # requests' raw counters and re-derive the two rates
                for k in ("steps", "draft_tokens", "accepted_tokens",
                          "emitted_tokens"):
                    sm[k] -= warm.get(k, 0)
                sm["acceptance_rate"] = (sm["accepted_tokens"]
                                         / sm["draft_tokens"]
                                         if sm["draft_tokens"] else 0.0)
                sm["accepted_tokens_per_step"] = (sm["emitted_tokens"]
                                                  / sm["steps"]
                                                  if sm["steps"] else 0.0)
        finally:
            server.stop()
        toks = sum(len(o) for o in outs)
        return toks / dt, toks, metrics

    # fixtures come from the named traffic profiles (search/traffic.py)
    # so the bench and the serving-strategy search (ISSUE 12) score
    # against the SAME workloads; each profile draws through `rs` in the
    # order the inline fixtures always used, so seeded draws are stable
    from flexflow_tpu.search import traffic as traffic_mod

    smoke_prof = traffic_mod.get_profile("smoke", requests=n_req,
                                         new_tokens=max_new)
    prompts = smoke_prof.sample(rs, lcfg.vocab_size).prompts
    _log("decode bench: plain paged serving")
    tps, toks, plain_m = run_server(prompts)
    # tick-latency percentiles ride the always-on serving histograms
    # (fftrace/obs.metrics) — no tracing needed for these
    tick_h = plain_m["histograms"]["tick_latency_s"]

    # TTFT compile/serve split (shapecheck runtime arm): percentiles
    # over ALL requests including the warm-ups — those pay the
    # first-compile cost, so incl-vs-excl is exactly what catalog
    # warming (Server.warm_launch_shapes) saves a cold first request
    recs = [r for r in plain_m["requests"] if r["ttft_s"] is not None]
    ttft_split = {
        "ttft_p95_incl_compile_s": round(float(np.percentile(
            [r["ttft_s"] for r in recs], 95)), 6),
        "ttft_p95_excl_compile_s": round(float(np.percentile(
            [r.get("ttft_excl_compile_s", r["ttft_s"]) for r in recs],
            95)), 6),
        "first_compile_s_max": round(max(
            (r.get("first_compile_s") or 0.0) for r in recs), 6),
        "compile": plain_m.get("compile", {}),
    }

    # shared-system-prompt fixture: every request opens with the same
    # system prefix, so the prefix cache serves the bulk of prefill for
    # the second and later requests — report TTFT p50/p95 and the hit
    # rate (ISSUE 5: >=50% of 2nd+ prefill tokens from cache)
    shared_prof = traffic_mod.get_profile("shared-system-prompt",
                                          page_size=page, requests=n_req,
                                          new_tokens=max_new)
    sys_len = shared_prof.shared_prefix_tokens
    shared_sample = shared_prof.sample(rs, lcfg.vocab_size)
    sys_prompt = shared_sample.shared_prefix
    shared = shared_sample.prompts
    _log("decode bench: shared-system-prompt fixture (prefix cache)")
    server = ff.serve_generation(slots=4, max_len=max_len, paged=True,
                                 page_size=page)
    try:
        # warm-up OFF the clock: publish the shared blocks and trace
        # every chunk bucket a measured suffix can hit (4..16 uncached
        # tokens -> buckets 8/16/32; the full first prompt covers the
        # larger ones) — same discipline as the plain fixture's bucket
        # warm-up, so the percentiles measure serving latency, not jit
        # tracing
        n_warm = 0
        for wlen in (17, 12, 4):
            warm = np.concatenate([
                sys_prompt,
                rs.randint(0, lcfg.vocab_size, (wlen,)).astype(np.int32)])
            server.generate(warm, max_new_tokens=max_new)
            n_warm += 1
        futs = [server.submit(p, max_new_tokens=max_new) for p in shared]
        for f in futs:
            f.result(timeout=1200)
        sm = server.metrics()
    finally:
        server.stop()
    # every measured request runs against the warmed cache + traced
    # buckets; the warm-up records are excluded
    later = sm["requests"][n_warm:]
    ttfts = [r["ttft_s"] for r in later if r["ttft_s"] is not None]
    hit = sum(r["cached_prefill_tokens"] for r in later)
    computed = sum(r["prefill_tokens"] for r in later)
    hit_rate = hit / (hit + computed) if hit + computed else 0.0
    prefix_metrics = {
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 6),
        "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 6),
        "prefix_cache_hit_rate": round(hit_rate, 4),
        "hit_tokens": int(sm["prefix_cache"]["hit_tokens"]),
        "evictions": int(sm["prefix_cache"]["evictions"]),
        "fixture": f"{sys_len}-token shared system prompt, "
                   f"{len(shared)} requests",
    }

    # ragged work packing A/B (ISSUE 10): a MIXED fixture — long prompts
    # prefilling chunk by chunk while short prompts decode — served with
    # packed per-slot descriptors (ragged_pack=True) and with the legacy
    # fixed-shape rotating-chunk launches (False). Reported per arm:
    # decode tokens/sec, TTFT p95 and the padded-row waste ratio; the
    # acceptance bar is packed waste strictly below legacy at
    # equal-or-better tokens/sec.
    _log("decode bench: ragged packing A/B (mixed prefill/decode)")
    chunk = 3 * page
    mixed_prof = traffic_mod.get_profile("mixed-length", page_size=page,
                                         prefill_chunk=chunk,
                                         requests=n_req,
                                         new_tokens=max_new)
    mixed = mixed_prof.sample(rs, lcfg.vocab_size).prompts
    ragged_ab = {}
    for pack in (True, False):
        server = ff.serve_generation(slots=4, max_len=max_len, paged=True,
                                     page_size=page, prefill_chunk=chunk,
                                     ragged_pack=pack)
        try:
            # warm both arms' launch shapes off the clock
            server.generate(mixed[0][:3], max_new_tokens=2)
            server.generate(mixed[1], max_new_tokens=2)
            n_warm = 2
            m0 = server.metrics()
            t0 = time.perf_counter()
            futs = [server.submit(p, max_new_tokens=max_new)
                    for p in mixed]
            outs = [f.result(timeout=1200) for f in futs]
            dt = time.perf_counter() - t0
            m = server.metrics()
        finally:
            server.stop()
        rows = m["launch_rows"] - m0["launch_rows"]
        pad = m["padded_rows"] - m0["padded_rows"]
        ttfts = [r["ttft_s"] for r in m["requests"][n_warm:]
                 if r["ttft_s"] is not None]
        ragged_ab["packed" if pack else "legacy"] = {
            "decode_tokens_per_sec": round(
                sum(len(o) for o in outs) / dt, 2),
            "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 6),
            "padding_waste_ratio": round(pad / rows if rows else 0.0, 4),
            "launch_rows": int(rows),
            "kernel_variant": m["kernel_variant"],
        }
    ragged_ab["fixture"] = (
        f"{n_req} requests, half short (4..9 tokens), half {chunk}+ "
        f"tokens chunked at prefill_chunk={chunk}")

    # decode megastep A/B (ISSUE 11): the SAME decode-heavy fixture
    # served with the one-tick host loop (megastep_ticks=1) and with
    # 8 ticks fused per dispatch (megastep_ticks=8, the device-resident
    # while_loop). Reported per arm: decode tokens/sec, effective
    # per-tick latency p50/p95 (the histogram divides each megastep's
    # wall time by its tick count, so widths stay comparable) and host
    # roundtrips per decoded token. The acceptance bar is N=8 strictly
    # higher tokens/sec AND strictly fewer roundtrips/token than N=1.
    _log("decode bench: megastep A/B (N=1 vs N=8)")
    mega_prompts = [rs.randint(0, lcfg.vocab_size, (rs.randint(4, 9),))
                    .astype(np.int32) for _ in range(n_req)]
    mega_ab = {}
    for n_ticks in (1, 8):
        server = ff.serve_generation(slots=4, max_len=max_len, paged=True,
                                     page_size=page,
                                     megastep_ticks=n_ticks)
        try:
            # trace both arms' launch shapes off the clock
            server.generate(mega_prompts[0], max_new_tokens=max_new)
            m0 = server.metrics()
            t0 = time.perf_counter()
            futs = [server.submit(p, max_new_tokens=max_new)
                    for p in mega_prompts]
            outs = [f.result(timeout=1200) for f in futs]
            dt = time.perf_counter() - t0
            m = server.metrics()
        finally:
            server.stop()
        rt = m["megastep"]["host_roundtrips"] \
            - m0["megastep"]["host_roundtrips"]
        dtok = m["megastep"]["decode_tokens"] \
            - m0["megastep"]["decode_tokens"]
        th = m["histograms"]["tick_latency_s"]
        mega_ab[f"n{n_ticks}"] = {
            "decode_tokens_per_sec": round(
                sum(len(o) for o in outs) / dt, 2),
            "tick_latency_p50_s": round(float(th["p50"]), 6),
            "tick_latency_p95_s": round(float(th["p95"]), 6),
            "host_roundtrips_per_token": round(rt / dtok, 4) if dtok
            else 0.0,
            "megastep_breaks": dict(m["megastep"]["breaks"]),
        }
    mega_ab["fixture"] = (
        f"{n_req} short prompts (4..8 tokens), {max_new} new tokens "
        f"each, page_size={page}")

    # universal-megastep A/B (ISSUE 20): the SAME mixed prefill-heavy/
    # decode-heavy fixture (the ragged A/B's mixed-length sample: half
    # short, half chunk-spanning prompts) served three ways — the
    # one-tick host loop, the decode-only fused megastep (prefill
    # chunks force one-tick dispatches while in flight), and the
    # universal megastep with overlapped host dispatch (chunks and
    # drafted chains ride the fused while_loop; admission runs while
    # the device computes). Reported per arm: decode tokens/sec, host
    # roundtrips per decoded token, and TTFT p95. The acceptance bar is
    # universal strictly dominating decode-only on BOTH rt/token and
    # tokens/sec on this mixed traffic.
    _log("decode bench: universal megastep A/B "
         "(legacy vs decode-fused vs universal+overlap)")
    fused_ab = {}
    fused_outs = {}
    arms = (("legacy", dict(megastep_ticks=1)),
            ("decode_fused", dict(megastep_ticks=8)),
            ("universal", dict(megastep_ticks=8, megastep_mixed=True,
                               overlap_dispatch=True)))
    for label, kwargs in arms:
        server = ff.serve_generation(slots=4, max_len=max_len, paged=True,
                                     page_size=page, prefill_chunk=chunk,
                                     **kwargs)
        try:
            # catalog-driven warmup: every launch family this arm can
            # dispatch compiles off the clock
            server.warm_launch_shapes()
            m0 = server.metrics()
            t0 = time.perf_counter()
            futs = [server.submit(p, max_new_tokens=max_new)
                    for p in mixed]
            outs = [f.result(timeout=1200) for f in futs]
            dt = time.perf_counter() - t0
            m = server.metrics()
        finally:
            server.stop()
        fused_outs[label] = outs
        rt = m["megastep"]["host_roundtrips"] \
            - m0["megastep"]["host_roundtrips"]
        dtok = m["megastep"]["decode_tokens"] \
            - m0["megastep"]["decode_tokens"]
        ttfts = [r["ttft_s"] for r in m["requests"]
                 if r["ttft_s"] is not None]
        fused_ab[label] = {
            "decode_tokens_per_sec": round(
                sum(len(o) for o in outs) / dt, 2),
            "host_roundtrips_per_token": round(rt / dtok, 4) if dtok
            else 0.0,
            "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 6),
            "host_overlap_ratio": round(
                float(m["megastep"]["host_overlap_ratio"]), 4),
            "megastep_breaks": dict(m["megastep"]["breaks"]),
        }
    fused_ab["greedy_streams_matched"] = sum(
        int(np.array_equal(a, b) and np.array_equal(a, c))
        for a, b, c in zip(fused_outs["legacy"],
                           fused_outs["decode_fused"],
                           fused_outs["universal"]))
    fused_ab["universal_dominates_decode_fused"] = bool(
        fused_ab["universal"]["host_roundtrips_per_token"]
        < fused_ab["decode_fused"]["host_roundtrips_per_token"]
        and fused_ab["universal"]["decode_tokens_per_sec"]
        > fused_ab["decode_fused"]["decode_tokens_per_sec"])
    fused_ab["fixture"] = (
        f"{len(mixed)} mixed-length requests (half short, half "
        f"{chunk}+ tokens), prefill_chunk={chunk}, page_size={page}")

    # searched-vs-default A/B (ISSUE 12): run the serving-strategy
    # search at a small budget on the smoke profile, then serve BOTH the
    # hand default and the searched winner on the plain fixture —
    # simulated objective side by side with realized decode tokens/sec
    # and TTFT p95, so the search's wins are checked against a real
    # server, not just its own tick pricing. Must run before
    # make_token_cyclic below, which rewrites the weights.
    _log("decode bench: searched-vs-default serving strategy A/B")
    from flexflow_tpu.search.servesearch import (
        ServeStrategy,
        search_serve_strategy,
    )

    sres = search_serve_strategy(
        ff, traffic=smoke_prof, budget=120, seed=0, slots=4,
        max_len=max_len, default=ServeStrategy(page_size=page))
    searched_ab = {
        "objective": {
            "default": round(sres.default_objective, 8),
            "searched": round(sres.best_objective, 8),
            "improvement": round(sres.improvement, 4),
        },
        "strategy": sres.best.to_json(),
    }
    for label, strat in (("default", sres.default),
                         ("searched", sres.best)):
        server = ff.serve_generation(slots=4, max_len=max_len,
                                     serve_strategy=strat)
        try:
            # full warm pass off the clock: each strategy compiles its
            # own launch shapes (chunk buckets, megastep loop, packing
            # variant), so serve the whole fixture once untimed — the
            # timed pass then measures serving, not jit tracing
            for f in [server.submit(p, max_new_tokens=max_new)
                      for p in prompts]:
                f.result(timeout=1200)
            n_warm = len(prompts)
            t0 = time.perf_counter()
            futs = [server.submit(p, max_new_tokens=max_new)
                    for p in prompts]
            outs = [f.result(timeout=1200) for f in futs]
            dt = time.perf_counter() - t0
            m = server.metrics()
        finally:
            server.stop()
        ttfts = [r["ttft_s"] for r in m["requests"][n_warm:]
                 if r["ttft_s"] is not None]
        searched_ab[label] = {
            "decode_tokens_per_sec": round(
                sum(len(o) for o in outs) / dt, 2),
            "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 6),
            "describe": strat.describe(),
        }

    # quantized-KV A/B (ISSUE 13): the SAME shared-prefix fixture served
    # from the model-dtype pool and from an int8+scale-sidecar pool,
    # each sized to the SAME HBM budget (a pool two sequences wide at fp
    # bytes — tight enough that capacity binds). The int8 arm buys ~4x
    # the pages, so it admits more concurrent requests and keeps more
    # prefix pages cached; reported per arm: pool pages, concurrent-
    # request capacity, peak concurrency, preemptions, prefix hit rate,
    # decode tokens/sec, and the kv_cache_dtype / kv_quant_error gauges.
    # Greedy outputs are compared stream-for-stream across the arms
    # (token flips are the documented logit-tolerance story, not bugs).
    # Must run before make_token_cyclic below (it rewrites the weights).
    _log("decode bench: quantized KV A/B (fixed HBM budget)")
    from flexflow_tpu.search.cost_model import kv_cache_token_bytes

    pages_per_seq = -(-max_len // page)
    kv_fp_b = kv_cache_token_bytes(ff.graph)
    kv_q_b = kv_cache_token_bytes(ff.graph, kv_dtype="int8",
                                  page_size=page)
    hbm_budget = (2 * pages_per_seq + 1) * page * kv_fp_b
    quant_ab = {
        "hbm_budget_bytes": int(hbm_budget),
        "kv_token_bytes": {"fp": int(kv_fp_b), "int8": int(kv_q_b)},
    }
    arm_outs = {}
    for arm, kv_dt in (("fp", "auto"), ("int8", "int8")):
        kv_b = kv_fp_b if kv_dt == "auto" else kv_q_b
        pool_pages = max(int(hbm_budget // (page * kv_b)),
                         pages_per_seq + 1)
        server = ff.serve_generation(slots=4, max_len=max_len, paged=True,
                                     page_size=page, num_pages=pool_pages,
                                     kv_dtype=kv_dt)
        try:
            # warm the chunk buckets + decode step off the clock
            server.generate(shared[0][:3], max_new_tokens=2)
            server.generate(shared[0], max_new_tokens=2)
            n_warm = 2
            t0 = time.perf_counter()
            futs = [server.submit(p, max_new_tokens=max_new)
                    for p in shared]
            outs = [f.result(timeout=1200) for f in futs]
            dt = time.perf_counter() - t0
            m = server.metrics()
        finally:
            server.stop()
        arm_outs[arm] = outs
        later = m["requests"][n_warm:]
        hit = sum(r["cached_prefill_tokens"] for r in later)
        computed = sum(r["prefill_tokens"] for r in later)
        quant_ab[arm] = {
            "pool_pages": pool_pages,
            "request_capacity": (pool_pages - 1) // pages_per_seq,
            "decode_tokens_per_sec": round(
                sum(len(o) for o in outs) / dt, 2),
            "peak_active": int(m["peak_active"]),
            "preemptions": int(m["preemptions"]),
            "prefix_cache_hit_rate": round(
                hit / (hit + computed) if hit + computed else 0.0, 4),
            "kv_cache_dtype": m["kv_cache_dtype"],
            "kv_quant_error": m["kv_quant_error"],
        }
    quant_ab["capacity_ratio"] = round(
        quant_ab["int8"]["pool_pages"] / quant_ab["fp"]["pool_pages"], 2)
    quant_ab["greedy_streams_matched"] = sum(
        int(np.array_equal(a, b))
        for a, b in zip(arm_outs["fp"], arm_outs["int8"]))
    quant_ab["fixture"] = (
        f"{len(shared)} shared-prefix requests, both pools sized to "
        f"{hbm_budget} KV bytes")

    # production-shaped profiles (search/traffic.py): the ROADMAP's two
    # serving shapes — long-context summarization (prefill-heavy) and
    # agentic many-turn (deep shared prefix, decode-heavy) — served
    # through the same harness, so the bench and the serving-strategy
    # search score the SAME fixtures the search can now also replay
    production = {}
    for prof_name in ("long-context-summarization", "agentic-multiturn"):
        prof = traffic_mod.get_profile(prof_name, page_size=page,
                                       requests=n_req)
        _log(f"decode bench: {prof.name} fixture")
        p_tps, p_toks, pm = run_server(
            prof.sample(rs, lcfg.vocab_size).prompts,
            max_new_tokens=prof.new_tokens)
        p_recs = pm["requests"]
        p_ttfts = [r["ttft_s"] for r in p_recs if r["ttft_s"] is not None]
        p_hit = sum(r["cached_prefill_tokens"] for r in p_recs)
        p_comp = sum(r["prefill_tokens"] for r in p_recs)
        production[prof.name] = {
            "tokens_per_sec": round(p_tps, 2),
            "decode_tokens": p_toks,
            "ttft_p95_s": round(float(np.percentile(p_ttfts, 95)), 6),
            "prefix_cache_hit_rate": round(
                p_hit / (p_hit + p_comp) if p_hit + p_comp else 0.0, 4),
            "fixture": prof.description,
        }

    # repetitive fixture: token-cyclic model (shared with tests/test_spec)
    from flexflow_tpu.spec.fixtures import make_token_cyclic

    make_token_cyclic(ff)
    _log("decode bench: speculative serving on the repetitive fixture")
    spec_tps, _spec_toks, m = run_server(
        prompts, speculate=SpecConfig(width=2, depth=4))
    sm = m["speculative"]

    # traced pass (fftrace): a short re-run with the span recorder + tick
    # ledger on produces the Chrome-trace artifact and a predicted-vs-
    # measured calibration summary. The timed runs above stay untraced so
    # the reported throughput is the no-tracing number.
    from flexflow_tpu import obs
    from flexflow_tpu.obs.calibrate import (
        calibration_report,
        stamp_ledger_meta,
    )

    _log("decode bench: traced pass (fftrace)")
    calibration = None
    rec = obs.enable()
    try:
        # short plain + speculative passes so decode, prefill AND verify
        # tick shapes all land in the calibration ledger
        run_server(prompts[:2])
        run_server(prompts[:max(2, n_req // 4)],
                   speculate=SpecConfig(width=2, depth=4))
    finally:
        obs.disable()
    try:
        stamp_ledger_meta(rec.ledger, ff, fixture="bench_decode")
        report = calibration_report(rec.ledger)
        calibration = {
            "pricing_mode": report["base"].get("pricing_mode"),
            "phases": {k: round(v, 4) for k, v in report["phases"].items()},
            "shapes": len(report["shapes"]),
        }
    except Exception as e:
        _log(f"calibration report unavailable: {type(e).__name__}: {e}")
    if not smoke:
        # same green-artifact discipline as bench_decode_last_green.json:
        # smoke runs never overwrite the persisted trace
        try:
            os.makedirs(os.path.dirname(_DECODE_TRACE_PATH), exist_ok=True)
            rec.export_chrome_trace(_DECODE_TRACE_PATH)
            _log(f"trace artifact: {_DECODE_TRACE_PATH}")
        except OSError as e:
            _log(f"could not persist trace artifact: {e}")

    return {
        "metric": "paged_decode_tokens_per_sec",
        "value": round(tps, 2),
        "unit": "tokens/s",
        "requests": n_req,
        "decode_tokens": toks,
        "tick_latency_p50_s": round(float(tick_h["p50"]), 6),
        "tick_latency_p95_s": round(float(tick_h["p95"]), 6),
        "ttft_compile_split": ttft_split,
        "calibration": calibration,
        "prefix_cache": prefix_metrics,
        "ragged_packing": ragged_ab,
        "megastep": mega_ab,
        "fused_megastep": fused_ab,
        "servesearch": searched_ab,
        "quantized_kv": quant_ab,
        "profiles": production,
        "speculative": {
            "tokens_per_sec": round(spec_tps, 2),
            "acceptance_rate": round(sm["acceptance_rate"], 4),
            "accepted_tokens_per_step": round(
                sm["accepted_tokens_per_step"], 4),
            "fixture": "token-cyclic model (repetitive greedy stream)",
        },
    }


def _configure_child_platform() -> None:
    plat = os.environ.get("FLEXFLOW_BENCH_PLATFORM")
    if plat:
        # must happen before the first backend touch: site customizations
        # can force-register a TPU plugin that ignores JAX_PLATFORMS env
        import jax

        jax.config.update("jax_platforms", plat)


def _device_facts() -> dict:
    import jax

    ds = jax.devices()
    return {"n_devices": len(ds), "device_kind": ds[0].device_kind}


def _run_side(side: str) -> dict:
    _configure_child_platform()
    rs = np.random.RandomState(0)
    vocab = _llama_cfg().vocab_size
    x = rs.randint(0, vocab, (BATCH, SEQ)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    tps = bench_framework(x, y) if side == "framework" else bench_naive(x, y)
    return {"tokens_per_sec": tps, **_device_facts()}


def _probe_main() -> None:
    """Child body for --probe: the cheapest possible backend-init check."""
    _configure_child_platform()
    print(json.dumps(_device_facts()))


# ---- parent-side orchestration (never touches jax) -------------------------

_BUDGET = float(os.environ.get("FLEXFLOW_BENCH_BUDGET", "3000"))

# Round-long capture resilience (the tunnel has eaten two rounds' captures:
# r03 timeout, r04 init hang): every green result is persisted here, and
# when the backend is down at capture time the LAST GREEN result is emitted
# instead of a 0.0 diagnostic — clearly labeled with its capture time, so a
# transient tunnel outage can no longer erase a real measured number.
_GREEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "docs", "bench_last_green.json")
# the serving-side (--decode) metric persists its own last-green artifact
# under the SAME 7-day staleness guard as the train metric
_DECODE_GREEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "docs", "bench_decode_last_green.json")
# Chrome-trace artifact from the decode bench's traced pass (Perfetto-
# loadable); written only on non-smoke runs, alongside the green JSON
_DECODE_TRACE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "docs", "bench_decode_trace.json.gz")


def _persist_green(res: dict, path: "str | None" = None) -> None:
    if path is None:
        path = _GREEN_PATH  # resolved at call time (tests monkeypatch it)
    if os.environ.get("FLEXFLOW_BENCH_SMOKE") or res.get("value", 0) <= 0:
        return
    try:
        out = dict(res)
        out["_captured_unix"] = time.time()
        out["_captured"] = time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                         time.gmtime())
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    except OSError as e:
        _log(f"could not persist green result: {e}")


_GREEN_MAX_AGE_S = float(os.environ.get("FLEXFLOW_BENCH_GREEN_MAX_AGE",
                                        str(7 * 24 * 3600)))


def _emit_last_green_or(diagnostic: dict, exit_code: int,
                        want: "str | tuple | None" = None,
                        path: "str | None" = None) -> None:
    """Backend unreachable: prefer the persisted green artifact (labeled as
    cached) over a 0.0 diagnostic; exit 0 on cache hit so drivers record
    the parsed line. `want` (a config name like "1b", or a tuple of
    acceptable configs for the combined-gate fallbacks) refuses a cached
    result measured at a DIFFERENT config — a 1b request must never be
    answered with a 200m number. Artifacts older than _GREEN_MAX_AGE_S
    (default 7 days) are refused too: a week-old number presented as
    current would mask a real regression for an entire round."""
    if path is None:
        path = _GREEN_PATH  # resolved at call time (tests monkeypatch it)
    try:
        with open(path) as f:
            res = json.load(f)
        if want is not None:
            wanted = (want,) if isinstance(want, str) else tuple(want)
            if not any(f"_{w}_" in res.get("metric", "") for w in wanted):
                res = {}
        age = time.time() - res.get("_captured_unix", 0)
        if res and age > _GREEN_MAX_AGE_S:
            _log(f"cached green result is {age / 86400:.1f} days old "
                 "(> max age); refusing it")
            res = {}
        if res.get("value", 0) > 0:
            res["cached"] = True
            res["cache_note"] = (
                "backend unreachable at capture time; this is the most "
                f"recent green run, captured {res.get('_captured', '?')}"
            )
            _log("backend down: emitting persisted last-green result "
                 f"({res.get('_captured', '?')})")
            print(json.dumps(res))
            return
    except (OSError, ValueError):
        pass
    print(json.dumps(diagnostic))
    sys.exit(exit_code)


def _remaining() -> float:
    return _BUDGET - (time.time() - _T0)


def _spawn(args: list, timeout: float, extra_env: dict | None = None):
    """Run a child bench process; returns (rc, last_stdout_line_or_None).
    rc -9 means we killed it at the deadline (backend hang)."""
    import subprocess

    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, __file__] + args,
            stdout=subprocess.PIPE, stderr=None, text=True,
            timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return -9, None
    lines = [ln for ln in (proc.stdout or "").strip().splitlines() if ln]
    return proc.returncode, (lines[-1] if lines else None)


def _probe_backend(retries: int = 4, per_timeout: float = 150.0):
    """Bounded, retried backend-init probe. The axon/tunnel backend can hang
    jax.devices() forever (r03 failure mode); each attempt gets its own
    deadline and a hung child is killed and retried — the tunnel often
    recovers between attempts."""
    for i in range(retries):
        if _remaining() < 30:
            break
        t = min(per_timeout, max(30.0, _remaining() - 10))
        _log(f"backend probe attempt {i + 1}/{retries} (deadline {t:.0f}s)")
        rc, line = _spawn(["--probe"], timeout=t)
        if rc == 0 and line:
            try:
                facts = json.loads(line)
                _log(f"backend up: {facts['n_devices']}x {facts['device_kind']}")
                return facts
            except (ValueError, KeyError):
                pass
        _log(f"probe failed (rc={rc}); backend hang or init error")
        time.sleep(5)
    return None


def _spawn_side(side: str, config: str, timeout: float, attempts: int = 2):
    """Each side runs in its own process so HBM is fully released between
    the framework and baseline runs (params + Adam state + compiled
    executables of one side would otherwise crowd out the other)."""
    for i in range(attempts):
        if _remaining() < 60:
            _log(f"side {side}/{config}: out of budget, giving up")
            return None
        t = min(timeout, max(60.0, _remaining() - 30))
        _log(f"side {side}/{config} attempt {i + 1}/{attempts} "
             f"(deadline {t:.0f}s, budget {_remaining():.0f}s)")
        rc, line = _spawn(["--side", side], timeout=t,
                          extra_env={"FLEXFLOW_BENCH_CONFIG": config})
        if rc == 0 and line:
            try:
                return json.loads(line)
            except ValueError:
                pass
        _log(f"side {side}/{config} failed (rc={rc})")
        time.sleep(5)
    return None


def _run_config(config: str, side_timeout: float):
    """Run both sides at one config; returns the result dict or None."""
    fw = _spawn_side("framework", config, side_timeout)
    if fw is None:
        return None
    nv = _spawn_side("naive", config, side_timeout)
    if nv is None:
        return None
    cfg = _llama_cfg(profile=config)
    peak = _peak_flops(fw["device_kind"], fw["n_devices"])
    mfu = fw["tokens_per_sec"] * _flops_per_token(cfg, SEQ) / peak
    name = f"llama_{config}_train_tokens_per_sec"
    return {
        "metric": name,
        "value": round(fw["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(fw["tokens_per_sec"] / nv["tokens_per_sec"], 4),
        "mfu": round(mfu, 4),
        "baseline_tokens_per_sec": round(nv["tokens_per_sec"], 1),
    }


def main():
    global BATCH, SEQ, WARMUP, ITERS
    if "--smoke" in sys.argv:
        # tiny plumbing check (CPU-capable): exercises both subprocess
        # sides end to end without the real model size
        sys.argv.remove("--smoke")
        os.environ["FLEXFLOW_BENCH_SMOKE"] = "1"
    if "--platform" in sys.argv:
        i = sys.argv.index("--platform")
        if i + 1 >= len(sys.argv):
            sys.exit("usage: bench.py [--smoke] [--platform cpu|tpu] "
                     "[--config 1b|200m]")
        os.environ["FLEXFLOW_BENCH_PLATFORM"] = sys.argv[i + 1]
        del sys.argv[i:i + 2]
    if "--decode" in sys.argv:
        # serving-side bench: in-process, no subprocess orchestration (it
        # has no naive-baseline side and is CPU-capable under --smoke).
        # Green runs persist docs/bench_decode_last_green.json; when the
        # backend is down the cached artifact answers instead of a 0.0
        # diagnostic, under the same 7-day staleness guard as the train
        # metric.
        sys.argv.remove("--decode")
        _configure_child_platform()
        try:
            res = bench_decode()
        except Exception as e:  # backend init hang/crash: serve the cache
            _log(f"decode bench failed: {type(e).__name__}: {e}")
            _emit_last_green_or({
                "metric": "paged_decode_tokens_per_sec",
                "value": 0.0, "unit": "tokens/s",
                "error": f"{type(e).__name__}: {e}",
            }, exit_code=5, path=_DECODE_GREEN_PATH)
            return
        _persist_green(res, path=_DECODE_GREEN_PATH)
        print(json.dumps(res))
        return
    only_config = None
    if "--config" in sys.argv:
        i = sys.argv.index("--config")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1] not in ("1b", "200m"):
            sys.exit("usage: bench.py [--smoke] [--platform cpu|tpu] "
                     "[--config 1b|200m]")
        only_config = sys.argv[i + 1]
        os.environ["FLEXFLOW_BENCH_CONFIG"] = only_config
        del sys.argv[i:i + 2]
    if only_config is None and os.environ.get("FLEXFLOW_BENCH_CONFIG"):
        # env-only selection restricts the run the same way --config does
        only_config = os.environ["FLEXFLOW_BENCH_CONFIG"]
    _bench_profile()  # validate FLEXFLOW_BENCH_CONFIG before spawning sides
    if os.environ.get("FLEXFLOW_BENCH_SMOKE"):
        BATCH, SEQ, WARMUP, ITERS = 2, 128, 1, 2
    if len(sys.argv) > 2 and sys.argv[1] == "--side":
        print(json.dumps(_run_side(sys.argv[2])))
        return
    if "--probe" in sys.argv:
        _probe_main()
        return

    facts = _probe_backend()
    if facts is None:
        # last-green artifact if one exists, else a diagnostic JSON line
        _emit_last_green_or({
            "metric": "llama_train_tokens_per_sec",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "error": "backend init hang: jax.devices() never returned "
                     "within any probe deadline (tunnel down?)",
        }, exit_code=3, want=("1b", "200m"))
        return

    if os.environ.get("FLEXFLOW_BENCH_SMOKE"):
        res = _run_config("smoke", side_timeout=420)
        if res is None:
            print(json.dumps({
                "metric": "llama_smoke_train_tokens_per_sec",
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "error": "smoke: at least one side failed all attempts",
            }))
            sys.exit(4)
        print(json.dumps(res))
        return

    if only_config:
        res = _run_config(only_config,
                          side_timeout=600 if only_config == "1b" else 540)
        if res is None:
            _emit_last_green_or({
                "metric": f"llama_{only_config}_train_tokens_per_sec",
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "error": "both attempts of at least one side failed",
            }, exit_code=4, want=only_config)
            return
        _persist_green(res)
        print(json.dumps(res))
        return

    # Default gate path: 200m first (proven config — regression guard),
    # print its line IMMEDIATELY, then attempt 1b if budget remains; a 1b
    # success prints a superseding final line carrying both results.
    res200 = _run_config("200m", side_timeout=540)
    if res200 is not None:
        _persist_green(res200)
        print(json.dumps(res200), flush=True)
    else:
        _log("200m failed on both sides' retries")
    if _remaining() < 1100:
        _log(f"skipping 1b: only {_remaining():.0f}s of budget left")
        if res200 is None:
            _emit_last_green_or({
                "metric": "llama_train_tokens_per_sec",
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "error": "200m failed and no budget for 1b",
            }, exit_code=4, want=("1b", "200m"))
        return
    res1b = _run_config("1b", side_timeout=600)
    if res1b is None:
        _log("1b did not complete; 200m line above stands")
        if res200 is None:
            _emit_last_green_or({
                "metric": "llama_train_tokens_per_sec",
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "error": "both 200m and 1b failed",
            }, exit_code=4, want=("1b", "200m"))
        return
    if res200 is not None:
        res1b["config_200m"] = {k: res200[k] for k in
                                ("value", "vs_baseline", "mfu",
                                 "baseline_tokens_per_sec")}
    _persist_green(res1b)
    print(json.dumps(res1b))


if __name__ == "__main__":
    main()
