"""AlexNet/CIFAR-10 — the reference bootcamp demo
(bootcamp_demo/ff_alexnet_cifar10.py analog; BASELINE config 1) on
synthetic CIFAR-shaped data.

Run:  python examples/python/alexnet_cifar10.py -b 64 -e 2
"""

import numpy as np

from flexflow_tpu import (
    FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
)
from flexflow_tpu.models.alexnet import build_alexnet_cifar10


def synthetic_cifar(n=2048, seed=0):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 10, n).astype(np.int32)
    x = rs.randn(n, 3, 32, 32).astype(np.float32) + y[:, None, None, None] * 0.05
    return x, y


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    ff = FFModel(cfg)
    build_alexnet_cifar10(ff, batch_size=cfg.batch_size)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    x, y = synthetic_cifar()
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
