"""BERT-base with attribute (attention-head) parallelism — BASELINE
config 3 (reference SOAP attribute-parallel dimension, model.cc:3617).

Run:  python examples/python/bert_attribute_parallel.py -b 8 -e 1 \\
          --mesh data=2,model=4
"""

import numpy as np

from flexflow_tpu import (
    AdamOptimizer, FFConfig, FFModel, LossType, MetricsType,
)
from flexflow_tpu.models.bert import (
    BertConfig, bert_attribute_parallel_strategy, build_bert,
)


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    bcfg = BertConfig(vocab_size=1024, hidden=128, layers=2, heads=8,
                      intermediate=256, max_seq=128)
    ff = FFModel(cfg)
    build_bert(ff, bcfg, batch_size=cfg.batch_size, seq_len=128)
    strategy = None
    if cfg.mesh_shape and cfg.mesh_shape.get("model", 1) > 1:
        strategy = bert_attribute_parallel_strategy(bcfg)
    ff.compile(
        optimizer=AdamOptimizer(lr=1e-4),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
        strategy=strategy,
    )
    rs = np.random.RandomState(0)
    n = cfg.batch_size * 4
    x = rs.randint(0, bcfg.vocab_size, (n, 128)).astype(np.int32)
    y = rs.randint(0, bcfg.num_classes, n).astype(np.int32)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
