"""CANDLE-UNO drug-response regression on synthetic features (reference
examples/cpp/candle_uno): three encoder towers -> dense head -> growth.

Run:  python examples/python/candle_uno.py -b 16 -e 2
"""

import numpy as np

from flexflow_tpu import (
    FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
)
from flexflow_tpu.models.candle_uno import build_candle_uno


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    ff = FFModel(cfg)
    dims = {"gene": 64, "drug1": 48, "drug2": 48}  # CPU-friendly sizes
    build_candle_uno(ff, feature_dims=dims, tower_dims=(64, 32),
                     head_dims=(64, 32))
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    rs = np.random.RandomState(0)
    n = max(cfg.batch_size * 4, 32)
    xs = [rs.randn(n, 1).astype(np.float32)]
    xs += [rs.randn(n, d).astype(np.float32) for d in dims.values()]
    y = rs.rand(n, 1).astype(np.float32)
    ff.fit(xs, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
