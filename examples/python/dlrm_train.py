"""DLRM CTR training on synthetic clicks (reference examples/cpp/DLRM):
sparse embedding bags + bottom/top MLPs, trained with MSE like the
reference example, fed through multiple input tensors.

Run:  python examples/python/dlrm_train.py -b 32 -e 2
"""

import numpy as np

from flexflow_tpu import (
    FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
)
from flexflow_tpu.models.dlrm import build_dlrm

NUM_SPARSE, VOCAB, EMBED, DENSE = 4, 1000, 16, 8


def synthetic_clicks(n=1024, seed=0):
    rs = np.random.RandomState(seed)
    dense = rs.randn(n, DENSE).astype(np.float32)
    sparse = [rs.randint(0, VOCAB, (n, 1)).astype(np.int32)
              for _ in range(NUM_SPARSE)]
    # clicks correlate with the dense features through a fixed projection
    w = rs.randn(DENSE, 1)
    y = (1.0 / (1.0 + np.exp(-dense @ w))).astype(np.float32)
    return dense, sparse, y


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    ff = FFModel(cfg)
    build_dlrm(ff, num_sparse=NUM_SPARSE, vocab=VOCAB, embed_dim=EMBED,
               dense_dim=DENSE, bot_mlp=(64, 32, EMBED), top_mlp=(64, 1),
               batch_size=cfg.batch_size)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    dense, sparse, y = synthetic_clicks()
    ff.fit([dense] + sparse, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
