"""Fine-tune a HuggingFace Llama checkpoint through the HF importer —
the reference's examples/python/pytorch/mt5 flow (fine-tune a pretrained
HF model via the torch frontend), TPU-native: the checkpoint is mapped
onto the framework's own graph (frontends/hf.py), so training runs the
fused/flash lowerings and any searched parallel strategy.

Run (tiny local model, no network):
    python examples/python/hf_finetune.py -b 4 -e 1
Run (a real downloaded checkpoint directory):
    python examples/python/hf_finetune.py --model /path/to/llama-ckpt -b 4
"""

import numpy as np

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, LossType
from flexflow_tpu.frontends.hf import copy_hf_weights, import_hf_causal_lm

SEQ = 64


def load_hf_model(path=None):
    from transformers import LlamaConfig, LlamaForCausalLM

    if path:
        # Llama-family or GPT-2 checkpoints (import_hf_causal_lm dispatches
        # on config.model_type)
        from transformers import AutoModelForCausalLM

        return AutoModelForCausalLM.from_pretrained(path)
    # no checkpoint given: a tiny locally-constructed Llama (same class a
    # pretrained checkpoint loads into; CI-safe, no network)
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=SEQ,
                      tie_word_embeddings=False)
    import torch

    torch.manual_seed(0)
    return LlamaForCausalLM(cfg)


def main(argv=None):
    import sys

    args = list(argv if argv is not None else sys.argv[1:])
    path = None
    if "--model" in args:
        i = args.index("--model")
        if i + 1 >= len(args):
            raise ValueError("flag --model requires a checkpoint path")
        path = args[i + 1]
        del args[i:i + 2]
    cfg = FFConfig.from_args(args)
    hf = load_hf_model(path)

    ff = FFModel(cfg)
    import_hf_causal_lm(hf, ff, batch_size=cfg.batch_size, seq_len=SEQ)
    ff.compile(optimizer=AdamOptimizer(lr=1e-3),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    n = copy_hf_weights(hf, ff)
    print(f"imported {n} weight tensors from "
          f"{path or 'a locally-built tiny Llama'}")

    # synthetic next-token fine-tuning data (cycling alphabet)
    rs = np.random.RandomState(0)
    nrows = cfg.batch_size * 8
    starts = rs.randint(0, 16, nrows)
    x = ((starts[:, None] + np.arange(SEQ)[None]) % 16).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    m = ff.fit(x, y, epochs=cfg.epochs, verbose=True)
    print(f"fine-tuned {m.train_all} sequences")


if __name__ == "__main__":
    main()
