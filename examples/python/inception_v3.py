"""InceptionV3 on synthetic images (reference examples/cpp/InceptionV3):
multi-branch concat blocks — the Unity search's substitution playground.

Run:  python examples/python/inception_v3.py -b 4 -e 1 [--budget 8]
"""

import numpy as np

from flexflow_tpu import (
    FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
)
from flexflow_tpu.models.inception import build_inception_v3


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    ff = FFModel(cfg)
    size, classes = 75, 10  # small images keep the example CPU-friendly
    build_inception_v3(ff, image_size=size, classes=classes)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    rs = np.random.RandomState(0)
    n = max(cfg.batch_size * 2, 8)
    x = rs.randn(n, 3, size, size).astype(np.float32)
    y = rs.randint(0, classes, n).astype(np.int32)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
