"""Llama decoder training with a hybrid TP+DP strategy — BASELINE config 4
(the reference's examples/cpp/Transformer analog, scaled by flags).

Run (single chip):   python examples/python/llama_train.py -b 8 -e 1
Run (8-dev search):  python examples/python/llama_train.py --budget 10 --devices 8
Pipeline parallel:   ... --pipeline --mesh data=2,pipe=4
The search (--budget) discovers the strategy; without it the hand TP (or
PP, with --pipeline) strategy is used when the mesh has the matching axis.
"""

import numpy as np

from flexflow_tpu import (
    AdamOptimizer, FFConfig, FFModel, LossType, MetricsType,
)
from flexflow_tpu.models.llama import (
    LlamaConfig, build_llama, llama_pp_strategy, llama_tp_strategy,
)


def main(argv=None):
    import sys

    args = list(argv if argv is not None else sys.argv[1:])
    use_pipeline = "--pipeline" in args
    if use_pipeline:
        args.remove("--pipeline")
    cfg = FFConfig.from_args(args)
    lcfg = LlamaConfig.tiny(vocab=2048)
    if use_pipeline:
        import dataclasses

        # tiny but 4 layers, so a pipe=4 mesh runs a real GPipe schedule
        lcfg = dataclasses.replace(LlamaConfig.tiny(vocab=2048), layers=4)
    seq = 256
    ff = FFModel(cfg)
    build_llama(ff, lcfg, batch_size=cfg.batch_size, seq_len=seq,
                use_pipeline=use_pipeline)
    strategy = None
    if cfg.search_budget == 0 and cfg.mesh_shape:
        if use_pipeline and cfg.mesh_shape.get("pipe", 1) > 1:
            strategy = llama_pp_strategy(lcfg)
        elif cfg.mesh_shape.get("model", 1) > 1:
            strategy = llama_tp_strategy(lcfg)
    ff.compile(
        optimizer=AdamOptimizer(lr=1e-3),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
        strategy=strategy,
    )
    rs = np.random.RandomState(0)
    n = cfg.batch_size * 8
    x = rs.randint(0, lcfg.vocab_size, (n, seq)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
