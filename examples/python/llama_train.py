"""Llama decoder training with a hybrid TP+DP strategy — BASELINE config 4
(the reference's examples/cpp/Transformer analog, scaled by flags).

Run (single chip):   python examples/python/llama_train.py -b 8 -e 1
Run (8-dev search):  python examples/python/llama_train.py --budget 10 --devices 8
The search (--budget) discovers the strategy; without it the hand TP
strategy is used when the mesh has a model axis.
"""

import numpy as np

from flexflow_tpu import (
    AdamOptimizer, FFConfig, FFModel, LossType, MetricsType,
)
from flexflow_tpu.models.llama import (
    LlamaConfig, build_llama, llama_tp_strategy,
)


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    lcfg = LlamaConfig.tiny(vocab=2048)
    seq = 256
    ff = FFModel(cfg)
    build_llama(ff, lcfg, batch_size=cfg.batch_size, seq_len=seq)
    strategy = None
    if cfg.search_budget == 0 and cfg.mesh_shape and cfg.mesh_shape.get("model", 1) > 1:
        strategy = llama_tp_strategy(lcfg)
    ff.compile(
        optimizer=AdamOptimizer(lr=1e-3),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
        strategy=strategy,
    )
    rs = np.random.RandomState(0)
    n = cfg.batch_size * 8
    x = rs.randint(0, lcfg.vocab_size, (n, seq)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
