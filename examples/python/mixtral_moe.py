"""Mixtral-style MoE training with expert parallelism — BASELINE config 5
(the reference's examples/cpp/mixture_of_experts analog).

Run:  python examples/python/mixtral_moe.py -b 8 -e 1
"""

import numpy as np

from flexflow_tpu import (
    AdamOptimizer, FFConfig, FFModel, LossType,
)
from flexflow_tpu.models.mixtral import (
    MixtralConfig, build_mixtral, mixtral_ep_strategy,
)


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    mcfg = MixtralConfig.tiny()
    ff = FFModel(cfg)
    build_mixtral(ff, mcfg, batch_size=cfg.batch_size, seq_len=128)
    strategy = None
    if cfg.mesh_shape and cfg.mesh_shape.get("expert", 1) > 1:
        strategy = mixtral_ep_strategy(mcfg)
    ff.compile(
        optimizer=AdamOptimizer(lr=1e-3),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        strategy=strategy,
    )
    rs = np.random.RandomState(0)
    n = cfg.batch_size * 4
    x = rs.randint(0, mcfg.vocab_size, (n, 128)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    ff.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
