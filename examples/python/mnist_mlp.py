"""MLP classifier — the reference's examples/python/native/mnist_mlp.py
analog, on synthetic MNIST-shaped data (zero-egress image: no downloads).

Run:  python examples/python/mnist_mlp.py -b 64 -e 3 [--devices N]
"""

import numpy as np

from flexflow_tpu import (
    FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
)
from flexflow_tpu.models.mlp import build_mlp


def synthetic_mnist(n=4096, seed=0):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 10, n)
    protos = rs.randn(10, 784).astype(np.float32)
    x = protos[y] + 0.3 * rs.randn(n, 784).astype(np.float32)
    return x, y.astype(np.int32)


def main(argv=None):
    import sys

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    ff = FFModel(cfg)
    build_mlp(ff, 784, [512, 512], 10, batch_size=cfg.batch_size)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    x, y = synthetic_mnist()
    ff.fit(x, y, epochs=cfg.epochs)
    ff.eval(x[:1024], y[:1024])


if __name__ == "__main__":
    main()
