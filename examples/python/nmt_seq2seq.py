"""Seq2seq NMT training — the reference's legacy standalone NMT app analog
(nmt/nmt.cc: stacked-LSTM encoder/decoder + vocab projection), on a
synthetic copy-with-offset translation task (zero-egress image: no
downloads).

Run:  python examples/python/nmt_seq2seq.py -b 32 -e 3 [--devices N]
"""

import numpy as np

from flexflow_tpu import (
    AdamOptimizer, FFConfig, FFModel, LossType, MetricsType,
)
from flexflow_tpu.models.nmt import NMTConfig, build_nmt, nmt_dp_strategy

SRC_LEN, TGT_LEN = 16, 16


def synthetic_pairs(cfg: NMTConfig, n=2048, seed=0):
    """"Translation" = map each source token to (token*3+1) mod tgt_vocab —
    learnable by the encoder-decoder, impossible for a unigram prior."""
    rs = np.random.RandomState(seed)
    src = rs.randint(1, cfg.src_vocab, (n, SRC_LEN)).astype(np.int32)
    tgt = ((src[:, :TGT_LEN] * 3 + 1) % cfg.tgt_vocab).astype(np.int32)
    # teacher forcing: decoder input is the shifted target
    dec_in = np.concatenate([np.zeros((n, 1), np.int32), tgt[:, :-1]], axis=1)
    return src, dec_in, tgt


def main(argv=None):
    import sys

    ffcfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    cfg = NMTConfig(src_vocab=512, tgt_vocab=512, embed_dim=128, hidden=192,
                    layers=2)
    ff = FFModel(ffcfg)
    build_nmt(ff, cfg, src_len=SRC_LEN, tgt_len=TGT_LEN)
    strategy = nmt_dp_strategy(cfg) if ffcfg.mesh_shape else None
    ff.compile(
        optimizer=AdamOptimizer(lr=3e-3),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
        strategy=strategy,
    )
    src, dec_in, tgt = synthetic_pairs(cfg)
    ff.fit([src, dec_in], tgt, epochs=ffcfg.epochs)
    ff.eval([src[:512], dec_in[:512]], tgt[:512])


if __name__ == "__main__":
    main()
