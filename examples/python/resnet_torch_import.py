"""ResNet imported from torchvision-style PyTorch code via the torch.fx
frontend — BASELINE config 2 (reference examples/python/pytorch flow:
torch module -> fx trace -> FFModel).

Run:  python examples/python/resnet_torch_import.py -b 8 -e 1
"""

import numpy as np

from flexflow_tpu import (
    FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
)


def make_torch_resnet_block():
    import torch.nn as nn

    # small residual CNN standing in for full ResNet-50 (same op mix;
    # torchvision isn't baked into the image)
    class Block(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 16, 3, padding=1)
            self.bn1 = nn.BatchNorm2d(16)
            self.relu = nn.ReLU()
            self.conv2 = nn.Conv2d(16, 16, 3, padding=1)
            self.bn2 = nn.BatchNorm2d(16)
            self.pool = nn.AdaptiveAvgPool2d(1) if hasattr(nn, "AdaptiveAvgPool2d") else nn.AvgPool2d(32)
            self.fc = nn.Linear(16, 10)

        def forward(self, x):
            h = self.relu(self.bn1(self.conv1(x)))
            h = self.bn2(self.conv2(h))
            h = self.relu(h)
            h = nn.functional.avg_pool2d(h, 32)  # static: fx-traceable
            h = h.flatten(1)
            return self.fc(h)

    return Block()


def main(argv=None):
    import sys

    from flexflow_tpu.frontends.torch_fx import PyTorchModel

    cfg = FFConfig.from_args(argv if argv is not None else sys.argv[1:])
    ff = FFModel(cfg)
    x = ff.create_tensor((cfg.batch_size, 3, 32, 32), name="input")
    module = make_torch_resnet_block()
    out = PyTorchModel(module).torch_to_ff(ff, [x])[0]
    ff.softmax(out, name="softmax")
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    rs = np.random.RandomState(0)
    n = cfg.batch_size * 4
    xs = rs.randn(n, 3, 32, 32).astype(np.float32)
    ys = rs.randint(0, 10, n).astype(np.int32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
