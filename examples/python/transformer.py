"""Transformer encoder training — the reference examples/cpp/Transformer
analog (attention encoder stack + regression head, MSE on synthetic
random data, transformer.cc:138-188). --enc-dec switches to the
encoder-decoder variant with cross-attention.

Run:  python examples/python/transformer.py -b 8 -e 2 [--enc-dec]
"""

import numpy as np

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, LossType, MetricsType
from flexflow_tpu.models.transformer import (
    TransformerConfig,
    build_transformer_encoder,
    build_transformer_encoder_decoder,
)

SEQ = 32


def main(argv=None):
    import sys

    args = list(argv if argv is not None else sys.argv[1:])
    enc_dec = "--enc-dec" in args
    if enc_dec:
        args.remove("--enc-dec")
    ffcfg = FFConfig.from_args(args)
    cfg = TransformerConfig(dim=64, heads=8, hidden=256, layers=4)
    ff = FFModel(ffcfg)
    if enc_dec:
        build_transformer_encoder_decoder(ff, cfg, src_len=SEQ,
                                          tgt_len=SEQ // 2)
    else:
        build_transformer_encoder(ff, cfg, seq_len=SEQ)
    ff.compile(optimizer=AdamOptimizer(lr=1e-3),
               loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[MetricsType.MEAN_SQUARED_ERROR])
    rs = np.random.RandomState(0)
    n = 512
    if enc_dec:
        src = rs.randn(n, SEQ, cfg.dim).astype(np.float32)
        tgt = rs.randn(n, SEQ // 2, cfg.dim).astype(np.float32)
        y = tgt.mean(-1, keepdims=True).astype(np.float32)
        ff.fit([src, tgt], y, epochs=ffcfg.epochs)
    else:
        x = rs.randn(n, SEQ, cfg.dim).astype(np.float32)
        y = x.mean(-1, keepdims=True).astype(np.float32)
        ff.fit(x, y, epochs=ffcfg.epochs)


if __name__ == "__main__":
    main()
