"""flexflow_tpu — a TPU-native distributed DNN training framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of FlexFlow
(reference: williamberman/FlexFlow): a frontend layer graph is compiled into a
Parallel Computation Graph (PCG) over sharded tensors, a strategy search
(MCMC + Unity-style graph DP + substitutions) picks per-op shardings costed by
a TPU machine model, and the winning PCG is lowered to ONE jitted XLA SPMD
program per training step over a `jax.sharding.Mesh`.

Reference architecture map (see SURVEY.md):
  - Legion tasks/regions/mapper  -> single jitted step + Mesh + NamedSharding
  - ParallelTensor dim degrees   -> PartitionSpec over named mesh axes
  - parallel ops (Repartition/Combine/Replicate/Reduction) -> explicit PCG
    nodes lowered to sharding constraints / collectives
  - NCCL allreduce in optimizer  -> psum over ICI inside the step function
  - cuDNN/cuBLAS kernels         -> XLA HLO + Pallas kernels for the hot ops
"""

from flexflow_tpu.ffconst import (
    ActiMode,
    AggrMode,
    DataType,
    LossType,
    MetricsType,
    OpType,
    ParamSyncType,
    PoolType,
)
from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel
from flexflow_tpu.runtime.optimizer import SGDOptimizer, AdamOptimizer
from flexflow_tpu.runtime.initializer import (
    GlorotUniformInitializer,
    ZeroInitializer,
    ConstantInitializer,
    UniformInitializer,
    NormInitializer,
)

__version__ = "0.1.0"

__all__ = [
    "FFModel",
    "FFConfig",
    "DataType",
    "OpType",
    "ActiMode",
    "AggrMode",
    "PoolType",
    "LossType",
    "MetricsType",
    "ParamSyncType",
    "SGDOptimizer",
    "AdamOptimizer",
    "GlorotUniformInitializer",
    "ZeroInitializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormInitializer",
]

# set by the CLI driver (`python -m flexflow_tpu SCRIPT [flags]`)
_driver_config = None


def get_driver_config():
    """The FFConfig parsed from the CLI by the `python -m flexflow_tpu`
    driver; FFConfig() defaults when not running under the driver."""
    return _driver_config or FFConfig()


__all__.append("get_driver_config")
