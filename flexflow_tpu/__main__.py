"""CLI driver — `python -m flexflow_tpu script.py [flags]`.

Reference analog: the `flexflow_python` interpreter (python/main.cc +
flexflow_top.py) which started Legion and ran the user script as the
top-level task. TPU-native there is no runtime to boot: the driver parses
reference-style flags into the default FFConfig, exposes it via
`flexflow_tpu.get_driver_config()`, and execs the script.
"""

from __future__ import annotations

import runpy
import sys

from flexflow_tpu.config import FFConfig

def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # --platform cpu [--cpu-devices N]: configure the backend BEFORE any
    # jax backend touch (env vars alone can be overridden by site plugins)
    if "--platform" in argv:
        i = argv.index("--platform")
        platform = argv[i + 1]
        del argv[i:i + 2]
        import jax

        jax.config.update("jax_platforms", platform)
        if "--cpu-devices" in argv:
            i = argv.index("--cpu-devices")
            from flexflow_tpu.parallel.compat import ensure_cpu_devices

            ensure_cpu_devices(int(argv[i + 1]))
            del argv[i:i + 2]
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("usage: python -m flexflow_tpu [--platform cpu "
              "[--cpu-devices N]] SCRIPT [flags]\n"
              "flags: -b/--batch-size -e/--epochs --devices --mesh "
              "data=2,model=4 --budget --only-data-parallel "
              "--import-strategy F --export-strategy F --profiling ...")
        return 0
    script, rest = argv[0], argv[1:]
    # stash the parsed config ON THE PACKAGE (not this module — under
    # `python -m` this file runs as '__main__' and a scripts' import of
    # flexflow_tpu.__main__ would be a fresh second instance)
    import flexflow_tpu

    flexflow_tpu._driver_config = FFConfig.from_args(rest)
    sys.argv = [script] + rest
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
