"""fflint — pass-based static analysis for strategies, the sharding
algebra, and the substitution corpus.

Unity-style search is only trustworthy while its invariants hold; round-5
review enforced them by human advisor (two cost-model/lowering pricing
divergences shipped, 377/408 corpus rules silently inert with no tool to
say why). This subsystem turns those recurring review findings into a CI
gate. Eight passes ship (registered like op lowerings, so future PRs add
passes, not frameworks):

  consistency — strategy/sharding algebra per node: degrees divide dims,
      GQA head grouping, producer/consumer resharding, and the
      cost-model-vs-lowering comm-spec cross-check (parallel.comm_spec).
  rulesat     — per-rule static satisfiability of the substitution corpus
      (fireable / inert-unsatisfiable / unreachable-on-baselines, with
      reasons), cross-validated against search.soundness instantiation.
  hostsync    — AST lint of runtime/serving/paged/spec for jit-boundary
      hazards (.item() device syncs in decode loops, jnp ops in host-side
      loops, shape-dependent branches in jitted fns, stale suppression
      pragmas).
  hloaudit    — ground-truth audit of the LOWERED programs: AOT-compiles
      each config's real jitted entry points, parses the optimized HLO
      (collective schedule, transpose/copy overhead, buffer-assignment
      peak HBM) and diffs it against the cost model's priced-events
      manifest. Compiles XLA programs, so the CLI runs it only when
      selected (--passes hloaudit / all).
  poolcheck   — the paged serving state machine: an explicit-state model
      checker BFS-explores bounded configurations of the REAL PagePool +
      scheduler bookkeeping (admission/COW/free/defrag/preempt/spec-
      commit), asserting the declarative invariant catalog
      (pool_invariants.py) at every reachable state and reporting
      minimal counterexample traces; plus an AST lint arm for
      write-after-share, page-table, pool-encapsulation, and
      lock-discipline hazards (pragma-annotatable like hostsync).
  racecheck   — lock-discipline + interleaving checking for the threaded
      serving protocols: a whole-repo lock model inferring which locks
      guard which fields (race-unguarded-write, lock-order-cycle,
      lock-held-device-sync, atomicity-split, with race-ok pragmas), and
      a bounded interleaving model checker over abstract LTS models of
      the prefill→decode handoff, tier spill/fetch, and drain-and-swap
      protocols with DPOR-style sleep-set pruning and minimal replayable
      counterexample traces. poolcheck's lock lint delegates here.
  numcheck    — the low-precision gate: an AST dtype-flow arm tracking
      array dtype provenance through the serving hot paths
      (dtype-silent-promotion, scale-unpaired-access,
      dtype-accum-unspecified, with dtype-ok pragmas), an HLO numerics
      arm diffing each lowered entry's convert/dot-accumulation dtypes
      against the Executor's declared dtype plan (hlo-unexpected-f64,
      hlo-accum-downgrade, hlo-unplanned-convert; pairs with
      hloaudit's lowering driver), and a tolerance-budget arm
      validating the declarative numerics band catalog
      (num_budgets.py) that the tests and the kv_quant_canary consume.
  shapecheck  — the launch-shape-space auditor: a taint arm classifying
      every symbolic width feeding a jit launch as clamped/unbounded, an
      enumeration arm computing the closed per-config catalog of
      reachable launch shapes (the upper bound on XLA compilations,
      budget-gated), and a soundness arm diffing runtime compile events
      (obs.compile_tracker) against the catalog — steady-state serving
      provably never recompiles.

CLI: tools/fflint.py (--json, --strict, per-pass selection, --sarif);
tier-1 gates on zero strict findings via tests/test_analysis.py. See
docs/analysis.md.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

# severity ladder: "error" always gates the CLI exit code; "warning"
# gates only under --strict; "info" is observability and never gates
SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Finding:
    """One analyzer finding. `where` names the subject (node, rule, or
    file:line) so every message is actionable without re-running."""

    pass_name: str
    severity: str
    code: str
    where: str
    message: str

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class AnalysisContext:
    """Inputs a pass may consume; passes skip checks whose inputs are
    absent (e.g. rulesat without baseline graphs skips reachability)."""

    # consistency inputs: a PCG + per-node ShardingView assignment on a
    # mesh described by axis_sizes; cost_model enables the comm cross-check
    graph: Optional[object] = None
    strategy: Optional[Dict] = None
    axis_sizes: Optional[Dict[str, int]] = None
    cost_model: Optional[object] = None
    # a label for findings ("llama_tp_dp", "import:strategy.json", ...)
    subject: str = ""
    # rulesat inputs
    rules: Optional[List[Dict]] = None
    baseline_graphs: Optional[List] = None  # [(config_name, Graph)]
    coverage_snapshot: Optional[Dict] = None
    # rulesat classification output ({rule_name: {...}}), filled by the pass
    rule_classification: Optional[Dict] = None
    # hostsync inputs: files or directories to scan
    src_paths: Optional[List[str]] = None
    # hloaudit inputs: {entry: {"hlo_text": str, "memory": stats} or
    # {"error": str}} from analysis.hloaudit.lower_executor_modules, plus
    # tolerance overrides (an AuditOptions or its kwargs dict)
    hlo_modules: Optional[Dict] = None
    hlo_opts: Optional[object] = None
    # hloaudit per-subject program summaries, filled by the pass
    hlo_summary: Optional[Dict] = None
    # poolcheck controls: lint arm only (--since mode), a PagePool
    # subclass to check (the seeded-mutation fixtures), harness-level
    # mutation labels, and a directory for counterexample trace JSONs
    poolcheck_lint_only: bool = False
    poolcheck_pool_factory: Optional[Callable] = None
    poolcheck_mutations: Optional[List[str]] = None
    poolcheck_trace_dir: Optional[str] = None
    # model-check summary (explored/distinct states per config), filled
    # by the pass
    poolcheck_summary: Optional[Dict] = None
    # shapecheck controls: compile budget per served config (None =
    # shapecheck.DEFAULT_SHAPE_BUDGET) and config overrides
    # ({name: enumerate_catalog kwargs}; None = DEFAULT_CONFIGS)
    shapecheck_budget: Optional[int] = None
    shapecheck_configs: Optional[Dict] = None
    # shape catalogs + jit entry-point inventory, filled by the pass
    shapecheck_summary: Optional[Dict] = None
    # racecheck controls: lint arm only (--since mode), explicit lint
    # paths (fixtures), protocol-model mutation labels, interleaving
    # trace dir, and the context-switch bound (None = default)
    racecheck_lint_only: bool = False
    racecheck_paths: Optional[List[str]] = None
    racecheck_mutations: Optional[List[str]] = None
    racecheck_trace_dir: Optional[str] = None
    racecheck_switch_bound: Optional[int] = None
    # interleaving-exploration summary (explored/distinct states per
    # model), filled by the pass
    racecheck_summary: Optional[Dict] = None
    # numcheck controls: the per-entry dtype plan for the HLO numerics
    # arm (Executor.dtype_plan(); arm skips when absent) and the
    # tolerated out-of-plan float-convert count per dtype pair
    numcheck_dtype_plan: Optional[Dict] = None
    numcheck_convert_band: Optional[int] = None
    # AST-arm scan inventory / per-subject HLO numerics, filled by the pass
    numcheck_summary: Optional[Dict] = None


@dataclasses.dataclass
class Report:
    findings: List[Finding] = dataclasses.field(default_factory=list)
    stats: Dict[str, Dict] = dataclasses.field(default_factory=dict)

    def extend(self, findings: List[Finding]) -> None:
        self.findings.extend(findings)

    def by_severity(self, severity: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self) -> List[Finding]:
        return self.by_severity("error")

    @property
    def warnings(self) -> List[Finding]:
        return self.by_severity("warning")

    def gating(self, strict: bool = False) -> List[Finding]:
        """Findings that fail the run: errors always, warnings when
        strict."""
        out = list(self.errors)
        if strict:
            out += self.warnings
        return out

    def to_json(self) -> Dict:
        counts = {s: len(self.by_severity(s)) for s in SEVERITIES}
        return {
            "findings": [f.to_json() for f in self.findings],
            "counts": counts,
            "stats": self.stats,
        }


# ---------------------------------------------------------------------------
# pass registry (the register_lowering idiom: passes are registered by
# name; adding a pass is one decorated function, not a framework change)

_PASSES: Dict[str, Callable[[AnalysisContext], List[Finding]]] = {}


def register_pass(name: str):
    def deco(fn):
        fn.pass_name = name
        _PASSES[name] = fn
        return fn

    return deco


def get_pass(name: str) -> Callable[[AnalysisContext], List[Finding]]:
    _ensure_registered()
    if name not in _PASSES:
        raise KeyError(
            f"no analysis pass named {name!r}; available: "
            f"{sorted(_PASSES)}"
        )
    return _PASSES[name]


def available_passes() -> List[str]:
    _ensure_registered()
    return sorted(_PASSES)


def _ensure_registered() -> None:
    # imports populate the registry on first use (registry.py idiom)
    from flexflow_tpu.analysis import (  # noqa: F401
        consistency,
        hloaudit,
        hostsync,
        numcheck,
        poolcheck,
        racecheck,
        rulesat,
        shapecheck,
    )


def run_passes(names: Optional[List[str]], ctx: AnalysisContext,
               report: Optional[Report] = None) -> Report:
    """Run the named passes (all registered passes when None) over one
    context, appending to `report` when given (the CLI runs consistency
    once per BASELINE config into a single report)."""
    _ensure_registered()
    report = report or Report()
    for name in names or available_passes():
        fn = get_pass(name)
        findings = fn(ctx)
        report.extend(findings)
        st = report.stats.setdefault(name, {"findings": 0, "subjects": []})
        st["findings"] += len(findings)
        if ctx.subject:
            st["subjects"].append(ctx.subject)
    return report
