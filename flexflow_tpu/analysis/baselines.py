"""BASELINE configs for the static analyzer (and coverage tooling).

The single home of the config list that used to live in
tools/rule_coverage.py: each entry is (name, build(ff), mesh_shape) for
the BASELINE.md targets plus InceptionV3 (where the concat/merge algebra
demonstrably fires) plus a seq-parallel llama variant that exercises the
ring/ulysses comm-spec cross-check. `build_baseline_subjects()` builds
the PCGs with their canonical hand strategies (default DP where no hand
strategy exists) — the subjects `fflint --strict` must run clean on.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple


def baseline_configs() -> List[Tuple[str, Callable, Dict[str, int]]]:
    """(name, build(ff) -> None, mesh_shape) per BASELINE config plus
    InceptionV3; small layer counts — coverage and consistency depend on
    structure, not depth."""
    from flexflow_tpu.models.alexnet import build_alexnet_cifar10
    from flexflow_tpu.models.bert import BertConfig, build_bert
    from flexflow_tpu.models.inception import build_inception_v3
    from flexflow_tpu.models.llama import LlamaConfig, build_llama
    from flexflow_tpu.models.mixtral import MixtralConfig, build_mixtral
    from flexflow_tpu.models.resnet import build_resnet50

    def alexnet(ff):
        build_alexnet_cifar10(ff, batch_size=8)

    def resnet(ff):
        build_resnet50(ff, batch_size=8, classes=100)

    def bert(ff):
        build_bert(ff, BertConfig(vocab_size=512, hidden=64, layers=2,
                                  heads=4, intermediate=128),
                   batch_size=8, seq_len=64)

    def llama(ff):
        build_llama(ff, LlamaConfig(vocab_size=512, dim=64, layers=2,
                                    heads=4, kv_heads=2, hidden=128,
                                    rope_theta=10000.0),
                    batch_size=8, seq_len=128)

    def mixtral(ff):
        build_mixtral(ff, MixtralConfig.tiny(), batch_size=8, seq_len=32)

    def inception(ff):
        # 75px input keeps the tiny-config search fast; every inception
        # block's concat-of-parallel-branches structure is preserved
        build_inception_v3(ff, batch_size=8, classes=32, image_size=75)

    return [
        ("alexnet_cifar10", alexnet, {"data": 2, "model": 4}),
        ("resnet50", resnet, {"data": 2, "model": 4}),
        ("bert_base", bert, {"data": 2, "model": 4}),
        ("llama_tp_dp", llama, {"data": 2, "seq": 2, "model": 2}),
        ("mixtral_ep", mixtral, {"data": 2, "expert": 4}),
        ("inception_v3", inception, {"data": 2, "model": 4}),
    ]


def _llama_tiny_cfg():
    from flexflow_tpu.models.llama import LlamaConfig

    return LlamaConfig(vocab_size=512, dim=64, layers=2, heads=4,
                       kv_heads=2, hidden=128, rope_theta=10000.0)


def build_graph(build: Callable, mesh_shape: Dict[str, int]):
    """Build one config's PCG (no search, no compile, no mesh needed)."""
    from flexflow_tpu import FFConfig, FFModel

    ff = FFModel(FFConfig(batch_size=8, mesh_shape=dict(mesh_shape)))
    build(ff)
    ff.graph.infer_shapes()
    return ff.graph


def _hand_strategy(name: str) -> Optional[Dict]:
    """The shipped hand strategy for a config (None = default DP)."""
    if name == "bert_base":
        from flexflow_tpu.models.bert import (
            BertConfig,
            bert_attribute_parallel_strategy,
        )

        return bert_attribute_parallel_strategy(
            BertConfig(vocab_size=512, hidden=64, layers=2, heads=4,
                       intermediate=128))
    if name == "llama_tp_dp":
        from flexflow_tpu.models.llama import llama_tp_strategy

        return llama_tp_strategy(_llama_tiny_cfg())
    if name == "mixtral_ep":
        from flexflow_tpu.models.mixtral import (
            MixtralConfig,
            mixtral_ep_strategy,
        )

        return mixtral_ep_strategy(MixtralConfig.tiny())
    return None


SP_SUBJECT_NAMES = ("llama_sp_ring", "llama_sp_ulysses")

_SP_MESH = {"data": 2, "seq": 2, "model": 2}


def known_subject_names() -> List[str]:
    return [name for name, _, _ in baseline_configs()] + list(SP_SUBJECT_NAMES)


def _subject_recipe(name: str):
    """(build(ff), mesh_shape, strategy(graph)) for one subject name —
    the single home of per-config construction, shared by
    build_baseline_subjects (graphs for the consistency pass) and
    build_baseline_executor (compiled executors for hloaudit), so the
    two passes can never silently audit different subjects."""
    from flexflow_tpu.models.llama import build_llama, llama_tp_strategy
    from flexflow_tpu.search.api import space_dp_strategy

    if name not in known_subject_names():
        raise ValueError(f"unknown BASELINE config name {name!r}; known: "
                         f"{known_subject_names()}")
    if name in SP_SUBJECT_NAMES:
        seq_mode = "ring" if name.endswith("ring") else "ulysses"

        def build(ff):
            build_llama(ff, _llama_tiny_cfg(), batch_size=8, seq_len=128,
                        use_ring_attention=True, seq_mode=seq_mode)

        return build, dict(_SP_MESH), lambda graph: llama_tp_strategy(
            _llama_tiny_cfg(), seq_parallel=True)

    _, build, mesh_shape = next(
        c for c in baseline_configs() if c[0] == name)

    def strategy_for(graph):
        hand = _hand_strategy(name)
        return (hand if hand is not None
                else space_dp_strategy(graph, mesh_shape))

    return build, dict(mesh_shape), strategy_for


def build_baseline_subjects(names: Optional[List[str]] = None):
    """[(name, graph, strategy, axis_sizes)] for the consistency pass:
    every BASELINE config under its canonical strategy (hand strategy
    where one ships, default DP otherwise), plus `llama_sp_ring` /
    `llama_sp_ulysses` — seq-parallel ring-attention builds whose views
    must agree with the exchange the lowering emits."""
    if names:
        unknown = sorted(set(names) - set(known_subject_names()))
        if unknown:
            # a typo must not silently validate NOTHING and report clean
            raise ValueError(
                f"unknown BASELINE config name(s) {unknown}; known: "
                f"{known_subject_names()}")
    subjects = []
    for name in known_subject_names():
        if names and name not in names:
            continue
        build, mesh_shape, strategy_for = _subject_recipe(name)
        graph = build_graph(build, mesh_shape)
        subjects.append((name, graph, strategy_for(graph), mesh_shape))
    return subjects


def build_baseline_executor(name: str):
    """Compile ONE BASELINE config end-to-end — FFModel.compile under its
    canonical strategy on the local (8-device CPU) mesh — and return
    (executor, graph, strategy, axis_sizes). This is the hloaudit entry:
    the executor's lowered_modules() are the ground-truth artifacts the
    cost model is audited against; _subject_recipe guarantees it is the
    SAME config/strategy the consistency pass checks
    (build_baseline_subjects)."""
    from flexflow_tpu import AdamOptimizer, FFConfig, FFModel, LossType

    build, mesh_shape, strategy_for = _subject_recipe(name)
    ff = FFModel(FFConfig(batch_size=8, mesh_shape=dict(mesh_shape)))
    build(ff)
    ff.graph.infer_shapes()
    strategy = strategy_for(ff.graph)
    ff.compile(optimizer=AdamOptimizer(lr=1e-4),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               strategy=strategy)
    return ff.executor, ff.graph, strategy, dict(mesh_shape)
