"""Strategy/sharding consistency pass.

Verifies the sharding algebra of a PCG + per-node ShardingView assignment
(searched, hand-written, or imported from a strategy file) node by node:

  - every mesh-axis degree divides the tensor dim it shards (prune_spec
    silently replicates non-dividing axes at execution, so a view that
    declares them is priced for a shard the machine never runs —
    warning: execution stays correct but the pricing diverges);
  - no axis appears twice within one spec, specs don't outrank tensors
    (errors: GSPMD/XLA reject these outright — the cryptic lowering
    failures strategy-file import used to die with);
  - GQA head grouping is consistent across wq/wk/wv/wo (warnings:
    GSPMD reshards to correctness, the grouping is priced wrong);
  - producer/consumer views agree or the reshard is explicit (implicit
    GSPMD reshards are legal and priced — reported as info);
  - the communication the cost model PRICES for an attention node+view
    matches the collectives the lowering would EMIT — both sides export a
    declarative comm-spec (CostModel.attention_comm_spec vs
    parallel.comm_spec.attention_lowered_comm_spec); this is the check
    the round-5 advisor did by hand for the ulysses h_deg and ring GQA
    divergences.

Unknown axes are info (a strategy written for a larger mesh degrades
gracefully by design).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass
from flexflow_tpu.ffconst import OpType, PARALLEL_OP_TYPES

_ATTN_OPS = (OpType.MULTIHEAD_ATTENTION, OpType.RING_ATTENTION)


def _deg(axes, axis_sizes) -> int:
    from flexflow_tpu.parallel.comm_spec import axes_degree

    return axes_degree(axes, axis_sizes)


def _fmt_spec(spec) -> str:
    if spec is None:
        return "R"
    return "(" + ",".join("+".join(a) if a else "·" for a in spec) + ")"


def _check_spec(findings: List[Finding], subject: str, node_name: str,
                what: str, spec, dims: Tuple[int, ...],
                axis_sizes: Dict[str, int]) -> None:
    """Structural checks of one spec against the tensor dims it shards."""
    if spec is None:
        return
    where = f"{subject}:{node_name}" if subject else node_name

    def add(severity, code, msg):
        findings.append(Finding("consistency", severity, code, where, msg))

    if len(spec) > len(dims):
        add("error", "spec-rank",
            f"{what} spec {_fmt_spec(spec)} has {len(spec)} entries for a "
            f"rank-{len(dims)} tensor {dims}")
        return
    for i, axes in enumerate(spec):
        if not axes:
            continue
        if len(set(axes)) != len(axes):
            add("error", "duplicate-axis",
                f"{what} dim {i} repeats a mesh axis: {_fmt_spec(spec)}")
            continue
        known = tuple(a for a in axes if a in axis_sizes)
        unknown = tuple(a for a in axes if a not in axis_sizes)
        if unknown:
            add("info", "unknown-axis",
                f"{what} dim {i} names mesh axes {unknown} absent from "
                f"this mesh {sorted(axis_sizes)} — they are dropped at "
                "execution (strategy written for a larger mesh)")
        d = _deg(known, axis_sizes)
        if d > 1 and dims[i] % d != 0:
            # warning, not error: prune_spec defines this as graceful
            # degradation (the axis is dropped at execution), so the
            # program stays correct — but the cost model prices the
            # shard the machine never runs, so under --strict it gates
            add("warning", "degree-divides",
                f"{what} dim {i} (size {dims[i]}) sharded {d}-way over "
                f"{known}: degree does not divide the dim, so execution "
                "replicates it (prune_spec) while the cost model prices "
                "the shard — fix the view or the mesh")


def _axes_used_twice_across_dims(spec) -> Optional[str]:
    seen = set()
    for axes in spec or ():
        for a in axes:
            if a in seen:
                return a
            seen.add(a)
    return None


def _check_gqa(findings: List[Finding], subject: str, node, view,
               axis_sizes: Dict[str, int]) -> None:
    a = node.attrs
    where = f"{subject}:{node.name}" if subject else node.name

    def add(severity, code, msg):
        findings.append(Finding("consistency", severity, code, where, msg))

    if a.num_kv and a.num_heads % a.num_kv != 0:
        add("warning", "gqa-grouping",
            f"num_heads={a.num_heads} is not a multiple of "
            f"kv_heads={a.num_kv}: GQA groups are ill-defined")
        return
    # head-dim positions: wq (embed, H, hd) dim 1; wk/wv (embed, Hkv, hd)
    # dim 1; wo (H, hd, embed) dim 0
    def head_axes(name, dim):
        spec = (view.weight_specs or {}).get(name)
        if spec is None or dim >= len(spec):
            return ()
        return tuple(spec[dim])

    wq, wo = head_axes("wq", 1), head_axes("wo", 0)
    wk, wv = head_axes("wk", 1), head_axes("wv", 1)
    if wq and wo and set(wq) != set(wo):
        add("warning", "gqa-grouping",
            f"wq shards heads over {wq} but wo over {wo}: the output "
            "projection's partial sums would mix different head groups")
    if wk != wv:
        add("warning", "gqa-grouping",
            f"wk shards kv heads over {wk} but wv over {wv}: k and v "
            "rows of one group would land on different shards")
    if wq and wk and set(wk) - set(wq):
        add("warning", "gqa-grouping",
            f"wk shards kv heads over {wk} not covered by wq's head "
            f"axes {wq}: kv groups must follow their query heads")
    if wq and not wk and a.num_kv != a.num_heads:
        add("info", "gqa-replicated-kv",
            f"query heads sharded over {wq} with kv heads replicated "
            "(legal GQA fallback; each shard repeats kv locally)")


def _norm(spec, ndim: int):
    out = []
    for i in range(ndim):
        axes = spec[i] if spec is not None and i < len(spec) else ()
        out.append(tuple(axes))
    while out and not out[-1]:
        out.pop()
    return tuple(out)


def _check_edges(findings: List[Finding], subject: str, graph, strategy,
                 axis_sizes) -> None:
    for node in graph.nodes:
        view = strategy.get(node.name, node.sharding)
        if view is None:
            continue
        for e in graph.out_edges(node):
            dst = graph.node(e.dst)
            if dst.op_type in PARALLEL_OP_TYPES:
                continue  # the reshard is explicit
            dst_view = strategy.get(dst.name, dst.sharding)
            if dst_view is None or not dst_view.input_specs:
                continue
            din = dst_view.input_spec(e.dst_idx)
            if din is None:
                continue
            shape = node.outputs[e.src_idx]
            src = _norm(view.output_spec(e.src_idx), shape.ndim)
            dstn = _norm(din, shape.ndim)
            if src != dstn:
                where = f"{subject}:{node.name}->{dst.name}" if subject \
                    else f"{node.name}->{dst.name}"
                findings.append(Finding(
                    "consistency", "info", "implicit-reshard", where,
                    f"producer emits {_fmt_spec(src)} but consumer "
                    f"declares input {_fmt_spec(dstn)}: GSPMD inserts the "
                    "reshard implicitly (priced by edge_xfer_time)"))


def _check_attention_comm(findings: List[Finding], subject: str, graph,
                          node, view, axis_sizes, cost_model) -> None:
    """Cross-check: priced comm-spec == lowered comm-spec."""
    from flexflow_tpu.parallel.comm_spec import attention_lowered_comm_spec

    # view may be None (node not covered by the strategy): the cost model
    # then prices NO attention comm, but a mesh-driven ring/ulysses
    # lowering still exchanges — exactly the underpricing to surface
    priced = [st for st in cost_model.attention_comm_spec(graph, node, view)
              if st.kind != "all_reduce"]  # wo psum is view-driven;
    # the exchange legs are where pricing historically drifted
    out = node.outputs[0]
    spec = view.output_spec(0) if view is not None else None
    view_seq = tuple(spec[1]) if spec and len(spec) > 1 and spec[1] else ()
    is_ring = node.op_type == OpType.RING_ATTENTION
    lowered = attention_lowered_comm_spec(
        node.attrs, out.dims[0].size, out.dims[1].size,
        out.dtype.size_bytes, axis_sizes,
        is_ring_op=is_ring, view_seq_axes=view_seq,
    )
    if sorted(st.key() for st in priced) == sorted(
            st.key() for st in lowered):
        return
    where = f"{subject}:{node.name}" if subject else node.name

    def fmt(steps):
        if not steps:
            return "(none)"
        return "; ".join(
            f"{st.kind} over {list(st.axes)} of {st.nbytes}B"
            for st in steps)

    findings.append(Finding(
        "consistency", "error", "comm-spec-mismatch", where,
        f"cost model prices [{fmt(priced)}] but the lowering emits "
        f"[{fmt(lowered)}] — the search would rank strategies against "
        "communication the machine never runs (the round-5 ulysses-h_deg "
        "bug class); align CostModel.attention_comm_spec with "
        "parallel.comm_spec.attention_lowered_comm_spec"))


def check_strategy(graph, strategy: Optional[Dict], axis_sizes: Dict[str, int],
                   cost_model=None, subject: str = "") -> List[Finding]:
    """Run all consistency checks; `strategy` falls back to each node's
    attached sharding when None (post-_apply_strategy graphs)."""
    findings: List[Finding] = []
    strategy = dict(strategy or {})

    known = {n.name for n in graph.nodes}
    stale = sorted(set(strategy) - known)
    if stale:
        sev = "error" if len(stale) == len(strategy) and strategy else "warning"
        findings.append(Finding(
            "consistency", sev, "stale-strategy",
            subject or "strategy",
            f"{len(stale)}/{len(strategy)} strategy entries name nodes "
            f"absent from the graph ({', '.join(stale[:5])}"
            f"{', ...' if len(stale) > 5 else ''}): "
            + ("the whole file matches nothing — wrong model or a stale "
               "export" if sev == "error" else
               "those views are ignored (stale or renamed nodes)")))

    for node in graph.nodes:
        view = strategy.get(node.name, node.sharding)
        if view is None:
            # a view-less attention node still gets the comm cross-check:
            # ring/ulysses lowerings exchange mesh-driven, so "no view"
            # prices zero while the machine still pays — flag it
            if (node.op_type in _ATTN_OPS and node.attrs is not None
                    and cost_model is not None and node.outputs
                    and node.outputs[0].ndim >= 3):
                _check_attention_comm(findings, subject, graph, node, None,
                                      axis_sizes, cost_model)
            continue
        ins = graph.input_shapes(node)
        if node.in_shapes and len(ins) < len(node.in_shapes):
            ins = list(node.in_shapes)
        for i, spec in enumerate(view.output_specs):
            if i < len(node.outputs):
                dims = tuple(d.size for d in node.outputs[i].dims)
                _check_spec(findings, subject, node.name,
                            f"output[{i}]", spec, dims, axis_sizes)
            if spec is not None:
                a = _axes_used_twice_across_dims(spec)
                if a:
                    findings.append(Finding(
                        "consistency", "error", "duplicate-axis",
                        f"{subject}:{node.name}" if subject else node.name,
                        f"output[{i}] uses mesh axis {a!r} on two dims: "
                        f"{_fmt_spec(spec)}"))
        if node.attrs is not None and view.weight_specs:
            try:
                ws = node.attrs.weights(*ins)
            except Exception:
                ws = {}
            for name, wspec in view.weight_specs.items():
                if name not in ws:
                    findings.append(Finding(
                        "consistency", "warning", "unknown-weight",
                        f"{subject}:{node.name}" if subject else node.name,
                        f"view shards weight {name!r} but "
                        f"{node.op_type.name} has weights "
                        f"{sorted(ws) or '(none)'}"))
                    continue
                dims = tuple(d for d in ws[name].shape.dims)
                _check_spec(findings, subject, node.name,
                            f"weight {name!r}", wspec, dims, axis_sizes)
        for i, spec in enumerate(view.input_specs):
            if spec is not None and i < len(ins):
                dims = tuple(d.size for d in ins[i].dims)
                _check_spec(findings, subject, node.name,
                            f"input[{i}]", spec, dims, axis_sizes)
        if node.op_type in _ATTN_OPS and node.attrs is not None:
            _check_gqa(findings, subject, node, view, axis_sizes)
            if cost_model is not None and node.outputs \
                    and node.outputs[0].ndim >= 3:
                _check_attention_comm(findings, subject, graph, node, view,
                                      axis_sizes, cost_model)

    _check_edges(findings, subject, graph, strategy, axis_sizes)
    return findings


@register_pass("consistency")
def consistency_pass(ctx: AnalysisContext) -> List[Finding]:
    if ctx.graph is None or ctx.axis_sizes is None:
        return []
    return check_strategy(ctx.graph, ctx.strategy, ctx.axis_sizes,
                          cost_model=ctx.cost_model, subject=ctx.subject)
