"""hloaudit — ground-truth static audit of lowered programs vs the
search cost model.

Search quality is bounded by cost-model fidelity (FlexFlow, MLSys'19),
and the consistency pass only cross-checks the DECLARED comm-spec for
attention — matmul TP all-reduces, DP grad sync, MoE all-to-alls, and
per-chip HBM were priced on trust. XLA gives a better oracle for free:
the whole step lowers to ONE optimized HLO module that can be parsed
statically (the full-compilation discipline of "Automatic Full
Compilation of Julia Programs to Cloud TPUs"). This pass AOT-lowers each
config's real jitted entry points (Executor.lowered_modules: train_step,
eval_step, paged_decode_fn, verify_fn) on the multi-device CPU mesh,
parses the optimized HLO into a structured program summary —

  - the collective schedule: kind / replica groups / payload bytes per
    all-reduce, all-gather, all-to-all, collective-permute,
    reduce-scatter, attributed back to PCG nodes through the stable-key
    jax.named_scope the executor stamps into HLO metadata op_names;
  - transpose/copy overhead bytes (the round-4 backward-layout audit,
    folded in from tools/hlo_transpose_audit.py — one HLO parser in the
    tree);
  - peak per-chip HBM from XLA's buffer assignment (memory_analysis);

— and diffs it against what the search PRICED: the per-node manifest
CostModel.priced_comm_manifest exports (node_comm_events +
weight_sync_events + edge resharding, kind/axes/bytes per node). Findings:

  hlo-unpriced-collective (error)   the lowered program runs a collective
      at a node that priced nothing of that class — the search ranked
      strategies blind to it (the round-5 divergence class, now machine-
      caught).
  hlo-mispriced-bytes (warn/error)  priced vs lowered payload bytes for
      one (node, class) diverge beyond the tolerance band. Bands are wide
      by design: priced bytes are forward-pass global-tensor conventions
      while lowered payloads are per-shard with backward multiplicity.
  hlo-vanished-collective (info)    priced but absent from the artifact
      (XLA legally folds collectives; observability only).
  hlo-mem-divergence (warning)      priced memory_per_chip vs XLA's peak
      beyond the ratio band (above an absolute floor — tiny test configs
      are all constant overhead).
  hlo-hbm-budget (error)            a config whose priced or lowered
      per-chip peak exceeds the machine model's HBM — the memory-aware
      λ-search would steer INTO an OOM.
  hlo-transpose-overhead (info)     transpose+copy bytes above threshold
      (rank offenders with tools/hlo_transpose_audit.py).
  hlo-entry-failed (warning)        a train/eval entry point failed to
      lower or compile (decode entries skip as info).

The diff is deliberately class-coarse (reduce / gather / exchange):
GSPMD decomposes collectives (an expert all-to-all can lower as
all-gathers + collective-permutes; an all-reduce as reduce-scatter +
all-gather), and backward transposes them (the transpose of an
all-gather is a reduce-scatter). What must never happen is a class of
traffic the search priced at zero.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass

# ---------------------------------------------------------------------------
# HLO text parsing (the one HLO parser in the tree; the transpose audit
# CLI wraps these same helpers)

_DT_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1,
             "u8": 1, "pred": 1, "s64": 8, "u64": 8, "f64": 8}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _literal_bytes(m: "re.Match") -> int:
    dt, dims = m.group(1), m.group(2)
    if dt not in _DT_BYTES:
        return 0
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DT_BYTES[dt]


def shape_bytes(shape_str: str) -> int:
    """Bytes of every shape literal in an HLO type string summed (tuple
    types sum their members)."""
    return sum(_literal_bytes(m) for m in _SHAPE_RE.finditer(shape_str))


def _payload_bytes(type_str: str, is_start: bool) -> int:
    """Payload bytes of one collective's result type. Arrays and SYNC
    tuples (variadic combined collectives — every member is moved data)
    sum their literals. Async `-start` tuples vary across XLA versions:
    operand/result pairs (flat or nested, possibly variadic) double the
    moved bytes — detected as the member list being its own first half
    repeated, and halved — while array-plus-scratch layouts (e.g.
    `(f32[N], u32[], u32[])` collective-permute-start) are summed as-is,
    the scratch words being noise against the band tolerances."""
    members = [_literal_bytes(m) for m in _SHAPE_RE.finditer(type_str)]
    total = sum(members)
    if not (is_start and type_str.startswith("(")):
        return total
    n = len(members)
    if n >= 2 and n % 2 == 0 and members[:n // 2] == members[n // 2:]:
        return total // 2
    return total


# transpose/copy results are always array-typed; one pattern shared by
# audit_hlo_text (the CLI scan) and parse_hlo_module so they can't drift
_TRANSPOSE_RE = re.compile(r"%?[\w.\-]+ = (\S+) (transpose|copy)\(")


def audit_hlo_text(txt: str, min_bytes: int = 0) -> List[Dict]:
    """Scan optimized HLO text for transpose/copy instructions; returns
    [{kind, bytes, line}] largest first (fused bodies print the same
    instruction syntax, so fusions are covered line by line)."""
    out = []
    for line in txt.splitlines():
        s = line.strip()
        m = _TRANSPOSE_RE.match(s)
        if not m:
            continue
        nbytes = shape_bytes(m.group(1))
        if nbytes < min_bytes:
            continue
        out.append({"kind": m.group(2), "bytes": nbytes, "line": s[:220]})
    out.sort(key=lambda d: -d["bytes"])
    return out


_COLL_KINDS = ("all-reduce", "all-gather", "all-to-all",
               "collective-permute", "reduce-scatter")

# the type is an array (`f32[...]`), a flat tuple (variadic combined
# collectives, async `-start` operand/result + scratch), or a one-level
# nested tuple (the combined variadic async form
# `((f32[...], ...), (f32[...], ...)) all-reduce-start`); `-done` lines
# never match, so each payload is counted once, at the start
_COLL_RE = re.compile(
    r"%?[\w.\-]+ = (\((?:[^()]|\([^()]*\))*\)|\S+) ("
    + "|".join(_COLL_KINDS) + r")(-start)?\("
)
_GROUPS_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')


_RNG_MARKERS = ("_uniform", "_bernoulli", "threefry", "random_bits",
                "random_gamma")


@dataclasses.dataclass
class LoweredCollective:
    """One collective instruction of the optimized module. `payload`
    follows the machine-model byte conventions the priced events use:
    per-chip operand for all-reduce / collective-permute, the full
    gathered (pre-scattered) tensor for all-gather (reduce-scatter),
    the per-chip tensor for all-to-all. `rng` marks partitioned-RNG
    plumbing (threefry counter exchanges under dropout): real wire
    traffic, but proportional to mask bits, attributed to whatever op
    holds the dropout — the cost model never prices it and the diff
    skips it (the bytes stay visible in the schedule stats)."""

    kind: str
    payload: int
    group_size: int
    node: Optional[str]
    op_name: str
    line: str
    rng: bool = False

    @property
    def comm_class(self) -> str:
        return _LOWERED_CLASS[self.kind]


@dataclasses.dataclass
class HLOSummary:
    """Structured summary of one entry point's optimized module."""

    collectives: List[LoweredCollective]
    transpose_bytes: int
    copy_bytes: int
    peak_bytes: Optional[int]  # per-chip, from buffer assignment

    def by_node(self) -> Dict[Optional[str], List[LoweredCollective]]:
        out: Dict[Optional[str], List[LoweredCollective]] = {}
        for c in self.collectives:
            out.setdefault(c.node, []).append(c)
        return out

    def schedule(self) -> Dict[str, Dict[str, float]]:
        """{kind: {count, payload_bytes, rng_bytes}} over the module."""
        out: Dict[str, Dict[str, float]] = {}
        for c in self.collectives:
            d = out.setdefault(c.kind, {"count": 0, "payload_bytes": 0,
                                        "rng_bytes": 0})
            d["count"] += 1
            d["payload_bytes"] += c.payload
            if c.rng:
                d["rng_bytes"] += c.payload
        return out


def peak_from_memory_stats(mem) -> Optional[int]:
    """Per-chip peak bytes from a CompiledMemoryStats (or the dict the
    CLI serializes it to): live arguments + outputs + XLA temp buffers,
    minus donated-alias double counting."""
    if mem is None:
        return None
    get = (mem.get if isinstance(mem, dict)
           else lambda k, d=0: getattr(mem, k, d))
    peak = (get("argument_size_in_bytes", 0) + get("output_size_in_bytes", 0)
            + get("temp_size_in_bytes", 0) - get("alias_size_in_bytes", 0))
    return int(peak) if peak > 0 else None


def parse_hlo_module(txt: str, node_keys: Sequence[str],
                     memory=None) -> HLOSummary:
    """Parse one optimized HLO module: every collective instruction
    (kind, replica-group size, payload bytes, attributed PCG node via the
    stable-key named_scope in metadata op_name) plus transpose/copy
    overhead totals."""
    # longest keys first so 'l0_attn_12' wins over a prefix key
    keys = sorted(node_keys, key=len, reverse=True)
    colls: List[LoweredCollective] = []
    t_bytes = c_bytes = 0
    for line in txt.splitlines():
        s = line.strip()
        m = _TRANSPOSE_RE.match(s)
        if m:
            b = shape_bytes(m.group(1))
            if m.group(2) == "transpose":
                t_bytes += b
            else:
                c_bytes += b
            continue
        m = _COLL_RE.match(s)
        if not m:
            continue
        result_bytes = _payload_bytes(m.group(1), bool(m.group(3)))
        kind = m.group(2)
        g = _GROUPS_RE.search(s)
        if g:
            group_size = len(g.group(1).split(","))
        else:
            g = _GROUPS_IOTA_RE.search(s)
            group_size = int(g.group(2)) if g else 1
        payload = result_bytes
        if kind == "reduce-scatter":
            payload = result_bytes * max(group_size, 1)
        om = _OPNAME_RE.search(s)
        op_name = om.group(1) if om else ""
        node = next((k for k in keys if k in op_name), None)
        rng = any(mk in op_name for mk in _RNG_MARKERS)
        colls.append(LoweredCollective(kind, payload, group_size, node,
                                       op_name, s[:240], rng=rng))
    return HLOSummary(colls, t_bytes, c_bytes,
                      peak_from_memory_stats(memory))


# ---------------------------------------------------------------------------
# diff: lowered artifact vs priced manifest

_LOWERED_CLASS = {"all-reduce": "reduce", "reduce-scatter": "reduce",
                  "all-gather": "gather", "all-to-all": "exchange",
                  "collective-permute": "exchange"}
_PRICED_CLASS = {"all_reduce": "reduce", "psum": "reduce",
                 "reduce_scatter": "reduce", "all_gather": "gather",
                 "all_to_all": "exchange", "ppermute": "exchange"}
# priced classes that can legitimately produce each lowered OPCODE:
# GSPMD decomposes an all-to-all into all-gathers/permutes, reassociates
# an all-reduce into reduce-scatter + all-gather, and the BACKWARD of an
# all-gather is a reduce-scatter (so priced gather traffic shows up as
# reduce-scatters in a training module) — but a lowered all-REDUCE can
# only come from priced reduce traffic, which is what makes zeroing a
# priced psum detectable
_SATISFIED_BY = {
    "all-reduce": ("reduce",),
    "reduce-scatter": ("reduce", "gather"),
    "all-gather": ("gather", "exchange", "reduce"),
    "all-to-all": ("exchange",),
    "collective-permute": ("exchange",),
}


@dataclasses.dataclass
class AuditOptions:
    """Tolerances. Byte bands are wide BY DESIGN: priced bytes follow the
    machine-formula conventions (global forward-pass tensors) while
    lowered payloads are per-shard with backward multiplicity — the audit
    exists to catch class-level blindness and order-of-magnitude drift,
    not to re-derive GSPMD."""

    # lowered collectives below this payload never error (latency-bound
    # chatter: loss/metric scalars, index plumbing)
    unpriced_floor_bytes: float = 64e3
    # byte-ratio checks apply only above this payload
    ratio_floor_bytes: float = 1e6
    ratio_warn: float = 8.0
    ratio_error: float = 64.0
    # memory divergence checks apply only above this size
    mem_floor_bytes: float = 64e6
    mem_ratio_warn: float = 8.0
    transpose_info_bytes: float = 256e6


def _fmt_mb(b: float) -> str:
    if b >= 1e9:
        return f"{b / 1e9:.2f}GB"
    if b >= 1e6:
        return f"{b / 1e6:.1f}MB"
    return f"{b / 1e3:.0f}KB"


def _event_fields(ev) -> Tuple[str, Tuple[str, ...], float, str]:
    """(kind, axes, nbytes, source) from a PricedEvent or a plain dict
    (tests build manifests by hand; the CLI may round-trip JSON)."""
    if isinstance(ev, dict):
        return (ev["kind"], tuple(ev.get("axes", ())),
                float(ev["nbytes"]), ev.get("source", "node_comm"))
    return ev.kind, tuple(ev.axes), float(ev.nbytes), ev.source


def diff_entry(subject: str, entry: str, manifest: Optional[Dict],
               summary: HLOSummary, opts: Optional[AuditOptions] = None,
               ) -> List[Finding]:
    """Diff one entry point's lowered collective schedule against the
    priced manifest. `manifest` is CostModel.priced_comm_manifest output
    (None for unpriced entry points — decode paths get schedule/memory
    observability but no comm diff)."""
    opts = opts or AuditOptions()
    findings: List[Finding] = []
    if manifest is None:
        return findings

    # priced classes (and bytes) per node: node events + incident edges
    priced_by_node: Dict[str, Dict[str, float]] = {}
    priced_kinds: Dict[str, set] = {}
    for key, evs in manifest.get("nodes", {}).items():
        for ev in evs:
            kind, _axes, nbytes, _src = _event_fields(ev)
            cls = _PRICED_CLASS[kind]
            d = priced_by_node.setdefault(key, {})
            d[cls] = d.get(cls, 0.0) + nbytes
            priced_kinds.setdefault(key, set()).add(kind)
    edge_classes: Dict[str, set] = {}
    for e in manifest.get("edges", ()):
        cls = _PRICED_CLASS[e["kind"]]
        for end in (e["src"], e["dst"]):
            edge_classes.setdefault(end, set()).add(cls)

    lowered_by_node: Dict[str, Dict[str, float]] = {}
    for c in summary.collectives:
        if c.node is None or c.rng:
            # loss/metrics/optimizer plumbing outside node scopes, and
            # partitioned-RNG counter exchanges the model never prices
            continue
        d = lowered_by_node.setdefault(c.node, {})
        d[c.kind] = d.get(c.kind, 0.0) + c.payload

    where = lambda key: f"{subject}:{entry}:{key}" if subject \
        else f"{entry}:{key}"  # noqa: E731

    for key, kinds in sorted(lowered_by_node.items()):
        have = set(priced_by_node.get(key, ()))
        have_edges = edge_classes.get(key, set())
        for kind, payload in sorted(kinds.items()):
            ok = set(_SATISFIED_BY[kind])
            if ok & have or ok & have_edges:
                # priced — check magnitude (node-priced bytes of every
                # class that can produce this opcode)
                priced_bytes = sum(priced_by_node.get(key, {}).get(c, 0.0)
                                   for c in ok)
                if (payload >= opts.ratio_floor_bytes
                        and priced_bytes > 0.0):
                    ratio = payload / priced_bytes
                    band = max(ratio, 1.0 / ratio)
                    if band > opts.ratio_warn:
                        sev = ("error" if band > opts.ratio_error
                               else "warning")
                        findings.append(Finding(
                            "hloaudit", sev, "hlo-mispriced-bytes",
                            where(key),
                            f"{kind} traffic diverges {band:.1f}x beyond "
                            f"the priced manifest: the lowered module "
                            f"moves {_fmt_mb(payload)} but the cost "
                            f"model priced {_fmt_mb(priced_bytes)} "
                            f"({sorted(priced_kinds.get(key, ()))}) — "
                            "the search ranks this node's strategies on "
                            "bytes the machine does not move"))
                continue
            if payload < opts.unpriced_floor_bytes:
                continue
            findings.append(Finding(
                "hloaudit", "error", "hlo-unpriced-collective",
                where(key),
                f"lowered HLO runs {kind} ({_fmt_mb(payload)} payload) "
                f"at this node, but the cost model priced no "
                f"{'/'.join(ok)}-class collective there (priced kinds: "
                f"{sorted(priced_kinds.get(key, ())) or '(none)'}) — "
                "the search is blind to this traffic (the round-5 "
                "divergence class); align CostModel pricing with the "
                "lowering or fix the strategy view"))

    # priced-but-vanished: observability (XLA legally folds collectives)
    for key, classes in sorted(priced_by_node.items()):
        lowered = lowered_by_node.get(key, {})
        for cls, nbytes in sorted(classes.items()):
            produced = {lc for lc, srcs in _SATISFIED_BY.items()
                        if cls in srcs}
            if nbytes >= opts.ratio_floor_bytes and not (
                    produced & set(lowered)):
                findings.append(Finding(
                    "hloaudit", "info", "hlo-vanished-collective",
                    where(key),
                    f"cost model prices {_fmt_mb(nbytes)} of {cls}-class "
                    f"comm here but the lowered module runs none — "
                    "either XLA folded it or the strategy overprices"))
    return findings


def check_memory(subject: str, entry: str, priced_mem: float,
                 summary: Optional[HLOSummary], machine,
                 opts: Optional[AuditOptions] = None) -> List[Finding]:
    """HBM checks for one entry: the budget gate (error — the
    memory-aware λ-search must not steer on numbers that OOM) and the
    priced-vs-buffer-assignment ratio band (warning, above the floor)."""
    opts = opts or AuditOptions()
    findings: List[Finding] = []
    where = f"{subject}:{entry}" if subject else entry
    budget = machine.memory_per_chip()
    peak = summary.peak_bytes if summary is not None else None
    if priced_mem > budget:
        findings.append(Finding(
            "hloaudit", "error", "hlo-hbm-budget", where,
            f"priced memory_per_chip {_fmt_mb(priced_mem)} exceeds the "
            f"machine model's HBM budget {_fmt_mb(budget)} "
            f"({machine.chip.name}) — the memory-aware search would "
            "select a strategy that cannot fit"))
    if peak is not None and peak > budget:
        findings.append(Finding(
            "hloaudit", "error", "hlo-hbm-budget", where,
            f"XLA buffer assignment peaks at {_fmt_mb(peak)} per chip, "
            f"over the {_fmt_mb(budget)} HBM budget "
            f"({machine.chip.name}) — this program OOMs on the modeled "
            "machine regardless of what the search priced"))
    if (peak is not None and priced_mem > 0
            and max(peak, priced_mem) >= opts.mem_floor_bytes):
        ratio = peak / priced_mem
        band = max(ratio, 1.0 / ratio)
        if band > opts.mem_ratio_warn:
            findings.append(Finding(
                "hloaudit", "warning", "hlo-mem-divergence", where,
                f"XLA peak {_fmt_mb(peak)} vs priced "
                f"{_fmt_mb(priced_mem)} per chip diverge {band:.1f}x — "
                "the memory-aware λ-search is steering on unvalidated "
                "numbers; recalibrate CostModel.node_memory"))
    return findings


def check_transposes(subject: str, entry: str, summary: HLOSummary,
                     opts: Optional[AuditOptions] = None) -> List[Finding]:
    opts = opts or AuditOptions()
    total = summary.transpose_bytes + summary.copy_bytes
    if total < opts.transpose_info_bytes:
        return []
    where = f"{subject}:{entry}" if subject else entry
    return [Finding(
        "hloaudit", "info", "hlo-transpose-overhead", where,
        f"optimized module carries {_fmt_mb(summary.transpose_bytes)} of "
        f"transposes + {_fmt_mb(summary.copy_bytes)} of copies — rank "
        "offenders with tools/hlo_transpose_audit.py and fix the "
        "lowering's layout (VERDICT r4 #2 discipline)")]


# ---------------------------------------------------------------------------
# the registered pass

PRICED_ENTRIES = ("train_step", "eval_step")


@register_pass("hloaudit")
def hloaudit_pass(ctx: AnalysisContext) -> List[Finding]:
    """Diff ctx.hlo_modules ({entry: {"hlo_text", "memory", optionally
    "error"}}) against ctx.cost_model's priced manifest for ctx.graph.
    The CLI fills hlo_modules via Executor.lowered_modules() +
    .compile(); tests inject text directly. Skips silently when the
    lowering inputs are absent (pass-registry contract)."""
    if ctx.graph is None or ctx.hlo_modules is None \
            or ctx.cost_model is None:
        return []
    from flexflow_tpu.search.cost_model import graph_cost

    opts = ctx.hlo_opts if isinstance(ctx.hlo_opts, AuditOptions) else (
        AuditOptions(**(ctx.hlo_opts or {})))
    node_keys = [n.stable_key() for n in ctx.graph.nodes]
    strategy = dict(ctx.strategy or {})
    findings: List[Finding] = []
    summary_out: Dict[str, Dict] = {}
    for entry, mod in sorted(ctx.hlo_modules.items()):
        where = f"{ctx.subject}:{entry}" if ctx.subject else entry
        if mod.get("error"):
            sev = "warning" if entry in PRICED_ENTRIES else "info"
            findings.append(Finding(
                "hloaudit", sev, "hlo-entry-failed", where,
                f"entry point failed to lower/compile: {mod['error']}"))
            continue
        summary = parse_hlo_module(mod["hlo_text"], node_keys,
                                   memory=mod.get("memory"))
        training = entry == "train_step"
        priced = entry in PRICED_ENTRIES
        manifest = None
        if priced:
            manifest = ctx.cost_model.priced_comm_manifest(
                ctx.graph, strategy or None, training=training)
            findings += diff_entry(ctx.subject, entry, manifest, summary,
                                   opts)
            gc = graph_cost(ctx.graph, strategy, ctx.cost_model,
                            training=training)
            findings += check_memory(ctx.subject, entry, gc.memory_per_chip,
                                     summary, ctx.cost_model.machine, opts)
        elif summary.peak_bytes is not None:
            findings += check_memory(ctx.subject, entry, 0.0, summary,
                                     ctx.cost_model.machine, opts)
        findings += check_transposes(ctx.subject, entry, summary, opts)
        summary_out[entry] = {
            "collective_schedule": summary.schedule(),
            "attributed": sum(1 for c in summary.collectives
                              if c.node is not None),
            "unattributed": sum(1 for c in summary.collectives
                                if c.node is None),
            "transpose_bytes": summary.transpose_bytes,
            "copy_bytes": summary.copy_bytes,
            "peak_bytes": summary.peak_bytes,
            "priced": priced,
        }
    if ctx.hlo_summary is None:
        ctx.hlo_summary = {}
    ctx.hlo_summary[ctx.subject or "module"] = summary_out
    return findings


# ---------------------------------------------------------------------------
# driver: lower + compile one executor's entry points into ctx.hlo_modules

def lower_executor_modules(executor,
                           entries: Optional[Sequence[str]] = None,
                           hlo_dump: Optional[str] = None,
                           subject: str = "") -> Dict[str, Dict]:
    """AOT-lower + XLA-compile an Executor's entry points into the
    {entry: {"hlo_text", "memory"} | {"error"}} mapping hloaudit_pass
    consumes. Nothing executes — only compiles. With `hlo_dump`, each
    optimized module is also written to <hlo_dump>/<subject>_<entry>.txt
    for offline diffing."""
    import os

    out: Dict[str, Dict] = {}
    if entries is None:
        entries = ["train_step", "eval_step"]
        if executor.can_paged_decode():
            entries += ["paged_decode", "verify"]
    for entry in entries:
        # one entry per lowered_modules() call: a decode path that cannot
        # trace must not take the train/eval audit down with it
        try:
            low = executor.lowered_modules([entry])[entry]
        except Exception as e:
            out[entry] = {"error": f"{type(e).__name__}: {e}"}
            continue
        try:
            compiled = low.compile()
            txt = compiled.as_text()
            try:
                mem = compiled.memory_analysis()
            except Exception:
                mem = None
            out[entry] = {"hlo_text": txt, "memory": mem}
            if hlo_dump:
                os.makedirs(hlo_dump, exist_ok=True)
                name = f"{subject}_{entry}.txt" if subject else f"{entry}.txt"
                with open(os.path.join(hlo_dump, name), "w") as f:
                    f.write(txt)
        except Exception as e:
            out[entry] = {"error": f"{type(e).__name__}: {e}"}
    return out
