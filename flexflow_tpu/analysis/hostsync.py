"""Retrace/host-sync lint — an AST pass over the serving/runtime hot
paths (`runtime/`, `serving.py`, `paged/`, `spec/`).

Flags jit-boundary hazards in DIRECT function bodies (v1 is deliberately
non-transitive — it reads each function's own AST, not its callees):

  item-sync-in-loop   (error)   `.item()` inside a loop: a per-element
      device sync in a decode hot loop serializes the pipeline; pull the
      whole batch once with np.asarray outside the per-token loop.
  jnp-in-host-loop    (warning) `jnp.*`/`jax.numpy.*` calls inside a
      loop of a NON-jitted function: each call dispatches to the device
      from host code — per-token loops pay a dispatch per step.
  asarray-in-loop     (info)    `np.asarray`/`np.array`/`jax.device_get`
      inside a loop: a bulk sync per iteration — fine once per decode
      tick, a hazard per token (observability; judge by loop granularity).
  shape-branch-in-jit (warning) an `if`/`while` on `.shape`/`.ndim`
      inside a jit-wrapped function: shapes are trace-time constants, so
      the branch recompiles per shape class (fine for deliberate kernel
      selection, a retrace storm when shapes vary per request).
  device-loop         (error)   a host sync inside the body/cond of a
      `lax.while_loop`/`fori_loop`/`scan`: `.item()`, `np.*`/`numpy.*`
      calls, `jax.device_get` or a host callback
      (`pure_callback`/`io_callback`) in a traced device-loop body
      either fails on tracers or silently re-enters the host mid-loop —
      the decode megastep's whole contract is that its inner loop has
      ZERO of these, so this rule takes no pragma suppression.
      `device_loop_bodies(path)` reports which bodies were analyzed, so
      a gate test can assert the rule engaged (a clean result proves
      nothing if no loop was seen).

Suppression: any flagged line (or its enclosing loop header) carrying a
`# fflint: host-ok` / `# fflint: ignore` comment is skipped — intentional
per-tick syncs are annotated, not silenced globally. A directive that no
longer suppresses ANY finding is itself flagged:

  stale-pragma        (info)    the annotated hazard was refactored away
      but the pragma survived — delete it so annotations keep meaning
      something (suppressions must not rot into blanket noise).
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from typing import Dict, List, Optional, Set

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass

DEFAULT_ROOTS = ("runtime", "serving.py", "paged", "spec", "obs",
                 "serving_autopilot.py")

_SYNC_CALLS = {("np", "asarray"), ("np", "array"), ("numpy", "asarray"),
               ("numpy", "array"), ("jax", "device_get")}
_DEVICE_MODULES = {"jnp", "lax"}

# structured-control-flow primitives whose function arguments trace as
# DEVICE loop bodies (argument index of each body-like callable)
_DEVICE_LOOP_FNS = {"while_loop": (0, 1), "fori_loop": (2,), "scan": (0,)}
_HOST_MODULES = {"np", "numpy"}
_HOST_CALLBACKS = {"pure_callback", "io_callback", "device_get"}


def default_src_paths() -> List[str]:
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(base, p) for p in DEFAULT_ROOTS]


def _dotted(node: ast.AST) -> Optional[tuple]:
    """('np', 'asarray') for np.asarray, ('jnp', 'sum') for jnp.sum."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _jitted_names(tree: ast.Module) -> Set[str]:
    """Function names wrapped by jax.jit in this module: decorated
    defs and `jax.jit(step)` call sites naming a local function."""
    jitted: Set[str] = set()

    def is_jit(expr: ast.AST) -> bool:
        d = _dotted(expr)
        if d and d[-1] == "jit":
            return True
        if isinstance(expr, ast.Call):
            # partial(jax.jit, ...) / jax.jit(fn, static_argnums=...)
            if is_jit(expr.func):
                return True
            return any(is_jit(a) for a in expr.args)
        return False

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit(dec) for dec in node.decorator_list):
                jitted.add(node.name)
        elif isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and d[-1] == "jit" and node.args \
                    and isinstance(node.args[0], ast.Name):
                jitted.add(node.args[0].id)
    return jitted


def _is_directive(txt: str) -> bool:
    if "fflint:" not in txt:
        return False
    # only the exact directives suppress — a comment like
    # '# fflint: broken, fix this' must NOT count
    directive = txt.split("fflint:", 1)[1].strip()
    return directive.startswith("host-ok") or directive.startswith("ignore")


def _comment_map(src: str) -> Dict[int, str]:
    """lineno -> COMMENT token text. Directives must live in actual
    comments: a docstring that merely *documents* the
    '# fflint: host-ok' convention is neither a suppression nor a stale
    pragma."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass  # ast.parse already succeeded; a tokenizer hiccup only
        # costs pragma visibility, never findings
    return out


def _suppressed(comments: Dict[int, str], *linenos: int) -> Optional[int]:
    """The line number of the directive that suppresses a finding on any
    of `linenos` (the flagged line or its enclosing loop headers), or
    None. Returning the LINE lets the caller track which pragmas earned
    their keep — unused ones are flagged stale."""
    for ln in linenos:
        if _is_directive(comments.get(ln, "")):
            return ln
    return None


class _FnScanner(ast.NodeVisitor):
    """Scan ONE function body (nested defs get their own scanner)."""

    def __init__(self, findings, rel, comments, fn_name, jitted,
                 used_pragmas: Optional[Set[int]] = None):
        self.findings = findings
        self.rel = rel
        self.comments = comments
        self.fn_name = fn_name
        self.jitted = fn_name in jitted
        self.loop_stack: List[int] = []  # header linenos
        self.used_pragmas = used_pragmas if used_pragmas is not None \
            else set()

    def _add(self, severity, code, lineno, msg):
        used = _suppressed(self.comments, lineno, *self.loop_stack)
        if used is not None:
            self.used_pragmas.add(used)
            return
        self.findings.append(Finding(
            "hostsync", severity, code, f"{self.rel}:{lineno}",
            f"in {self.fn_name}(): {msg}"))

    # nested function definitions are separate scopes — do not inherit
    # the enclosing loop stack (a closure defined in a loop runs later)
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def _loop(self, node):
        self.loop_stack.append(node.lineno)
        self.generic_visit(node)
        self.loop_stack.pop()

    visit_For = visit_While = _loop

    def _test_touches_shape(self, test: ast.AST) -> bool:
        return any(isinstance(n, ast.Attribute)
                   and n.attr in ("shape", "ndim")
                   for n in ast.walk(test))

    def visit_If(self, node):
        if self.jitted and self._test_touches_shape(node.test):
            self._add(
                "warning", "shape-branch-in-jit", node.lineno,
                "branch on .shape/.ndim inside a jitted function — the "
                "branch re-traces per shape class; hoist the decision "
                "out of the jitted fn or make it a static_argnum")
        self.generic_visit(node)

    def visit_While(self, node):
        if self.jitted and self._test_touches_shape(node.test):
            self._add(
                "warning", "shape-branch-in-jit", node.lineno,
                "while on .shape/.ndim inside a jitted function")
        self._loop(node)

    def visit_Call(self, node):
        in_loop = bool(self.loop_stack)
        d = _dotted(node.func)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args and in_loop:
            self._add(
                "error", "item-sync-in-loop", node.lineno,
                ".item() inside a loop is a per-element device sync — in "
                "a decode hot loop it serializes host and device every "
                "token; read the whole batch once with np.asarray "
                "outside the loop (annotate '# fflint: host-ok' if this "
                "loop is genuinely not per-token)")
        elif d and in_loop and not self.jitted:
            if d[:2] in _SYNC_CALLS:
                self._add(
                    "info", "asarray-in-loop", node.lineno,
                    f"{'.'.join(d)} inside a loop — one bulk device sync "
                    "per iteration (fine per decode tick, a hazard per "
                    "token)")
            elif d[0] in _DEVICE_MODULES or d[:2] == ("jax", "numpy"):
                self._add(
                    "warning", "jnp-in-host-loop", node.lineno,
                    f"{'.'.join(d)} inside a host-side loop dispatches "
                    "to the device each iteration — batch it, move the "
                    "loop into jit/scan, or annotate '# fflint: host-ok' "
                    "for a deliberate per-tick transfer")
        self.generic_visit(node)


class _DeviceLoopScanner(ast.NodeVisitor):
    """Scan one lax.while_loop/fori_loop/scan body for host syncs. No
    pragma suppression: a sync inside a traced device loop is never an
    intentional per-tick transfer — it is a bug (trace failure or a
    host re-entry mid-loop), the exact property the decode megastep's
    inner loop is built to prove away."""

    def __init__(self, findings, rel, kind, body_name):
        self.findings = findings
        self.rel = rel
        self.where = f"{kind} body {body_name!r}"

    def _add(self, lineno, msg):
        self.findings.append(Finding(
            "hostsync", "error", "device-loop", f"{self.rel}:{lineno}",
            f"in {self.where}: {msg}"))

    # a def nested inside a loop body still traces as part of it when
    # called there — v1 stays direct-body like the rest of the pass, so
    # nested defs are skipped (documented non-transitivity)
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        d = _dotted(node.func)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            self._add(node.lineno,
                      ".item() — a per-element device sync cannot trace "
                      "inside a device loop body")
        elif d and d[0] in _HOST_MODULES:
            self._add(node.lineno,
                      f"{'.'.join(d)} — numpy executes on host at trace "
                      "time; inside a device loop it fails on tracers or "
                      "bakes a stale constant")
        elif d and d[-1] in _HOST_CALLBACKS and d[0] == "jax":
            self._add(node.lineno,
                      f"{'.'.join(d)} — a host round-trip inside the "
                      "device loop defeats the fused dispatch")
        self.generic_visit(node)


def _device_loop_scan(tree: ast.Module, rel: str, findings: List[Finding],
                      bodies: Optional[List[Dict]] = None) -> None:
    """Find every lax.while_loop/fori_loop/scan call site, resolve its
    body-like arguments (local function names or inline lambdas), and
    scan each body for host syncs. `bodies` collects what was analyzed
    (for device_loop_bodies / gate tests)."""
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if not d or d[-1] not in _DEVICE_LOOP_FNS or "lax" not in d:
            continue
        kind = d[-1]
        for idx in _DEVICE_LOOP_FNS[kind]:
            if idx >= len(node.args):
                continue
            arg = node.args[idx]
            targets = []
            if isinstance(arg, ast.Name):
                # same-name defs elsewhere in the module are scanned
                # too — an over-approximation a lint can afford
                targets = [(arg.id, fn) for fn in defs.get(arg.id, ())]
            elif isinstance(arg, ast.Lambda):
                targets = [("<lambda>", arg)]
            for name, fn in targets:
                if bodies is not None:
                    bodies.append({"kind": kind, "body": name,
                                   "line": node.lineno})
                scanner = _DeviceLoopScanner(findings, rel, kind, name)
                body = fn.body if isinstance(fn.body, list) else [fn.body]
                for child in body:
                    scanner.visit(child)


def device_loop_bodies(path: str) -> List[Dict]:
    """The device-loop bodies the `device-loop` rule analyzed in `path`
    ({kind, body, line} per body). A gate test pairs this with
    scan_file: zero device-loop findings only proves something when at
    least one body was actually seen."""
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    bodies: List[Dict] = []
    _device_loop_scan(tree, os.path.basename(path), [], bodies)
    return bodies


def scan_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    rel = rel or os.path.basename(path)
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("hostsync", "error", "syntax-error",
                        f"{rel}:{e.lineno}", str(e))]
    comments = _comment_map(src)
    jitted = _jitted_names(tree)
    findings: List[Finding] = []
    used_pragmas: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scanner = _FnScanner(findings, rel, comments, node.name,
                                 jitted, used_pragmas)
            for child in node.body:
                scanner.visit(child)
    _device_loop_scan(tree, rel, findings)
    # suppression hygiene: a directive that silenced nothing is stale —
    # the hazard it annotated was refactored away and the annotation must
    # not survive to blanket-silence a future real finding
    for ln, txt in sorted(comments.items()):
        if _is_directive(txt) and ln not in used_pragmas:
            findings.append(Finding(
                "hostsync", "info", "stale-pragma", f"{rel}:{ln}",
                "'# fflint: host-ok' pragma no longer suppresses any "
                "finding — delete it (stale annotations rot into blanket "
                "noise)"))
    findings.sort(key=lambda f: f.where)
    return findings


def scan_paths(paths: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        rel = os.path.relpath(
                            full, os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
                        findings += scan_file(full, rel)
        elif os.path.exists(p):
            findings += scan_file(p, os.path.basename(p))
    return findings


@register_pass("hostsync")
def hostsync_pass(ctx: AnalysisContext) -> List[Finding]:
    paths = ctx.src_paths if ctx.src_paths is not None else default_src_paths()
    return scan_paths(paths)
