"""num_budgets — the declarative numerics budget catalog.

Every allowed error band in the tree gets a NAME here, with its value,
what kind of bound it is, and who consumes it. The catalog is the
single source of truth the low-precision work must extend rather than
invent: tests import their tolerances from it (a band change is a
reviewed diff of THIS file, not a drive-by constant edit), the
`kv_quant_canary` watchdog reads its alert threshold from it
(paged/scheduler.py), and the numcheck pass validates the catalog's
own hygiene (positive finite values, known kinds, required entries
present) so a deleted band fails fflint before it fails a test.

Kinds:
  abs          absolute bound on a max-abs delta (same units as data)
  rel          relative bound (rtol against a reference magnitude)
  scale_steps  bound expressed in multiples of a quantization grid
               step — the consumer multiplies by the relevant scale
  ratio        dimensionless floor/ceiling on a measured ratio

Pure data: no jax import, so the catalog is readable from the search
pricer, the analysis passes, and a bare checkout alike.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Tuple

KINDS = ("abs", "rel", "scale_steps", "ratio")


@dataclasses.dataclass(frozen=True)
class Budget:
    """One named error band. `consumers` names the code/tests that
    enforce it, so a band with no consumer is visibly dead weight."""

    value: float
    kind: str
    consumers: Tuple[str, ...]
    description: str

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


BUDGETS: Dict[str, Budget] = {
    # -- int8 KV pages (paged/quant.py) --------------------------------
    "int8-kv-roundtrip": Budget(
        0.51, "scale_steps",
        ("tests/test_quantized_kv.py::test_quantized_append_grow_only"
         "_roundtrip",),
        "one quantize/dequantize round-trip through the symmetric int8 "
        "grid lands within half a grid step (0.5 rounding + float "
        "slack) of the fp source; a row that survives a grow pays one "
        "trip per grid it crossed"),
    "int8-kv-commit-regrow": Budget(
        1.02, "scale_steps",
        ("tests/test_quantized_kv.py::test_scale_aware_commit_copies"
         "_across_scales",),
        "the scale-aware spec-commit row copy re-snaps existing rows to "
        "the grown destination grid: up to two half-step round-trips "
        "(source grid then destination grid) per element"),
    "int8-kv-mixed-batch": Budget(
        0.05, "abs",
        ("tests/test_quantized_kv.py::test_mixed_ragged_batch_quantized"
         "_tolerance",),
        "max abs attention-output delta of an int8 pool vs the fp32 "
        "pool on the mixed decode/chunk/tree ragged batch, on BOTH "
        "attention paths (Pallas dequant-on-load and the gather "
        "fallback) — the end-to-end bound the per-row round-trip "
        "budgets compose into"),
    "kv-canary-shadow-delta": Budget(
        1e-2, "abs",
        ("paged/scheduler.py kv_quant_canary watchdog",
         "tests/test_quantized_kv.py::test_greedy_int8_server_within"
         "_tolerance"),
        "max abs output-probability delta between the live quantized "
        "pool and the fp32 shadow cache (kv_quant_error gauge); the "
        "canary counts a breach and logs when the gauge crosses it "
        "(measured ~1e-4 on the reference config)"),
    "int8-weight-grid": Budget(
        0.5, "scale_steps",
        ("tests/test_quantized_kv.py::test_init_params_int8_fake_quant"
         "_snaps_to_grid",),
        "int8 weight fake-quantization (quantize_leaf) snaps every "
        "element within half a grid step of the fp draw, before the "
        "bf16 storage round-off term the test adds on top"),
    # -- speculative decode over quantized pools -----------------------
    "spec-acceptance-floor": Budget(
        1.5, "ratio",
        ("tests/test_quantized_kv.py::test_spec_acceptance_floor_on"
         "_quantized_pool",),
        "accepted tokens per verify step on the token-cyclic fixture "
        "must stay at or above this floor on an int8 pool — quantized "
        "verify must not reject a drafter that predicts the stream"),
    # -- HF importer parity (tools/hf_import) --------------------------
    "hf-import-parity-atol": Budget(
        0.05, "abs",
        ("tests/test_hf_import.py",),
        "absolute logit tolerance for a checkpoint imported from the "
        "HF layout vs the reference forward (paired with "
        "hf-import-parity-rtol)"),
    "hf-import-parity-rtol": Budget(
        0.25, "rel",
        ("tests/test_hf_import.py",),
        "relative logit tolerance for the HF-importer parity check "
        "(wide by design: tiny random models amplify rounding in "
        "near-zero logits)"),
}

# Bands the serving stack dereferences at runtime — numcheck's budget
# arm errors if one goes missing, so a catalog edit cannot silently
# strand the canary or the KV tolerance tests.
REQUIRED_BUDGETS = (
    "int8-kv-mixed-batch",
    "kv-canary-shadow-delta",
    "int8-kv-roundtrip",
)


def budget(name: str) -> Budget:
    """The named budget; raises KeyError with the catalog listing so a
    typo'd or deleted band fails loudly at the consumer."""
    try:
        return BUDGETS[name]
    except KeyError:
        raise KeyError(
            f"no numerics budget named {name!r}; catalog: "
            f"{sorted(BUDGETS)}") from None


def tolerance(name: str) -> float:
    """Shorthand for budget(name).value — what test asserts and the
    canary threshold read."""
    return budget(name).value


def validate_catalog() -> Dict[str, str]:
    """{budget_name: problem} for malformed entries (non-positive or
    non-finite value, unknown kind, missing description/consumers) plus
    '<missing>' entries for absent REQUIRED_BUDGETS. Empty when the
    catalog is healthy — numcheck's budget arm turns each problem into
    a finding."""
    problems: Dict[str, str] = {}
    for name, b in BUDGETS.items():
        if not isinstance(b.value, (int, float)) or not \
                math.isfinite(float(b.value)) or float(b.value) <= 0.0:
            problems[name] = f"value {b.value!r} must be finite and > 0"
        elif b.kind not in KINDS:
            problems[name] = (f"kind {b.kind!r} not in {KINDS}")
        elif not b.consumers:
            problems[name] = "no consumers named (dead band)"
        elif not b.description.strip():
            problems[name] = "empty description"
    for name in REQUIRED_BUDGETS:
        if name not in BUDGETS:
            problems[name] = ("<missing> — required by the serving "
                              "stack (REQUIRED_BUDGETS)")
    return problems
