"""numcheck — dtype-flow & scale-discipline static analysis, the
low-precision gate.

The lowered program is ground truth for numerics the same way it is for
collectives (hloaudit): a silent weak-type promotion or an unspecified
matmul accumulation dtype turns "int8 serving" into fp32 compute with
extra casts, and a quantized payload read that skips the scale sidecar
is garbage that still type-checks. Three arms:

  1. AST dtype-flow arm over the serving/compute hot paths (`paged/`,
     `spec/`, `runtime/executor.py`, `ops/`, `disagg/`): a dataflow
     lattice tracks array dtype provenance from creation sites
     (`.astype(jnp.int8)`, `jnp.zeros(..., dtype=int8)`,
     `quantize_leaf`, the pool's int8 payload) through assignments and
     calls, intra-function and deliberately OPTIMISTIC at unknowns
     (params, attributes, unrecognized calls are clean) — the same
     low-noise contract as shapecheck's taint arm.

  dtype-silent-promotion (error)   a low-precision payload (int8) or a
      forced f64 value meets float arithmetic / a float compute op with
      no explicit dequant or astype on the path. The finding carries
      the full derivation chain line by line (shapecheck's taint-chain
      idiom): int8 payload times a float is scale-less garbage; f64
      infects everything downstream at 2x HBM.
  scale-unpaired-access (error)    a `"k"`/`"v"` quantized payload read
      in a function that never touches the paired `k_scale`/`v_scale`
      sidecar — extends poolcheck's scale-sidecar invariant from page
      MOVEMENT to COMPUTE sites (metadata reads like `["k"].dtype` are
      exempt; mapping over every caches leaf counts as touching the
      sidecar by construction).
  dtype-accum-unspecified (warning) `dot`/`einsum`/`matmul` on operands
      known to be sub-fp32 (bf16/f16/fp8 provenance) without an
      explicit `preferred_element_type` — XLA may accumulate in the
      operand dtype and the error compounds over the contraction.
  dtype-cast-in-loop (info)        an `.astype(...)` inside a host
      `for`/`while` body — per-iteration casts are HBM traffic a hoist
      usually removes (observability only).
  stale-pragma (info)              a '# fflint: dtype-ok' pragma that
      no longer suppresses anything.

  Suppression: `# fflint: dtype-ok (reason)` on the flagged line or its
  enclosing loop header; the shared `# fflint: ignore` also applies.

  2. HLO numerics arm (runs when the CLI pairs numcheck with hloaudit:
     `--passes numcheck,hloaudit`): reuses hloaudit's lowering driver —
     each entry point's optimized HLO is scanned for `convert` ops and
     dot accumulation dtypes and diffed against the DECLARED per-entry
     dtype plan the Executor exports (`Executor.dtype_plan()`):

  hlo-unexpected-f64 (error)       f64 appears in a module whose plan
      forbids it (every plan does) — a weak-type promotion or stray
      np.float64 doubled the bytes of everything it touched.
  hlo-accum-downgrade (error)      a dot accumulates NARROWER than the
      plan's accumulation dtype — the mixed-precision win stopped
      being real.
  hlo-unplanned-convert (warning)  convert traffic touching a float
      dtype outside the entry's declared dtype set, above the count
      band — casts the plan never budgeted.

  3. Tolerance-budget arm: validates the declarative numerics budget
     catalog (analysis/num_budgets.py) — every band positive/finite
     with a known kind and named consumers, required serving bands
     present (budget-invalid / budget-missing errors). The catalog is
     what the tests and the kv_quant_canary watchdog read, so numcheck
     failing here means a tolerance was edited out from under its
     consumers.

`dtype_flow_sites(path)` inventories the payload-read / accumulation /
cast sites the scan actually saw, so a gate test can prove a clean scan
engaged the hot paths (a clean scan of zero sites proves nothing).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, List, Optional, Sequence, Set, Tuple

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass

# The hot-path roots the AST arm audits, relative to the flexflow_tpu
# package root.
DEFAULT_SUBJECTS = ("paged", "spec", "runtime/executor.py", "ops",
                    "disagg")

# taint tags, widest-contamination first (join picks the worst)
_TAGS = ("f64", "int8", "lowfp")

_INT8_NAMES = {"int8", "i8"}
_LOWFP_NAMES = {"bfloat16", "bf16", "float16", "fp16", "half",
                "float8_e4m3fn", "float8_e5m2", "fp8"}
_F64_NAMES = {"float64", "f64", "double"}

# calls whose result is contraction/float compute: an int8 or f64
# operand reaching one of these is the promotion sink
_ACCUM_OPS = {"dot", "matmul", "einsum", "dot_general", "batch_matmul"}
_FLOAT_OPS = _ACCUM_OPS | {"softmax", "_dot_product_attention",
                           "dot_product_attention"}

# element-wise/structural calls that PROPAGATE their operand's taint
_PROPAGATE_CALLS = {"clip", "round", "abs", "negative", "where",
                    "maximum", "minimum", "reshape", "transpose",
                    "broadcast_to", "asarray", "squeeze",
                    "expand_dims", "concatenate", "stack"}

# creation calls that accept a dtype= (positional trailing or kw)
_CREATION_CALLS = {"zeros", "ones", "full", "empty", "array", "asarray",
                   "zeros_like", "ones_like", "full_like", "empty_like"}

# attribute reads that are METADATA, not payload (exempt from the
# scale-pairing rule: `bufs["k"].dtype` reads no quantized bytes)
_METADATA_ATTRS = {"dtype", "shape", "ndim", "size", "nbytes",
                   "itemsize", "sharding", "weak_type"}


def default_src_paths() -> List[str]:
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(base, p) for p in DEFAULT_SUBJECTS]


# ---------------------------------------------------------------------------
# pragma machinery (hostsync/shapecheck idiom)


def _dotted(node: ast.AST) -> Optional[tuple]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _short(node: ast.AST, limit: int = 48) -> str:
    try:
        txt = ast.unparse(node)
    except Exception:
        txt = type(node).__name__
    return txt if len(txt) <= limit else txt[:limit - 3] + "..."


def _is_directive(txt: str) -> bool:
    if "fflint:" not in txt:
        return False
    directive = txt.split("fflint:", 1)[1].strip()
    return directive.startswith("dtype-ok") or directive.startswith("ignore")


def _is_own_directive(txt: str) -> bool:
    """Only dtype-ok pragmas are OURS to flag stale — a shared
    '# fflint: ignore' may be earning its keep for another pass."""
    if "fflint:" not in txt:
        return False
    return txt.split("fflint:", 1)[1].strip().startswith("dtype-ok")


def _comment_map(src: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass  # ast.parse already succeeded; a tokenizer hiccup only
        # costs pragma visibility, never findings
    return out


def _suppressed(comments: Dict[int, str], *linenos: int) -> Optional[int]:
    for ln in linenos:
        if _is_directive(comments.get(ln, "")):
            return ln
    return None


# ---------------------------------------------------------------------------
# AST dtype-flow arm


def _dtype_tag(node: ast.AST) -> Optional[str]:
    """The taint tag a dtype expression names: jnp.int8 / "int8" /
    np.float64 / jnp.bfloat16 ..., None for fp32/unknown."""
    name = None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    else:
        d = _dotted(node)
        if d:
            name = d[-1]
        elif isinstance(node, ast.Call):
            # jnp.dtype("int8") / np.dtype(np.float64)
            d = _dotted(node.func)
            if d and d[-1] == "dtype" and node.args:
                return _dtype_tag(node.args[0])
    if name in _INT8_NAMES:
        return "int8"
    if name in _LOWFP_NAMES:
        return "lowfp"
    if name in _F64_NAMES:
        return "f64"
    return None


def _join(*taints):
    """Worst tag wins; chains concatenate in argument order."""
    tag, chain = None, []
    for t in taints:
        if t is None:
            continue
        tt, tc = t
        chain = chain + list(tc)
        if tag is None or _TAGS.index(tt) < _TAGS.index(tag):
            tag = tt
    return (tag, chain) if tag is not None else None


class _DtypeScanner(ast.NodeVisitor):
    """Intra-function dtype-provenance dataflow. state maps a name to
    (tag, chain) where tag in {"int8", "lowfp", "f64"} and chain is
    [(lineno, description), ...] — the derivation the finding prints.
    OPTIMISTIC at unknowns: params, attributes and unrecognized calls
    are clean, so the errors are reserved for values that DEFINITELY
    carry low-precision/f64 provenance."""

    def __init__(self, findings, rel, comments, fn_name,
                 used_pragmas: Set[int], sites: Optional[List[Dict]] = None):
        self.findings = findings
        self.rel = rel
        self.comments = comments
        self.fn_name = fn_name
        self.loop_stack: List[int] = []
        self.used_pragmas = used_pragmas
        self.state: Dict[str, tuple] = {}
        self.sites = sites if sites is not None else []
        # creation sites already reported: one finding per derivation, not
        # one per downstream use (the chain replays the whole path anyway)
        self._reported: Set[tuple] = set()

    # -- classification ---------------------------------------------------

    def _classify(self, node: ast.AST):
        if isinstance(node, ast.Constant):
            return None
        if isinstance(node, ast.Name):
            return self.state.get(node.id)
        if isinstance(node, ast.Subscript):
            return self._classify(node.value)
        if isinstance(node, ast.Call):
            return self._classify_call(node)
        if isinstance(node, ast.BinOp):
            # sinks handled in visit_BinOp; propagation only here
            return _join(self._classify(node.left),
                         self._classify(node.right))
        if isinstance(node, ast.UnaryOp):
            return self._classify(node.operand)
        if isinstance(node, ast.IfExp):
            return _join(self._classify(node.body),
                         self._classify(node.orelse))
        if isinstance(node, ast.Tuple):
            return _join(*[self._classify(e) for e in node.elts])
        return None

    def _classify_call(self, node: ast.Call):
        d = _dotted(node.func)
        fname = d[-1] if d else None
        if fname == "astype" and isinstance(node.func, ast.Attribute):
            if node.args:
                tag = _dtype_tag(node.args[0])
                if tag is not None:
                    return (tag, [(node.lineno, _short(node))])
            # explicit cast to fp32/unknown: the dequant/astype the
            # promotion rule asks for — clears any taint
            return None
        if fname in ("set", "add", "max", "min", "mul", "get", "at"):
            # x.at[idx].set(v): the result is x's buffer (plus v)
            base = node.func
            while isinstance(base, (ast.Attribute, ast.Subscript,
                                    ast.Call)):
                base = getattr(base, "value", None) or \
                    getattr(base, "func", None)
                if base is None:
                    return None
            return _join(self._classify(base) if base is not None
                         else None,
                         *[self._classify(a) for a in node.args])
        if fname in _INT8_NAMES:
            return ("int8", [(node.lineno, _short(node))])
        if fname in _F64_NAMES:
            return ("f64", [(node.lineno, _short(node))])
        if fname in _LOWFP_NAMES or fname == "quantize_leaf":
            return ("lowfp", [(node.lineno, _short(node))])
        if fname == "dequantize_pages":
            return None  # scale-paired dequant: clean f32 by contract
        if fname == "quantized_append":
            return ("int8", [(node.lineno, _short(node))])
        if fname in _CREATION_CALLS:
            for kw in node.keywords:
                if kw.arg == "dtype":
                    tag = _dtype_tag(kw.value)
                    if tag is not None:
                        return (tag, [(node.lineno, _short(node))])
                    return None
            if node.args and fname.endswith("_like"):
                return self._classify(node.args[0])
            if len(node.args) >= 2 and not fname.endswith("_like"):
                tag = _dtype_tag(node.args[-1])
                if tag is not None:
                    return (tag, [(node.lineno, _short(node))])
            return None
        if fname in _PROPAGATE_CALLS:
            return _join(*[self._classify(a) for a in node.args])
        return None  # unknown call: optimistic

    # -- statement walking ------------------------------------------------

    def _assign_name(self, name: str, value: ast.AST, lineno: int):
        t = self._classify(value)
        if t is not None:
            tag, chain = t
            if not chain or chain[-1][0] != lineno:
                chain = list(chain) + [(lineno,
                                        f"{name} = {_short(value)}")]
            self.state[name] = (tag, chain)
        else:
            self.state.pop(name, None)

    def visit_Assign(self, node):
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self._assign_name(tgt.id, node.value, node.lineno)
            elif isinstance(tgt, ast.Tuple):
                if isinstance(node.value, ast.Tuple) \
                        and len(tgt.elts) == len(node.value.elts):
                    for t, v in zip(tgt.elts, node.value.elts):
                        if isinstance(t, ast.Name):
                            self._assign_name(t.id, v, node.lineno)
                else:
                    t = self._classify(node.value)
                    for el in tgt.elts:
                        if isinstance(el, ast.Name):
                            if t is not None:
                                self.state[el.id] = t
                            else:
                                self.state.pop(el.id, None)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            t = _join(self.state.get(node.target.id),
                      self._classify(node.value))
            if t is not None:
                self.state[node.target.id] = t
        self.generic_visit(node)

    # nested defs are separate scopes (same contract as shapecheck)
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def _loop(self, node):
        self.loop_stack.append(node.lineno)
        self.generic_visit(node)
        self.loop_stack.pop()

    visit_For = visit_While = _loop

    def _add(self, severity, code, lineno, msg) -> bool:
        used = _suppressed(self.comments, lineno, *self.loop_stack)
        if used is not None:
            self.used_pragmas.add(used)
            return False
        self.findings.append(Finding(
            "numcheck", severity, code, f"{self.rel}:{lineno}",
            f"in {self.fn_name}(): {msg}"))
        return True

    def _trace(self, chain, lineno, tail: str) -> str:
        steps = list(chain)
        if not steps or steps[-1][0] != lineno:
            steps = steps + [(lineno, tail)]
        return " -> ".join(f"line {ln}: {d}" for ln, d in steps)

    def _promotion(self, taint, lineno, context: str):
        tag, chain = taint
        key = (tag, chain[0] if chain else lineno)
        if key in self._reported:
            return
        if tag == "f64":
            emitted = self._add(
                "error", "dtype-silent-promotion", lineno,
                f"f64 value reaches {context} — a float64 creation "
                "silently promotes everything downstream to 2x-width "
                "compute and HBM traffic; cast to float32 at the "
                f"source. derivation: {self._trace(chain, lineno, context)}")
        else:
            emitted = self._add(
                "error", "dtype-silent-promotion", lineno,
                f"low-precision (int8) payload meets {context} with no "
                "explicit dequant/astype on the path — int8 codes "
                "entering float math without their scale are garbage "
                "that still type-checks; dequantize (dequantize_pages / "
                "astype through the scale) first. derivation: "
                f"{self._trace(chain, lineno, context)}")
        if emitted:
            self._reported.add(key)

    _FLOAT_BINOPS = (ast.Mult, ast.Add, ast.Sub, ast.Div, ast.Pow,
                     ast.MatMult)

    def visit_BinOp(self, node):
        if isinstance(node.op, self._FLOAT_BINOPS):
            lt = self._classify(node.left)
            rt = self._classify(node.right)
            for own, other, other_node in ((lt, rt, node.right),
                                           (rt, lt, node.left)):
                if own is None:
                    continue
                tag = own[0]
                if tag == "f64":
                    self._promotion(own, node.lineno,
                                    f"arithmetic ({_short(node)})")
                    break
                float_const = (isinstance(other_node, ast.Constant)
                               and isinstance(other_node.value, float))
                if tag == "int8" and (float_const or
                                      isinstance(node.op, ast.MatMult)
                                      or (other is not None
                                          and other[0] != "int8")):
                    self._promotion(own, node.lineno,
                                    f"float arithmetic ({_short(node)})")
                    break
        self.generic_visit(node)

    def visit_Call(self, node):
        d = _dotted(node.func)
        fname = d[-1] if d else None
        if fname == "astype" and self.loop_stack:
            self.sites.append({"scope": self.fn_name,
                               "line": node.lineno, "kind": "cast"})
            self._add(
                "info", "dtype-cast-in-loop", node.lineno,
                f"`{_short(node)}` runs every iteration of the loop at "
                f"line {self.loop_stack[-1]} — a per-iteration cast is "
                "HBM traffic; hoist it out of the loop if the operand "
                "is loop-invariant")
        if fname in _FLOAT_OPS:
            self.sites.append({"scope": self.fn_name,
                               "line": node.lineno, "kind": "accum-op"})
            arg_taints = [(a, self._classify(a)) for a in node.args]
            worst = _join(*[t for _, t in arg_taints])
            if worst is not None and worst[0] in ("int8", "f64"):
                self._promotion(worst, node.lineno, f"{fname}()")
            elif worst is not None and worst[0] == "lowfp" \
                    and fname in _ACCUM_OPS \
                    and not any(kw.arg == "preferred_element_type"
                                for kw in node.keywords):
                self._add(
                    "warning", "dtype-accum-unspecified", node.lineno,
                    f"{fname}() on sub-fp32 operands without "
                    "preferred_element_type — XLA may accumulate in "
                    "the operand dtype and the error compounds over "
                    "the contraction; pass preferred_element_type="
                    "jnp.float32 (the ragged Pallas kernel's "
                    "discipline). derivation: "
                    f"{self._trace(worst[1], node.lineno, fname + '()')}")
        self.generic_visit(node)


# -- scale-pairing (function-level, not dataflow) ---------------------------


def _scan_scale_pairing(fn: ast.AST, rel: str, fn_name: str, comments,
                        used_pragmas: Set[int],
                        sites: Optional[List[Dict]] = None) -> List[Finding]:
    """scale-unpaired-access: a Load of `X["k"]` / `X["v"]` (the caches
    payload convention) in a function with NO sidecar evidence — no
    "_scale" string, no scale-named identifier, no call into the
    scale-aware quant helpers. Metadata reads (`["k"].dtype`) are
    exempt; so are nested defs (scanned as their own functions)."""
    parent: Dict[ast.AST, ast.AST] = {}
    own_nodes: List[ast.AST] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            parent[child] = node
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue
            own_nodes.append(child)
            walk(child)

    walk(fn)

    evidence = False
    reads: List[Tuple[int, str]] = []
    for node in own_nodes:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if "_scale" in node.value:
                evidence = True
        elif isinstance(node, ast.Name) and "scale" in node.id.lower():
            evidence = True
        elif isinstance(node, ast.Attribute) and \
                "scale" in node.attr.lower():
            evidence = True
        elif isinstance(node, ast.arg) and "scale" in node.arg.lower():
            evidence = True
        elif isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and d[-1] in ("dequantize_pages", "quantized_append",
                               "scale_entry_names"):
                evidence = True
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.slice, ast.Constant) \
                and node.slice.value in ("k", "v"):
            par = parent.get(node)
            if isinstance(par, ast.Attribute) \
                    and par.attr in _METADATA_ATTRS:
                continue  # ["k"].dtype — metadata, no payload bytes
            reads.append((node.lineno, _short(node)))
            if sites is not None:
                sites.append({"scope": fn_name, "line": node.lineno,
                              "kind": "payload-read"})
    if evidence or not reads:
        return []
    findings: List[Finding] = []
    for lineno, txt in reads:
        used = _suppressed(comments, lineno)
        if used is not None:
            used_pragmas.add(used)
            continue
        findings.append(Finding(
            "numcheck", "error", "scale-unpaired-access",
            f"{rel}:{lineno}",
            f"in {fn_name}(): quantized payload read `{txt}` but this "
            "function never touches the k_scale/v_scale sidecar — on "
            "an int8 pool those codes are meaningless without their "
            "per-(page, head) scale (poolcheck guards the sidecar "
            "through page movement; compute sites must dequantize "
            "through it, or map over every caches leaf so the sidecar "
            "rides along)"))
    return findings


def dtype_flow_sites(path: str) -> List[Dict]:
    """The payload-read / accumulation-op / cast sites the scan saw in
    `path` ({scope, line, kind} per site) — the gate-test hook proving
    a clean scan actually engaged the hot paths."""
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    comments = _comment_map(src)
    sites: List[Dict] = []
    sink: List[Finding] = []
    used: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scanner = _DtypeScanner(sink, os.path.basename(path),
                                    comments, node.name, used,
                                    sites=sites)
            for child in node.body:
                scanner.visit(child)
            _scan_scale_pairing(node, os.path.basename(path), node.name,
                                comments, used, sites=sites)
    return sites


def scan_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    rel = rel or os.path.basename(path)
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("numcheck", "error", "syntax-error",
                        f"{rel}:{e.lineno}", str(e))]
    comments = _comment_map(src)
    findings: List[Finding] = []
    used_pragmas: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scanner = _DtypeScanner(findings, rel, comments, node.name,
                                    used_pragmas)
            for child in node.body:
                scanner.visit(child)
            findings += _scan_scale_pairing(node, rel, node.name,
                                            comments, used_pragmas)
    for ln, txt in sorted(comments.items()):
        if _is_own_directive(txt) and ln not in used_pragmas:
            findings.append(Finding(
                "numcheck", "info", "stale-pragma", f"{rel}:{ln}",
                "'# fflint: dtype-ok' pragma no longer suppresses any "
                "finding — delete it (stale annotations rot into "
                "blanket noise)"))
    findings.sort(key=lambda f: f.where)
    return findings


def scan_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        rel = os.path.relpath(
                            full, os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
                        findings += scan_file(full, rel)
        elif os.path.exists(p):
            findings += scan_file(p, os.path.basename(p))
    return findings


# ---------------------------------------------------------------------------
# HLO numerics arm (pairs with hloaudit's lowering driver)

# `%x = f32[8,16]{1,0} convert(bf16[8,16] %y)` — result dtype, operand
# dtype. Fusion bodies print the same instruction syntax, so converts
# inside fusions are counted line by line like hloaudit's transposes.
_CONVERT_RE = re.compile(
    r"%?[\w.\-]+ = (\w+)\[[^\]]*\]\S* convert\((\w+)\[")
# `%d = f32[...]{...} dot(...)` — the result dtype IS the accumulation
# dtype XLA committed to for this contraction
_DOT_RE = re.compile(r"%?[\w.\-]+ = (\w+)\[[^\]]*\]\S* dot\(")
_F64_RE = re.compile(r"\bf64\[")

_FLOAT_DTS = {"f64", "f32", "bf16", "f16", "f8e4m3fn", "f8e5m2"}
_DT_WIDTH = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1,
             "f8e5m2": 1, "s8": 1}


def extract_numerics(txt: str) -> Dict:
    """Numerics summary of one optimized HLO module: convert-op pairs
    {(src, dst): count}, dot accumulation dtypes {dtype: count}, and
    the count of f64-typed results."""
    converts: Dict[Tuple[str, str], int] = {}
    dots: Dict[str, int] = {}
    f64 = 0
    for line in txt.splitlines():
        s = line.strip()
        if _F64_RE.search(s):
            f64 += 1
        m = _CONVERT_RE.match(s)
        if m:
            pair = (m.group(2), m.group(1))
            converts[pair] = converts.get(pair, 0) + 1
            continue
        m = _DOT_RE.match(s)
        if m:
            dots[m.group(1)] = dots.get(m.group(1), 0) + 1
    return {"converts": converts, "dots": dots, "f64_lines": f64}


def diff_dtype_plan(subject: str, entry: str, plan: Dict,
                    numerics: Dict, convert_band: int = 0
                    ) -> List[Finding]:
    """Diff one entry point's observed HLO numerics against its
    declared dtype plan ({"compute", "accum", "kv", "allowed",
    "allow_f64"} — Executor.dtype_plan()). `convert_band` is the count
    of out-of-plan float converts tolerated per dtype pair before the
    band warning fires."""
    findings: List[Finding] = []
    where = f"{subject}:{entry}" if subject else entry
    allowed = set(plan.get("allowed", ()))
    if plan.get("allow_f64", False):
        # an explicit f64 allowance also budgets casts into/out of it
        allowed = allowed | {"f64"}
    accum = plan.get("accum", "f32")
    accum_w = _DT_WIDTH.get(accum, 4)

    if numerics.get("f64_lines", 0) and not plan.get("allow_f64", False):
        findings.append(Finding(
            "numcheck", "error", "hlo-unexpected-f64", where,
            f"{numerics['f64_lines']} f64-typed instruction(s) in the "
            f"lowered module but the dtype plan declares no f64 "
            f"(plan dtypes: {sorted(allowed) or '(none)'}) — a silent "
            "weak-type promotion (bare Python float / np.float64) is "
            "doubling compute and HBM bytes; pin the scalar's dtype at "
            "the source"))

    for dt, count in sorted(numerics.get("dots", {}).items()):
        if _DT_WIDTH.get(dt, 4) < accum_w:
            findings.append(Finding(
                "numcheck", "error", "hlo-accum-downgrade", where,
                f"{count} dot(s) accumulate at {dt}, narrower than the "
                f"plan's accumulation dtype {accum} — the contraction "
                "error compounds in the operand dtype; set "
                "preferred_element_type at the call site (witness: "
                f"dot result dtypes {numerics['dots']})"))

    unplanned = {pair: n for pair, n in
                 sorted(numerics.get("converts", {}).items())
                 if (pair[0] in _FLOAT_DTS or pair[1] in _FLOAT_DTS)
                 and not ({pair[0], pair[1]} & _FLOAT_DTS <= allowed)}
    for (src, dst), count in unplanned.items():
        if count > convert_band:
            findings.append(Finding(
                "numcheck", "warning", "hlo-unplanned-convert", where,
                f"{count} convert(s) {src} -> {dst} touch a float "
                f"dtype outside the entry's declared plan "
                f"{sorted(allowed)} (band: {convert_band}) — casts the "
                "plan never budgeted; either extend the Executor dtype "
                "plan or remove the stray cast"))
    return findings


# ---------------------------------------------------------------------------
# tolerance-budget arm


def budget_findings() -> List[Finding]:
    from flexflow_tpu.analysis.num_budgets import validate_catalog

    findings: List[Finding] = []
    for name, problem in sorted(validate_catalog().items()):
        code = ("budget-missing" if problem.startswith("<missing>")
                else "budget-invalid")
        findings.append(Finding(
            "numcheck", "error", code,
            f"analysis/num_budgets.py:{name}",
            f"numerics budget {name!r}: {problem} — the catalog is "
            "what the tolerance tests and the kv_quant_canary "
            "watchdog dereference; fix the band, do not orphan its "
            "consumers"))
    return findings


# ---------------------------------------------------------------------------
# registered pass


@register_pass("numcheck")
def numcheck_pass(ctx: AnalysisContext) -> List[Finding]:
    """Two modes, keyed on the context (pass-registry contract):

    - ctx.hlo_modules present (the CLI's `--passes numcheck,hloaudit`
      per-subject contexts): HLO numerics arm only — diff each entry's
      lowered module against ctx.numcheck_dtype_plan; skips silently
      when the plan is absent.
    - otherwise (default invocation): AST dtype-flow arm over
      ctx.src_paths (default: the hot-path roots) plus the
      tolerance-budget arm.
    """
    if ctx.hlo_modules is not None:
        plan = ctx.numcheck_dtype_plan
        if plan is None:
            return []
        band = (int(ctx.numcheck_convert_band)
                if ctx.numcheck_convert_band is not None else 0)
        findings: List[Finding] = []
        observed: Dict[str, Dict] = {}
        for entry, mod in sorted(ctx.hlo_modules.items()):
            if mod.get("error"):
                continue  # hloaudit already reports hlo-entry-failed
            eplan = plan.get(entry)
            if eplan is None:
                continue
            num = extract_numerics(mod["hlo_text"])
            findings += diff_dtype_plan(ctx.subject, entry, eplan, num,
                                        convert_band=band)
            observed[entry] = {
                "plan": eplan,
                "dots": dict(num["dots"]),
                "converts": {f"{s}->{d}": n for (s, d), n
                             in sorted(num["converts"].items())},
                "f64_lines": num["f64_lines"],
            }
        if ctx.numcheck_summary is None:
            ctx.numcheck_summary = {}
        ctx.numcheck_summary[ctx.subject or "module"] = observed
        return findings

    paths = (ctx.src_paths if ctx.src_paths is not None
             else default_src_paths())
    findings = scan_paths(paths)
    findings += budget_findings()
    from flexflow_tpu.analysis.num_budgets import BUDGETS

    inventory: Dict[str, int] = {"payload-read": 0, "accum-op": 0,
                                 "cast": 0}
    nfiles = 0
    for p in paths:
        files = []
        if os.path.isdir(p):
            for dirpath, _dirs, fns in os.walk(p):
                files += [os.path.join(dirpath, fn) for fn in fns
                          if fn.endswith(".py")]
        elif os.path.exists(p):
            files = [p]
        for f in files:
            nfiles += 1
            try:
                for s in dtype_flow_sites(f):
                    inventory[s["kind"]] = inventory.get(s["kind"], 0) + 1
            except SyntaxError:
                pass  # scan_file already reported it
    ctx.numcheck_summary = {
        "files_scanned": nfiles,
        "sites": inventory,
        "budgets": len(BUDGETS),
    }
    findings.sort(key=lambda f: (f.severity != "error", f.where))
    return findings
