"""Declarative invariant catalog for the paged serving state machine.

ONE catalog, consumed by three clients:

  * the poolcheck model checker (analysis/poolcheck.py) asserts every
    entry at every reachable state of its bounded exploration;
  * `PagePool.check_invariants()` (paged/pool.py) runs the pool-scope
    entries as a debug hook — the randomized op-sequence fuzz test in
    tests/test_paged.py calls it after every op;
  * docs/paged.md renders the catalog as the invariant table that
    replaced the old prose guarantees (each entry's name is the
    poolcheck finding code, `inv-<name>`).

Pool-scope entries take only the pool (plus an optional owners map);
op-scope entries (cow-write, defrag-preserve) are enforced by the model
checker AT THE MUTATING OPERATION, where the write/remap is visible —
they have no `check` function here, only the spec the checker implements.

This module is dependency-free on purpose: paged/pool.py imports it
lazily inside check_invariants(), and analysis/poolcheck.py imports it
eagerly, so neither direction creates an import cycle.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Invariant:
    """One catalog entry. `name` doubles as the poolcheck finding code
    suffix (`inv-<name>`); `scope` is where it can be evaluated:

      pool    — a function of the PagePool alone (check(pool));
      owners  — needs the live owner map {owner_id: [pages]} the
                scheduler/harness holds (check(pool, owners));
      rows    — needs the per-page committed-row counts only the model
                checker tracks (check(pool, committed));
      scales  — needs the quantized-pool scale-sidecar mirror the model
                checker tracks (check(pool, scale_of, content_tag));
      op      — only observable at the mutating operation itself; the
                model checker enforces it inline (check is None).
    """

    name: str
    scope: str
    description: str
    check: Optional[Callable] = None


# ---------------------------------------------------------------------------
# pool-scope checks (each returns a list of "name: detail" violations)


def _free_accounting(pool) -> List[str]:
    v = []
    free, lru, refs = set(pool._free), set(pool._lru), set(pool._refs)
    if len(pool._free) != len(free):
        v.append(f"free list holds duplicates: {sorted(pool._free)}")
    for a, b, la, lb in ((free, lru, "free", "lru"),
                         (free, refs, "free", "refs"),
                         (lru, refs, "lru", "refs")):
        both = a & b
        if both:
            v.append(f"pages {sorted(both)} are in both {la} and {lb}")
    everywhere = free | lru | refs
    if 0 in everywhere:
        v.append("null page 0 entered the allocator")
    bad = [p for p in everywhere if not 1 <= p < pool.num_pages]
    if bad:
        v.append(f"out-of-range page ids {sorted(bad)}")
    total = len(free) + len(lru) + len(refs)
    if total != pool.capacity:
        v.append(f"free({len(free)}) + cached({len(lru)}) + "
                 f"live({len(refs)}) = {total} != capacity "
                 f"{pool.capacity}")
    bad_refs = {p: r for p, r in pool._refs.items() if r < 1}
    if bad_refs:
        v.append(f"non-positive refcounts {bad_refs}")
    return [f"free-accounting: {m}" for m in v]


def _dead_list(pool) -> List[str]:
    v = []
    for p in pool._lru:
        if p in pool._refs:
            v.append(f"page {p} is dead-cached AND refcounted")
        if not pool._keys_of.get(p):
            v.append(f"page {p} is dead-cached but has no hash-index "
                     "entry (unhittable; it should be on the free list)")
    for p, keys in pool._keys_of.items():
        if keys and p not in pool._refs and p not in pool._lru:
            v.append(f"page {p} is hash-registered ({keys}) but neither "
                     "live nor dead-cached — a lookup would revive a "
                     "freed page")
    return [f"dead-list: {m}" for m in v]


def _index(pool) -> List[str]:
    v = []
    for h, p in pool._full.items():
        if ("full", h) not in pool._keys_of.get(p, []):
            v.append(f"full entry {h[:8]} -> {p} missing from the "
                     "inverse index")
    for h, (p, toks) in pool._partial.items():
        if ("partial", h) not in pool._keys_of.get(p, []):
            v.append(f"partial entry {h[:8]} -> {p} missing from the "
                     "inverse index")
        if not 0 < len(toks) < pool.page_size:
            v.append(f"partial entry {h[:8]} -> {p} has {len(toks)} "
                     f"tail tokens (must be in (0, page_size))")
    for p, keys in pool._keys_of.items():
        for kind, h in keys:
            if kind == "full" and pool._full.get(h) != p:
                v.append(f"inverse entry ('full', {h[:8]}) on page {p} "
                         f"points elsewhere ({pool._full.get(h)})")
            elif kind == "partial" and \
                    pool._partial.get(h, (None,))[0] != p:
                v.append(f"inverse entry ('partial', {h[:8]}) on page "
                         f"{p} points elsewhere")
    return [f"index: {m}" for m in v]


def _refcount_owners(pool, owners: Dict[object, Sequence[int]]
                     ) -> List[str]:
    held: Dict[int, int] = {}
    for pages in owners.values():
        for p in pages:
            held[p] = held.get(p, 0) + 1
    v = []
    for p in set(held) | set(pool._refs):
        if pool._refs.get(p, 0) != held.get(p, 0):
            v.append(f"page {p}: refcount {pool._refs.get(p, 0)} != "
                     f"{held.get(p, 0)} live owner-table references")
    return [f"refcount-owners: {m}" for m in v]


def _spec_scratch(pool, committed: Dict[int, int]) -> List[str]:
    """Published pages hold only COMMITTED K/V rows: a full entry
    implies every row committed; a partial entry implies at least its
    registered tail rows. Tree scratch (rows written past the committed
    head by speculative verify) must never reach the index."""
    v = []
    for h, p in pool._full.items():
        c = committed.get(p, 0)
        if c < pool.page_size:
            v.append(f"full-registered page {p} has only {c}/"
                     f"{pool.page_size} committed rows (scratch or "
                     "unwritten rows were published)")
    for h, (p, toks) in pool._partial.items():
        c = committed.get(p, 0)
        if c < len(toks):
            v.append(f"partial-registered page {p} names {len(toks)} "
                     f"tail rows but only {c} are committed")
    return [f"spec-scratch: {m}" for m in v]


def _scale_sidecar(pool, scale_of: Dict[int, int],
                   content_tag: Dict[int, int]) -> List[str]:
    """A quantized pool's scale sidecar must follow pages through every
    pool op. `content_tag` is the spec's ground truth — what the scale
    entry OUGHT to describe given the page's content history (stamped at
    every row write, copied by the COW clone, permuted by defrag, reset
    at allocation); `scale_of` mirrors what the implementation's sidecar
    actually holds. They must agree on every page whose content is
    reachable (live or dead-cached) — a page whose int8 payload is
    dequantized under another page's scale is silent corruption."""
    v = []
    for p in sorted(set(pool._refs) | set(pool._lru)):
        s, c = scale_of.get(p, 0), content_tag.get(p, 0)
        if s != c:
            v.append(f"page {p}: sidecar scale state {s} does not match "
                     f"its content state {c} (the scale entry was "
                     "dropped, leaked across a realloc, or left behind "
                     "by a page move)")
    return [f"scale-sidecar: {m}" for m in v]


def _tier_partition(pool) -> List[str]:
    """With a host tier attached (disagg/host_tier.py), every hash is in
    EXACTLY one place: resident (pool._full, owning a device page) or
    spilled (a tier entry holding the host payload) — never both, never
    neither-with-a-page. A hash resident AND spilled would let the two
    copies diverge (a COW writer re-registers, the stale spilled copy
    later fetches over it); a tier entry is by definition
    registered-but-NOT-resident."""
    tier = getattr(pool, "_tier", None)
    if tier is None:
        return []
    v = []
    spilled = set(tier.hashes())
    both = spilled & set(pool._full)
    if both:
        v.append(f"hashes {sorted(h[:8] for h in both)} are resident "
                 "AND spilled — the hash index is no longer a partition")
    if tier.occupancy_pages > tier.capacity_pages:
        v.append(f"tier holds {tier.occupancy_pages} entries over its "
                 f"capacity {tier.capacity_pages}")
    return [f"tier-partition: {m}" for m in v]


def _tier_scales(pool, tier_scale_of: Dict[str, int],
                 tier_content_tag: Dict[str, int]) -> List[str]:
    """Scales travel on spill and fetch: every spilled payload carries
    the scale-sidecar state its content was quantized under.
    `tier_content_tag` is the spec's ground truth (the content state the
    page had when it spilled); `tier_scale_of` mirrors the scale the
    implementation actually packed into the payload. A spilled page
    fetched under the wrong (or a zeroed) scale dequantizes to garbage
    on a different server — silent cross-worker corruption."""
    tier = getattr(pool, "_tier", None)
    if tier is None:
        return []
    v = []
    for h in tier.hashes():
        s = tier_scale_of.get(h, 0)
        c = tier_content_tag.get(h, 0)
        if s != c:
            v.append(f"spilled entry {h[:8]}: payload scale state {s} "
                     f"does not match its content state {c} (the scale "
                     "sidecar was dropped on spill or fetch)")
    return [f"tier-scales: {m}" for m in v]


CATALOG: Tuple[Invariant, ...] = (
    Invariant(
        "free-accounting", "pool",
        "free + dead-cached + live page counts sum to capacity; the "
        "three sets are disjoint, in range, and never contain the null "
        "page; refcounts are positive",
        _free_accounting),
    Invariant(
        "dead-list", "pool",
        "a page is on the LRU dead list iff its refcount is 0 AND it is "
        "hash-registered; every registered page is live or dead-cached, "
        "never free",
        _dead_list),
    Invariant(
        "index", "pool",
        "the full/partial hash indexes and the per-page inverse index "
        "(_keys_of) agree exactly; partial tails name 1..page_size-1 "
        "rows",
        _index),
    Invariant(
        "refcount-owners", "owners",
        "every page's refcount equals the number of live owner-table "
        "references to it (checked at operation boundaries)",
        _refcount_owners),
    Invariant(
        "spec-scratch", "rows",
        "pages named by the hash index hold only committed K/V rows — "
        "speculative tree scratch is never registered before its commit",
        _spec_scratch),
    Invariant(
        "scale-sidecar", "scales",
        "every reachable page's quantization-scale sidecar entry "
        "describes that page's current content: scales are reset with "
        "the page at allocation, copied by the COW clone, remapped by "
        "the defrag permutation, and kept by LRU revival — never "
        "dropped, leaked across a realloc, or left at a moved page's "
        "old slot",
        _scale_sidecar),
    Invariant(
        "tier-partition", "pool",
        "with a host tier attached, resident ⊎ spilled partitions the "
        "hash index: a tiered page is registered-but-not-resident (its "
        "hash is in the tier, not in _full), no hash is in both, and "
        "the tier never exceeds its capacity",
        _tier_partition),
    Invariant(
        "tier-scales", "tier-scales",
        "scales travel with their page through the host tier: every "
        "spilled payload carries the scale-sidecar state of the content "
        "it was read from, and a fetch restores both together",
        _tier_scales),
    Invariant(
        "cow-write", "op",
        "no row write lands in a page the writer does not own, a page "
        "with refcount != 1, or rows a hash-index entry has published "
        "(shared pages are written only via the COW clone helper)"),
    Invariant(
        "defrag-preserve", "op",
        "defrag returns a true permutation that fixes the null page and "
        "rewrites refcounts, LRU order, both hash indexes, and every "
        "owner's page list by the same old→new bijection"),
)


def by_name(name: str) -> Invariant:
    for entry in CATALOG:
        if entry.name == name:
            return entry
    raise KeyError(name)


def check_pool(pool, owners: Optional[Dict[object, Sequence[int]]] = None
               ) -> List[str]:
    """Run every pool-scope invariant (and refcount-owners when an
    owners map is given). Returns 'name: detail' violation strings."""
    v: List[str] = []
    for entry in CATALOG:
        if entry.scope == "pool":
            v += entry.check(pool)
        elif entry.scope == "owners" and owners is not None:
            v += entry.check(pool, owners)
    return v


def check_committed(pool, committed: Dict[int, int]) -> List[str]:
    """Run the committed-rows invariants (model checker / fuzz harness
    only — the live scheduler does not track per-page committed rows)."""
    v: List[str] = []
    for entry in CATALOG:
        if entry.scope == "rows":
            v += entry.check(pool, committed)
    return v


def check_scales(pool, scale_of: Dict[int, int],
                 content_tag: Dict[int, int]) -> List[str]:
    """Run the quantized-pool scale-sidecar invariants (model checker
    only — the live scheduler keeps the sidecar inside the caches dict,
    where the checker's mirror tracks it at op granularity)."""
    v: List[str] = []
    for entry in CATALOG:
        if entry.scope == "scales":
            v += entry.check(pool, scale_of, content_tag)
    return v


def check_tier_scales(pool, tier_scale_of: Dict[str, int],
                      tier_content_tag: Dict[str, int]) -> List[str]:
    """Run the host-tier scale-travel invariants over the attached
    tier's spilled entries (model checker only — the live tier stores
    scales inside its opaque payloads)."""
    v: List[str] = []
    for entry in CATALOG:
        if entry.scope == "tier-scales":
            v += entry.check(pool, tier_scale_of, tier_content_tag)
    return v
