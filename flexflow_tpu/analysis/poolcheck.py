"""poolcheck — explicit-state model checking + aliasing lints for the
paged serving state machine (the fifth fflint pass).

The prefix-cache PR made `PagePool` the correctness keystone of the
serving stack: refcounted content-addressed pages, COW tails, an LRU
dead list, leaf-first frees, and a defrag that rewrites every owner's
table. This pass checks that state machine two ways, both driven by the
declarative catalog in analysis/pool_invariants.py:

  MODEL CHECKER — BFS over every reachable configuration of a bounded
      serving scenario (≤3 requests, ≤8 pages, ≤2-page prompts, 2-token
      pages), driving the REAL PagePool through a harness that mirrors
      the scheduler's host-side bookkeeping ops: admission with prefix
      lookup + COW clone + the transient-shortfall rollback, chunked
      prefill with per-block publication, decode with page growth and
      preemption, leaf-first release with tail publication, defrag with
      the owner-table rewrite, and speculative verify/commit with tree
      scratch rows. Every invariant is asserted at every reached state;
      a violation is reported as an `inv-<name>` error finding carrying
      the MINIMAL counterexample trace (BFS order guarantees
      minimality), replayable via `replay()`.

  LINT ARM — an AST pass over serving.py, paged/, spec/ that flags
      write-after-share hazards:

  page-write-outside-cow        (error)   `.at[...].set/.add` on cache
      buffers in a host-side state-machine file (paged/scheduler.py,
      paged/pool.py, spec/server.py) outside the COW clone helper —
      in-place mutation of pool pages bypasses refcount discipline.
  table-write-outside-admission (error)   `self._tables` mutated
      outside the admission/defrag/release lifecycle methods.
  pool-private-access           (warning) `pool._x` underscore-state
      touched outside paged/pool.py — bookkeeping must go through the
      pool's methods or the invariants cannot be maintained.
  unlocked-cross-thread-read    (warning) in a thread-owning server
      class, a PUBLIC method reads a field the scheduler-loop thread
      mutates (or reads pool state) without holding `self._lock`.
      Intentional relaxed reads (metrics snapshots) are annotated
      `# fflint: lock-ok (reason)` on the line or its `def` line.
  stale-pragma                  (info)    a poolcheck directive
      (lock-ok / cow-ok / table-ok / pool-ok) that no longer
      suppresses anything.

CLI: tools/fflint.py runs poolcheck by default (tier-1 gates on it via
tests/test_analysis.py); `--since REV` runs the lint arm only. See
docs/analysis.md (pass, severities, pragmas) and docs/paged.md (the
invariant catalog this pass executes).
"""

from __future__ import annotations

import ast
import copy
import io
import json
import os
import tokenize
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass
from flexflow_tpu.analysis import pool_invariants as inv
from flexflow_tpu.paged.pool import EMPTY_HASH, PagePool

# ---------------------------------------------------------------------------
# model checker: a harness mirroring the scheduler's host-side bookkeeping


class _Req:
    """Model-side request: the subset of _GenRequest state the pool
    bookkeeping depends on."""

    __slots__ = ("prompt", "max_new", "tokens", "state", "pages", "pos",
                 "prefill_pos", "prefill_target", "hashed_blocks")

    def __init__(self, prompt: Tuple[int, ...], max_new: int):
        self.prompt = tuple(int(t) for t in prompt)
        self.max_new = int(max_new)
        self.tokens: List[int] = []
        self.state = "queued"  # queued | active | done
        self.pages: List[int] = []
        self.pos = 0
        self.prefill_pos = 0
        self.prefill_target = 0
        self.hashed_blocks = 0


# bounded scenarios (the ISSUE-9 bounds: ≤3 requests, ≤8 pages, ≤2-page
# prompts). Prompts are crafted to reach every sharing shape: identical
# prompts (page-aligned full-prompt hit → the COW clamp), a prompt
# extension (full-block share + partial-tail COW), and enough decode
# budget to cross page boundaries (decode-time publication + growth).
CONFIGS: Dict[str, Dict] = {
    "base": dict(num_pages=8, page_size=2, slots=2, spec_nodes=0,
                 prompts=((1, 2, 3), (1, 2, 3), (1, 2, 3, 4)),
                 max_new=(2, 1, 1)),
    "spec": dict(num_pages=8, page_size=2, slots=2, spec_nodes=2,
                 prompts=((1, 2, 3), (1, 2, 3)),
                 max_new=(2, 2)),
    # host-memory tier (disagg): a pool SMALL enough that admission
    # pressure must evict-and-spill, a tier small enough to exercise its
    # own capacity drops, and a shared prefix so fetches re-attach.
    # Gains ops: spill (proactive spill_oldest), fetch (prefetch of a
    # spilled hash), adopt (the prefill->decode handoff of a
    # prefill-complete request through the tier).
    "tiered": dict(num_pages=6, page_size=2, slots=2, spec_nodes=0,
                   prompts=((1, 2, 3), (1, 2, 3, 4)),
                   max_new=(2, 1), tier_pages=3),
}


class PoolModel:
    """Wraps a REAL PagePool and mirrors the scheduler's host-side ops
    (paged/scheduler.py, spec/server.py) at op granularity. Op-scope
    invariants (cow-write, defrag-preserve) are checked inline where the
    write/remap happens and accumulate in `self.violations`; state-scope
    invariants are evaluated by the checker after each op.

    `mutations` injects seeded defects for the fixture tests:
      cow_bypass          — admission maps a shared donor tail page in
                            place instead of COW-cloning it;
      scratch_preregister — speculative verify registers its tree
                            scratch page before the commit;
      scale_cow_drop      — the COW clone copies the page payload but
                            not its scale-sidecar entry;
      scale_realloc_leak  — allocation hands out a page without
                            resetting its previous tenant's scale;
      swap_free_skip      — drain-and-swap detaches live owners but
                            leaves their pages allocated in the adopted
                            pool (carried requests re-admit and the old
                            pages leak with no owner);
      scale_defrag_drop   — defrag permutes page payloads but leaves
                            the scale sidecar at the old slots;
      spill_scale_drop    — the spill payload carries the page content
                            but ZEROES its scale-sidecar state: a fetch
                            (possibly on another server) dequantizes
                            the int8 rows under the wrong scale.

    The quantized-pool scale sidecar is modeled as a pair of per-page
    tags: `content_tag` is the spec truth — a bounded
    writes-since-alloc counter (capped at page_size, so the state space
    stays finite) stamped at every row write, copied by COW, permuted
    by defrag, reset at alloc, kept by LRU revival; `scale_of` mirrors
    the ops the implementation's sidecar actually performs (the seeded
    mutations above each skip exactly one of them). The scale-sidecar
    invariant is scale_of == content_tag on every reachable page.
    """

    def __init__(self, pool_factory=None, *, num_pages: int,
                 page_size: int, slots: int, spec_nodes: int,
                 prompts, max_new, tier_pages: int = 0,
                 mutations: Tuple[str, ...] = ()):
        self.P = int(page_size)
        self.slots = int(slots)
        self.spec_nodes = int(spec_nodes)
        self.mutations = tuple(mutations)
        max_rows = max(len(p) + m for p, m in zip(prompts, max_new)) \
            + self.spec_nodes
        self.max_pages = -(-max_rows // self.P)
        factory = pool_factory or PagePool
        self.pool = factory(num_pages, page_size, self.max_pages)
        self.reqs = [_Req(p, m) for p, m in zip(prompts, max_new)]
        self.committed: Dict[int, int] = {}  # page -> committed K/V rows
        self.scale_of: Dict[int, int] = {}     # impl's sidecar mirror
        self.content_tag: Dict[int, int] = {}  # spec's content truth
        self.violations: List[str] = []
        self.tier = None
        if tier_pages:
            # drive the REAL spill/fetch code (pool._spill_page,
            # _fetch_full, spill_request, spill_oldest, prefetch) with
            # bookkeeping-mirror payloads instead of device buffers: a
            # payload is (content_tag, scale_of, committed) at read time
            from flexflow_tpu.disagg.host_tier import HostTier

            self.tier = HostTier(int(tier_pages))
            self.pool.attach_tier(self.tier, self._tier_read_model,
                                  self._tier_write_model)

    # -- host-tier payload mirrors (tiered config) -------------------------

    def _tier_read_model(self, page: int):
        scale = self.scale_of.get(page, 0)
        if "spill_scale_drop" in self.mutations:
            # SEEDED DEFECT: the spill packs the page's rows but not its
            # scale-sidecar entry — the payload lands in the tier with a
            # zeroed scale state and every fetch restores garbage
            scale = 0
        return (self.content_tag.get(page, 0), scale,
                self.committed.get(page, 0))

    def _tier_write_model(self, page: int, payload):
        content, scale, committed = payload
        self.content_tag[page] = content
        self.scale_of[page] = scale
        self.committed[page] = committed

    # -- bookkeeping helpers ----------------------------------------------

    def clone(self) -> "PoolModel":
        return copy.deepcopy(self)

    def owners(self) -> Dict[int, List[int]]:
        return {i: r.pages for i, r in enumerate(self.reqs)
                if r.state == "active"}

    def _seq(self, req: _Req) -> Tuple[int, ...]:
        return req.prompt + tuple(req.tokens)

    def _next_token(self, req: _Req) -> int:
        # deterministic greedy stand-in: a pure function of the prefix,
        # so identical prompts emit identical streams (maximal sharing —
        # the token-identity property the real servers assert)
        s = self._seq(req)
        return (sum(s) * 31 + len(s) * 7) % 5 + 10

    def _alloc(self, n: int) -> Optional[List[int]]:
        pages = self.pool.alloc(n)
        if pages is not None:
            for p in pages:
                self.committed[p] = 0  # fresh/recycled content is garbage
                self.content_tag[p] = 0
                if "scale_realloc_leak" not in self.mutations:
                    # mirrors scheduler._reset_page_scales at every
                    # allocation site; the mutation keeps the previous
                    # tenant's scale on the recycled page
                    self.scale_of[p] = 0
                else:
                    self.scale_of.setdefault(p, 0)
        return pages

    def _write_row(self, req: _Req, row: int, scratch: bool = False):
        """One K/V row write through the request's page list, with the
        cow-write discipline checked at the write itself."""
        idx = row // self.P
        if idx >= len(req.pages):
            self.violations.append(
                f"cow-write: row {row} written past the page list "
                f"({len(req.pages)} pages)")
            return
        page = req.pages[idx]
        rc = self.pool.refcount(page)
        if rc != 1:
            self.violations.append(
                f"cow-write: row {row} written into page {page} with "
                f"refcount {rc} (shared pages are cloned, never written "
                "in place)")
        for kind, h in self.pool._keys_of.get(page, []):
            if kind == "full":
                self.violations.append(
                    f"cow-write: row {row} written into full-registered "
                    f"page {page} (published rows are immutable)")
            else:
                ent = self.pool._partial.get(h)
                if ent and ent[0] == page and row % self.P < len(ent[1]):
                    self.violations.append(
                        f"cow-write: row {row} overwrites the published "
                        f"partial tail (rows [0, {len(ent[1])})) of page "
                        f"{page}")
        if not scratch:
            c = self.committed.get(page, 0)
            self.committed[page] = max(c, row % self.P + 1)
        # every row write (scratch included — verify rewrites draft K/V)
        # changes the page's content AND grows its quantization scale
        # atomically (quantized_append); the bounded counter keeps BFS
        # finite while still distinguishing stale from current scales
        self.content_tag[page] = min(self.P,
                                     self.content_tag.get(page, 0) + 1)
        self.scale_of[page] = min(self.P, self.scale_of.get(page, 0) + 1)

    # -- publication (mirrors _publish_prefix/_publish_tail) --------------

    def _publish_prefix(self, req: _Req, valid: int):
        P = self.P
        target = min(valid // P, len(req.pages))
        if req.hashed_blocks >= target:
            return
        seq = self._seq(req)
        chain = self.pool.chain_hashes(list(seq[:target * P]))
        for b in range(req.hashed_blocks, target):
            self.pool.register_full(req.pages[b], chain[b])
        req.hashed_blocks = target

    def _publish_tail(self, req: _Req):
        if not req.pages:
            return
        P = self.P
        valid = max(req.pos, req.prefill_pos)
        self._publish_prefix(req, valid)
        full = req.hashed_blocks
        tail = valid - full * P
        if tail > 0 and full < len(req.pages):
            seq = self._seq(req)
            chain = self.pool.chain_hashes(list(seq[:full * P]))
            parent = chain[-1] if chain else EMPTY_HASH
            self.pool.register_partial(req.pages[full], parent,
                                       list(seq[full * P:valid]))

    # -- ops ---------------------------------------------------------------

    def _admission_pages(self, req: _Req) -> int:
        # base: prompt + the first decode write row; spec: prompt + the
        # whole first verify tree (spec/server.py:_admission_pages)
        extra = self.spec_nodes if self.spec_nodes else 1
        need = min(len(self._seq(req)) + extra, self.max_pages * self.P)
        return self.pool.pages_for(need)

    def enabled_ops(self) -> List[str]:
        ops = []
        active = sum(1 for r in self.reqs if r.state == "active")
        for i, r in enumerate(self.reqs):
            if r.state == "queued" and active < self.slots \
                    and self._admission_pages(r) <= self.pool.free_pages:
                ops.append(f"admit({i})")
        for i, r in enumerate(self.reqs):
            if r.state == "active":
                ops.append(f"step({i})")
        for i, r in enumerate(self.reqs):
            if r.state == "active":
                ops.append(f"preempt({i})")
        if self.pool._refs or self.pool._lru:
            ops.append("defrag")
        if active:
            ops.append("swap")
        if self.tier is not None:
            if self.pool._lru:
                ops.append("spill")      # proactive spill_oldest
            if self.pool.free_pages >= 1:
                # prefetch always lands when a page is allocatable
                for j in range(len(self.tier.hashes())):
                    ops.append(f"fetch({j})")
            for i, r in enumerate(self.reqs):
                # the prefill->decode handoff fires at prefill
                # completion; post-prefill is when a request's pages
                # can leave through the tier
                if r.state == "active" \
                        and r.prefill_pos >= r.prefill_target:
                    ops.append(f"adopt({i})")
        return ops

    def apply(self, label: str):
        if label == "defrag":
            return self._op_defrag()
        if label == "swap":
            return self._op_swap()
        if label == "spill":
            return self._op_spill()
        op, rid = label[:-1].split("(")
        return getattr(self, "_op_" + op)(int(rid))

    def _op_admit(self, i: int):
        """Mirror of PagedGenerationServer._admit: prefix lookup, the
        last-prompt-token clamp, COW of the boundary page, private
        allocation of the suffix, and the transient-shortfall rollback."""
        req, pool, P = self.reqs[i], self.pool, self.P
        seq = self._seq(req)
        n = len(seq)
        shared, cached, cow = pool.lookup(list(seq))
        start = min(cached, n - 1)
        b0 = start // P
        keep = shared[:b0]
        cow_src = cow if cow is not None else (
            shared[b0] if b0 < len(shared) else None)
        total = pool.pages_for(n)
        fresh = self._alloc(total - b0)
        if fresh is None:
            # transient shortfall: drop the hits, retry as full recompute
            pool.free(keep + ([cow_src] if cow_src is not None else []))
            if cached > 0:
                pool.hit_tokens -= cached
                pool.hits -= 1
                pool.misses += 1
            shared, keep, cached, cow_src = [], [], 0, None
            start, b0 = 0, 0
            fresh = self._alloc(total)
            if fresh is None:
                return  # stays queued (the enabled gate was optimistic)
        if cached > start:
            pool.hit_tokens -= cached - start
        pages = keep + fresh
        req.pages = pages
        if cow_src is not None:
            if "cow_bypass" in self.mutations:
                # SEEDED DEFECT: map the shared donor page in place of
                # the private clone — writes past the shared rows now
                # mutate a page other owners (or the index) still name
                pool.free([pages[b0]])
                pages[b0] = cow_src
            else:
                # COW clone: rows below `start` carry over as committed;
                # copy_page tree-maps over EVERY cache leaf, so the
                # clone inherits the donor's content AND scale entry
                self.committed[pages[b0]] = max(0, start - b0 * P)
                self.content_tag[pages[b0]] = \
                    self.content_tag.get(cow_src, 0)
                if "scale_cow_drop" not in self.mutations:
                    self.scale_of[pages[b0]] = self.scale_of.get(cow_src, 0)
                pool.free([cow_src])
        req.prefill_pos = start
        req.prefill_target = n
        req.pos = 0
        req.hashed_blocks = min(b0, n // P)
        req.state = "active"

    def _op_step(self, i: int):
        req = self.reqs[i]
        if req.prefill_pos < req.prefill_target:
            self._prefill_chunk(req)
        else:
            self._decode(req)

    def _prefill_chunk(self, req: _Req):
        """One page-size chunk of chunked prefill, with per-block
        publication; the finishing chunk publishes the prompt tail and
        samples the first token (scheduler.py:_prefill_tick)."""
        n = req.prefill_target
        take = min(self.P, n - req.prefill_pos)
        for r in range(req.prefill_pos, req.prefill_pos + take):
            self._write_row(req, r)
        req.prefill_pos += take
        self._publish_prefix(req, req.prefill_pos)
        if req.prefill_pos >= n:
            self._publish_tail(req)
            tok = self._next_token(req)
            req.pos = n
            req.tokens.append(tok)
            self._finish_if_done(req)

    def _grow(self, req: _Req, target_pages: int) -> bool:
        """_ensure_pages for one request: grow to `target_pages`,
        preempting the youngest OTHER active request under pressure
        (or self when none — a stall, never a wrong answer)."""
        while len(req.pages) < target_pages:
            got = self._alloc(1)
            if got is not None:
                req.pages.append(got[0])
                continue
            others = [r for r in self.reqs
                      if r is not req and r.state == "active"]
            if others:
                self._do_preempt(others[-1])
            else:
                self._do_preempt(req)
                return False
        return True

    def _decode(self, req: _Req):
        rows = self.max_pages * self.P
        if self.spec_nodes:
            # speculative verify: grow to cover the whole tree, write
            # its scratch rows past the committed head, then commit
            target = self.pool.pages_for(min(req.pos + self.spec_nodes,
                                             rows))
            if not self._grow(req, target):
                return
            hi = min(req.pos + self.spec_nodes, rows)
            for r in range(req.pos, hi):
                self._write_row(req, r, scratch=True)
            if "scratch_preregister" in self.mutations and hi > req.pos:
                # SEEDED DEFECT: publish the drafted tree before the
                # commit — the page holding the tree's LAST scratch row
                # reaches the hash index while its rows are still
                # uncommitted draft K/V
                idx = (hi - 1) // self.P
                if idx < len(req.pages):
                    self.pool.register_full(
                        req.pages[idx], f"scratch:{self._seq(req)}")
            # commit the accepted path: scratch rows [pos, pos+L) become
            # committed K/V, pos advances, tokens append (greedy stand-in
            # accepts as deep a path as the budget allows)
            L = min(self.spec_nodes, req.max_new - len(req.tokens),
                    hi - req.pos)
            for r in range(req.pos, req.pos + L):
                page = req.pages[r // self.P]
                c = self.committed.get(page, 0)
                self.committed[page] = max(c, r % self.P + 1)
            for _ in range(L):
                req.tokens.append(self._next_token(req))
            req.pos += L
        else:
            if not self._grow(req, self.pool.pages_for(req.pos + 1)):
                return
            self._write_row(req, req.pos)
            req.pos += 1
            req.tokens.append(self._next_token(req))
        self._publish_prefix(req, req.pos)
        self._finish_if_done(req)

    def _finish_if_done(self, req: _Req):
        if len(req.tokens) >= req.max_new:
            self._publish_tail(req)
            self.pool.free(list(reversed(req.pages)))  # leaf-first
            req.pages = []
            req.state = "done"

    def _do_preempt(self, req: _Req):
        self._publish_tail(req)
        self.pool.free(list(reversed(req.pages)))  # leaf-first
        req.pages = []
        req.pos = 0
        req.prefill_pos = 0
        req.prefill_target = 0
        req.hashed_blocks = 0
        req.state = "queued"  # requeues; resume re-attaches via lookup

    def _op_preempt(self, i: int):
        self._do_preempt(self.reqs[i])

    def _op_spill(self):
        """Proactive pressure relief: PagePool.spill_oldest moves the
        LRU-oldest dead page's payload into the tier and frees it."""
        self.pool.spill_oldest()

    def _op_fetch(self, j: int):
        """PagePool.prefetch of the j-th spilled hash (sorted for a
        deterministic label): the payload lands in a fresh page, parked
        dead-cached for the next lookup."""
        hashes = sorted(self.tier.hashes())
        if j < len(hashes):
            self.pool.prefetch(hashes[j])

    def _op_adopt(self, i: int):
        """The prefill->decode handoff (disagg/workers.py
        PrefillWorker._on_prefill_complete): publish, spill the
        request's pages into the tier, free, and requeue with tokens
        intact — the later admit(i) re-attaches via lookup's
        transparent fetch, modeling the decode worker's admission
        (one pool plays both sides; the tier is the channel)."""
        req = self.reqs[i]
        self._publish_tail(req)
        self.pool.spill_request(req.pages)
        self.pool.free(list(reversed(req.pages)))  # leaf-first
        req.pages = []
        req.pos = 0
        req.prefill_pos = 0
        req.prefill_target = 0
        req.hashed_blocks = 0
        req.state = "queued"

    def _op_swap(self):
        """Strategy change in flight: mirror of the drain-and-swap
        handoff (scheduler._detach_active + the successor's
        adopt_pool_from/absorb_requests). Every live owner publishes
        its tail, releases its pages into the pool the successor
        adopts, and carries over as queued with its emitted tokens
        intact — re-admission re-attaches via prefix lookup, so the
        carried streams stay token-identical. Unlike preempt (one
        victim under page pressure) this detaches ALL actives
        atomically between ticks."""
        for req in self.reqs:
            if req.state != "active":
                continue
            self._publish_tail(req)
            if "swap_free_skip" in self.mutations:
                # SEEDED DEFECT: the detach hands the request to the
                # successor but never frees its pages — the adopted
                # pool keeps refcounts nobody owns, and the carried
                # request double-allocates on re-admission
                pass
            else:
                self.pool.free(list(reversed(req.pages)))  # leaf-first
            req.pages = []
            req.pos = 0
            req.prefill_pos = 0
            req.prefill_target = 0
            req.hashed_blocks = 0
            req.state = "queued"

    def _op_defrag(self):
        """pool.defrag() + the scheduler-side owner-table rewrite, with
        the defrag-preserve invariant checked against the pre-state."""
        pool = self.pool
        pre_refs = dict(pool._refs)
        pre_lru = list(pool._lru)
        pre_full = dict(pool._full)
        pre_partial = dict(pool._partial)
        allocated = set(pre_refs) | set(pre_lru)
        perm, old_to_new = pool.defrag()

        def m(p):
            return int(old_to_new[p])

        v = []
        if sorted(int(x) for x in perm) != list(range(pool.num_pages)):
            v.append("perm is not a permutation of the page ids")
        if m(0) != 0:
            v.append("the null page was remapped")
        if pool._refs != {m(p): r for p, r in pre_refs.items()}:
            v.append(f"refcounts not preserved: {pre_refs} -> "
                     f"{pool._refs} under {dict((p, m(p)) for p in pre_refs)}")
        if list(pool._lru) != [m(p) for p in pre_lru]:
            v.append("the LRU dead list (or its order) was not preserved")
        if pool._full != {h: m(p) for h, p in pre_full.items()}:
            v.append("the full-block hash index was not preserved")
        if pool._partial != {h: (m(p), t)
                             for h, (p, t) in pre_partial.items()}:
            v.append("the partial-tail hash index was not preserved")
        self.violations += [f"defrag-preserve: {s}" for s in v]
        for r in self.reqs:
            r.pages = [m(p) for p in r.pages]
        self.committed = {m(p): c for p, c in self.committed.items()
                          if p in allocated}
        self.content_tag = {m(p): t for p, t in self.content_tag.items()
                            if p in allocated}
        if "scale_defrag_drop" in self.mutations:
            # SEEDED DEFECT: the payload permutation ran but the scale
            # sidecar was left behind — page m(p)'s int8 rows now
            # dequantize under whatever scale sat at slot m(p) before
            self.scale_of = {p: t for p, t in self.scale_of.items()
                             if p in allocated}
        else:
            self.scale_of = {m(p): t for p, t in self.scale_of.items()
                             if p in allocated}

    # -- canonical state -------------------------------------------------

    def key(self) -> tuple:
        """Canonical serialization for BFS dedup. The free list is
        SORTED (a symmetry reduction: its order only selects which
        interchangeable page id the next alloc hands out); the LRU keeps
        its order (eviction order is semantic). Prefix-cache counters
        are excluded — they never influence a transition."""
        pool = self.pool
        reqs = tuple((r.state, tuple(r.tokens), tuple(r.pages), r.pos,
                      r.prefill_pos, r.prefill_target, r.hashed_blocks)
                     for r in self.reqs)
        keys_of = tuple(sorted((p, tuple(sorted(ks)))
                               for p, ks in pool._keys_of.items() if ks))
        live = set(pool._refs) | set(pool._lru)
        return (reqs,
                tuple(sorted(pool._free)),
                tuple(sorted(pool._refs.items())),
                tuple(pool._lru),
                tuple(sorted(pool._full.items())),
                tuple(sorted(pool._partial.items())),
                keys_of,
                tuple(sorted((p, c) for p, c in self.committed.items()
                             if p in live)),
                # stale entries on FREE pages are excluded: a correct
                # model resets them at the next alloc, so they never
                # influence a transition (the realloc-leak mutation is
                # caught at the alloc itself, before any dedup)
                tuple(sorted((p, t) for p, t in self.scale_of.items()
                             if p in live)),
                tuple(sorted((p, t) for p, t in self.content_tag.items()
                             if p in live)),
                # tier entries IN ORDER (its LRU eviction order is
                # semantic, like the pool's dead list)
                (tuple((h, self.tier.peek(h))
                       for h in self.tier.hashes())
                 if self.tier is not None else ()))


class CheckResult:
    """Outcome of one bounded exploration."""

    def __init__(self, config: str, explored: int, reached: int,
                 hits: List[Tuple[str, str, Tuple[str, ...]]],
                 truncated: bool):
        self.config = config
        self.explored = explored
        self.reached = reached
        self.hits = hits            # (invariant, detail, minimal trace)
        self.truncated = truncated


def _state_violations(state: PoolModel) -> List[str]:
    v = (list(state.violations)
         + inv.check_pool(state.pool, state.owners())
         + inv.check_committed(state.pool, state.committed)
         + inv.check_scales(state.pool, state.scale_of,
                            state.content_tag))
    if state.tier is not None:
        # unpack the mirror payloads: scales must have traveled
        tier_scale: Dict[str, int] = {}
        tier_content: Dict[str, int] = {}
        for h in state.tier.hashes():
            payload = state.tier.peek(h)
            if payload is not None:
                tier_content[h], tier_scale[h], _ = payload
        v += inv.check_tier_scales(state.pool, tier_scale, tier_content)
    return v


def model_check(config: str = "base", pool_factory=None,
                mutations: Tuple[str, ...] = (),
                max_states: int = 400_000,
                max_findings: int = 4) -> CheckResult:
    """BFS over every reachable state of the bounded scenario. The
    first state violating an invariant yields that invariant's MINIMAL
    counterexample (BFS explores by depth); violating states are not
    expanded further."""
    root = PoolModel(pool_factory=pool_factory,
                     mutations=tuple(mutations), **CONFIGS[config])
    seen: Set[tuple] = {root.key()}
    frontier: deque = deque([(root, ())])
    hits: List[Tuple[str, str, Tuple[str, ...]]] = []
    explored = 0
    while frontier and len(hits) < max_findings \
            and explored < max_states:
        state, trace = frontier.popleft()
        explored += 1
        for label in state.enabled_ops():
            child = state.clone()
            child.violations = []
            child.apply(label)
            ctrace = trace + (label,)
            found = _state_violations(child)
            if found:
                for msg in found:
                    name = msg.split(":", 1)[0]
                    if all(h[0] != name for h in hits):
                        hits.append((name, msg, ctrace))
                continue  # a broken state's successors prove nothing new
            k = child.key()
            if k not in seen:
                seen.add(k)
                frontier.append((child, ctrace))
    return CheckResult(config, explored, len(seen), hits,
                       truncated=bool(frontier) and explored >= max_states)


def replay(trace, config: str = "base", pool_factory=None,
           mutations: Tuple[str, ...] = ()) -> List[str]:
    """Re-execute a counterexample trace from the initial state and
    return every violation it produces (empty = does not reproduce)."""
    state = PoolModel(pool_factory=pool_factory,
                      mutations=tuple(mutations), **CONFIGS[config])
    out: List[str] = []
    for label in trace:
        state.violations = []
        state.apply(label)
        out += _state_violations(state)
    return out


# ---------------------------------------------------------------------------
# lint arm: AST checks over serving.py / paged/ / spec/

LINT_ROOTS = ("serving.py", "paged", "spec", "serving_autopilot.py",
              "disagg")
# the host-side state-machine files the page/table write checks cover
# (kernel files write K/V rows THROUGH the table by design)
_STATE_FILE_BASENAMES = {"scheduler.py", "pool.py", "server.py"}
_COW_FNS = {"copy_page",
            # alloc-time scale-sidecar zeroing: runs only on pages just
            # handed out by the allocator (exclusively owned, nothing
            # published), part of the allocation lifecycle like the
            # table writes in _admit/_ensure_pages
            "reset_page_scales",
            # host-tier restore: writes a spilled payload into a page
            # the allocator JUST handed out (_fetch_full pins it at
            # refcount 1 before anything can share it) — the fetch-side
            # twin of the alloc lifecycle, never a shared-page write
            "write_page"}
_TABLE_FNS = {"__init__", "_admit", "_apply_defrag", "_release_slot",
              "_evict", "_ensure_pages",
              # the release arm of drain-and-swap: joins the loop, frees
              # every slot's pages, then zeroes the rows — the model
              # checker's `swap` op mirrors it
              "_detach_active"}
_DIRECTIVES = ("lock-ok", "cow-ok", "table-ok", "pool-ok")


def default_lint_paths() -> List[str]:
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(base, p) for p in LINT_ROOTS]


def _dotted(node: ast.AST) -> Optional[tuple]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _directive_of(txt: str) -> Optional[str]:
    if "fflint:" not in txt:
        return None
    d = txt.split("fflint:", 1)[1].strip()
    for name in _DIRECTIVES:
        if d.startswith(name):
            return name
    return None


def _comment_map(src: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    return out


class _FileLint:
    """Per-file lint state: comments, pragma bookkeeping, findings."""

    def __init__(self, rel: str, src: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.comments = _comment_map(src)
        self.used_pragmas: Set[int] = set()
        self.findings: List[Finding] = []

    def add(self, severity: str, code: str, lineno: int, msg: str,
            directive: str, *extra_linenos: int):
        for ln in (lineno,) + extra_linenos:
            d = _directive_of(self.comments.get(ln, ""))
            if d in (directive, "ignore"):
                self.used_pragmas.add(ln)
                return
        self.findings.append(Finding(
            "poolcheck", severity, code, f"{self.rel}:{lineno}", msg))

    def stale_pragmas(self):
        for ln, txt in sorted(self.comments.items()):
            if _directive_of(txt) is not None \
                    and ln not in self.used_pragmas:
                self.findings.append(Finding(
                    "poolcheck", "info", "stale-pragma",
                    f"{self.rel}:{ln}",
                    f"'# fflint: {_directive_of(txt)}' pragma no longer "
                    "suppresses any poolcheck finding — delete it"))


def _is_at_set(node: ast.Call) -> bool:
    """x.at[...].set(...) / .add(...) — the functional buffer write."""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in ("set", "add")
            and isinstance(f.value, ast.Subscript)
            and isinstance(f.value.value, ast.Attribute)
            and f.value.value.attr == "at")


def _fn_of(tree: ast.Module) -> Dict[int, str]:
    """lineno -> name of the function whose body contains it (innermost
    def wins), for allowlist checks."""
    spans: List[Tuple[int, int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.lineno, node.end_lineno or node.lineno,
                          node.name))
    out: Dict[int, str] = {}
    for lo, hi, name in sorted(spans):  # later/inner spans overwrite
        for ln in range(lo, hi + 1):
            out[ln] = name
    return out


def _lint_state_file(fl: _FileLint):
    """page-write / table-write checks, only on the state-machine
    files (scheduler.py / pool.py / spec server.py)."""
    fn_of = _fn_of(fl.tree)
    for node in ast.walk(fl.tree):
        if isinstance(node, ast.Call) and _is_at_set(node):
            fn = fn_of.get(node.lineno, "<module>")
            if fn not in _COW_FNS:
                fl.add(
                    "error", "page-write-outside-cow", node.lineno,
                    f"in {fn}(): .at[...].{node.func.attr} writes a "
                    "cache buffer outside the COW clone helper — pool "
                    "pages may be shared (refcount > 1) or published; "
                    "route the write through copy_page / the jitted "
                    "step, or annotate '# fflint: cow-ok (reason)'",
                    "cow-ok")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                if _dotted(base) == ("self", "_tables"):
                    fn = fn_of.get(node.lineno, "<module>")
                    if fn not in _TABLE_FNS:
                        fl.add(
                            "error", "table-write-outside-admission",
                            node.lineno,
                            f"in {fn}(): page-table mutation outside "
                            "the admission/growth/defrag/release "
                            f"lifecycle ({sorted(_TABLE_FNS)}) — table "
                            "contents must stay a pure function of the "
                            "pool bookkeeping, or annotate "
                            "'# fflint: table-ok (reason)'",
                            "table-ok")


def _lint_pool_private(fl: _FileLint):
    """pool._x access outside paged/pool.py."""
    if os.path.basename(fl.rel) == "pool.py":
        return
    for node in ast.walk(fl.tree):
        if isinstance(node, ast.Attribute) \
                and node.attr.startswith("_") \
                and not node.attr.startswith("__"):
            d = _dotted(node.value)
            if d and d[-1] == "pool":
                fl.add(
                    "warning", "pool-private-access", node.lineno,
                    f"touches pool.{node.attr} — PagePool underscore "
                    "state is maintained by its own methods; going "
                    "around them breaks the invariant catalog "
                    "(docs/paged.md), or annotate "
                    "'# fflint: pool-ok (reason)'",
                    "pool-ok")


# -- lock discipline ---------------------------------------------------------


class _LockScanner(ast.NodeVisitor):
    """Flag unlocked reads of loop-owned fields (and pool state) in ONE
    public method of a threaded server class. `owned` and `lock_attrs`
    come from racecheck's whole-repo lock model (see _lint_locks)."""

    def __init__(self, fl: _FileLint, cls: str, meth, owned: Set[str],
                 lock_attrs: Optional[Set[str]] = None):
        self.fl = fl
        self.cls = cls
        self.meth = meth
        self.owned = owned
        self.lock_attrs = lock_attrs or {"_lock"}
        self.lock_depth = 0
        self.pool_aliases: Set[str] = set()

    def _flag(self, lineno: int, what: str):
        self.fl.add(
            "warning", "unlocked-cross-thread-read", lineno,
            f"in {self.cls}.{self.meth.name}(): reads {what} without "
            "holding self._lock while the scheduler-loop thread mutates "
            "it — take the lock, or annotate a deliberate relaxed read "
            "'# fflint: lock-ok (reason)'",
            "lock-ok", self.meth.lineno)

    def visit_FunctionDef(self, node):
        return  # nested defs are separate (deferred) execution contexts

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        locked = any(
            d is not None and len(d) == 2 and d[0] == "self"
            and d[1] in self.lock_attrs
            for d in (_dotted(i.context_expr) for i in node.items))
        if locked:
            self.lock_depth += 1
            for stmt in node.body:
                self.visit(stmt)
            self.lock_depth -= 1
        else:
            self.generic_visit(node)

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Attribute) \
                and _dotted(node.value) == ("self", "pool"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.pool_aliases.add(t.id)
            return  # the alias binding itself is not a state read
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Load) and self.lock_depth == 0:
            d = _dotted(node)
            if d and d[0] == "self" and len(d) >= 2:
                if len(d) == 2 and d[1] in self.owned:
                    self._flag(node.lineno, f"self.{d[1]}")
                    return
                if len(d) >= 3 and d[1] == "pool":
                    self._flag(node.lineno,
                               f"self.pool.{'.'.join(d[2:])}")
                    return
            elif d and d[0] in self.pool_aliases and len(d) >= 2:
                self._flag(node.lineno, f"{'.'.join(d)} (pool state)")
                return
        self.generic_visit(node)


def _lint_locks(file_lints: List[_FileLint]):
    """Delegates to racecheck's whole-repo lock model (ONE lock model in
    the tree): racecheck closes the class hierarchy both ways and infers
    threadedness, loop-owned fields, and lock-guarded fields; this arm
    keeps poolcheck's historical public-surface unlocked-read scan over
    that model. Non-transitive within a method, like hostsync: each
    method's own AST only."""
    from flexflow_tpu.analysis import racecheck

    units = [(fl.rel, fl.tree) for fl in file_lints]
    model = racecheck.build_lock_model(units)
    fl_by_rel = {fl.rel: fl for fl in file_lints}
    for name in sorted(model.classes):
        cm = model.classes[name]
        fl = fl_by_rel.get(cm.rel)
        if fl is None:
            continue
        if not model.family_threaded(name):
            continue
        # cross-thread state = poolcheck's historical loop-owned fields
        # UNION racecheck's lock-guarded fields (a field someone takes a
        # lock to write is cross-thread by that very act)
        owned = model.family_owned(name) \
            | set(model.family_guarded(name))
        lock_attrs = model.family_lock_attrs(name) | {"_lock"}
        for meth in cm.public_method_nodes():
            scanner = _LockScanner(fl, name, meth, owned, lock_attrs)
            for stmt in meth.body:
                scanner.visit(stmt)


def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    fls = _collect_file_lints([path], rel_override=rel)
    _lint_locks(fls)
    out: List[Finding] = []
    for fl in fls:
        fl.stale_pragmas()
        out += fl.findings
    out.sort(key=lambda f: f.where)
    return out


def _collect_file_lints(paths: List[str],
                        rel_override: Optional[str] = None
                        ) -> List[_FileLint]:
    files: List[Tuple[str, str]] = []  # (full, rel)
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, names in os.walk(p):
                for fn in sorted(names):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        files.append((full, os.path.relpath(full, base)))
        elif os.path.exists(p):
            files.append((p, rel_override or os.path.basename(p)))
    out: List[_FileLint] = []
    for full, rel in files:
        with open(full) as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=full)
        except SyntaxError as e:
            fl = _FileLint(rel, "", ast.Module(body=[], type_ignores=[]))
            fl.findings.append(Finding(
                "poolcheck", "error", "syntax-error",
                f"{rel}:{e.lineno}", str(e)))
            out.append(fl)
            continue
        fl = _FileLint(rel, src, tree)
        if os.path.basename(rel) in _STATE_FILE_BASENAMES:
            _lint_state_file(fl)
        _lint_pool_private(fl)
        out.append(fl)
    return out


def lint_paths(paths: List[str]) -> List[Finding]:
    fls = _collect_file_lints(paths)
    _lint_locks(fls)
    out: List[Finding] = []
    for fl in fls:
        fl.stale_pragmas()
        out += fl.findings
    out.sort(key=lambda f: f.where)
    return out


# ---------------------------------------------------------------------------
# the pass


def _model_findings(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    mutations = tuple(ctx.poolcheck_mutations or ())
    trace_dir = ctx.poolcheck_trace_dir
    summary: Dict[str, object] = {"configs": {}}
    total = 0
    for config in sorted(CONFIGS):
        res = model_check(config,
                          pool_factory=ctx.poolcheck_pool_factory,
                          mutations=mutations)
        total += res.explored
        summary["configs"][config] = {
            "explored_states": res.explored,
            "distinct_states": res.reached,
            "violations": len(res.hits),
        }
        for name, msg, trace in res.hits:
            detail = msg.split(":", 1)[1].strip() if ":" in msg else msg
            entry = inv.by_name(name) if _known(name) else None
            findings.append(Finding(
                "poolcheck", "error", f"inv-{name}",
                f"poolcheck:model/{config}",
                f"invariant '{name}' violated — {detail}. "
                f"Spec: {entry.description if entry else '?'}. "
                f"Minimal counterexample ({len(trace)} ops): "
                f"{' -> '.join(trace)}"))
            if trace_dir:
                os.makedirs(trace_dir, exist_ok=True)
                fn = os.path.join(trace_dir,
                                  f"{config}-inv-{name}.json")
                with open(fn, "w") as f:
                    json.dump({"config": config, "invariant": name,
                               "detail": detail, "trace": list(trace),
                               "replay": "flexflow_tpu.analysis."
                                         "poolcheck.replay(trace, "
                                         f"config={config!r})"},
                              f, indent=1)
        if res.truncated:
            findings.append(Finding(
                "poolcheck", "warning", "model-check-truncated",
                f"poolcheck:model/{config}",
                f"exploration stopped at {res.explored} states with the "
                "frontier non-empty — the bounded state space was NOT "
                "fully explored; raise max_states"))
    summary["explored_states"] = total
    ctx.poolcheck_summary = summary
    findings.append(Finding(
        "poolcheck", "info", "model-check-summary", "poolcheck:model",
        f"explored {total} states across {len(CONFIGS)} bounded "
        f"configs ({', '.join(sorted(CONFIGS))}); "
        f"{len(inv.CATALOG)} invariants asserted at every state"))
    return findings


def _known(name: str) -> bool:
    try:
        inv.by_name(name)
        return True
    except KeyError:
        return False


@register_pass("poolcheck")
def poolcheck_pass(ctx: AnalysisContext) -> List[Finding]:
    paths = ctx.src_paths if ctx.src_paths is not None \
        else default_lint_paths()
    findings = lint_paths(paths)
    if not ctx.poolcheck_lint_only:
        findings += _model_findings(ctx)
    return findings
