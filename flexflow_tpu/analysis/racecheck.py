"""racecheck — lock-discipline analysis + bounded interleaving model
checking for the threaded serving protocols (the seventh fflint pass).

PRs 16-17 made the server genuinely concurrent: prefill/decode workers
hand live requests through a shared HostTier, PrefixAffinityRouter
mutates affinity/load maps from caller threads, and ServingAutopilot
drains-and-swaps a running server under `_swap_lock`. This pass checks
that concurrency two ways, mirroring poolcheck's lint + model-check
split:

  STATIC ARM — a whole-repo lock model over serving.py,
      paged/scheduler.py, spec/server.py, disagg/, serving_autopilot.py
      and obs/. Every `self._*lock`-style attribute is a lock; a field
      written under lock L on ANY path is L-guarded; thread contexts
      come from entry-point discovery (`threading.Thread(target=...)`
      methods and the intra-class call graph they reach, vs the public
      caller surface). Rules:

  race-unguarded-write   (error)   a guarded field written lock-free
      where another thread context also touches it (or anywhere, for a
      shared object with no thread of its own).
  lock-order-cycle       (error)   a cycle in the cross-file
      lock-acquisition-order graph (lock held while a method that
      takes another lock is called, resolved one call level deep).
  lock-held-device-sync  (warning) device_get / block_until_ready /
      thread join / future result / event wait while holding a lock —
      the drain-stall class, one call level deep.
  atomicity-split        (warning) a method reads a guarded field
      under a lock, releases it, and re-acquires the same lock to
      write that field — check-then-act across a lock release.
  stale-pragma           (info)    a race-ok pragma suppressing nothing.

  Pragmas: `# fflint: race-ok (reason)` on the flagged line or its
  `def` line.

  DYNAMIC ARM — explore_interleavings(): a bounded explicit-state
      checker over abstract labeled-transition-system models of the
      cross-thread protocols, with per-thread program counters:
      `handoff` (prefill→decode handoff through the shared tier),
      `tierpool` (concurrent spill/fetch/admission on a pool pair with
      LRU capacity drops), `swap` (drain-and-swap under live submits,
      the swap lock modeled explicitly), and `dispatch` (the
      overlapped megastep handoff: host admission racing the in-flight
      device dispatch, fenced by one device_get). All interleavings up
      to a context-switch bound (DEFAULT_SWITCH_BOUND) are explored
      with DPOR-style sleep-set pruning over declared action
      read/write footprints; PROTOCOL_INVARIANTS (future never
      dropped, request owned by exactly one worker, tier partition
      holds mid-fetch, no swap while a handoff is in flight, single
      token-buffer owner and no stale-table bookkeeping across the
      dispatch fence, plus abstract mirrors of the poolcheck catalog's
      conservation and accounting) are asserted at every state. A
      violation reports the MINIMAL interleaving (BFS order),
      replayable via replay_interleaving(); seeded mutations
      (double_submit, unlocked_submit, no_safepoint_join,
      fetch_no_remove, read_before_fence, admit_steals_live_page)
      prove the gate can fail.

poolcheck's `unlocked-cross-thread-read` lint delegates to
build_lock_model() here, so there is exactly ONE lock model in the
tree. CLI: tools/fflint.py runs racecheck by default; `--since` keeps
the static arm only. See docs/analysis.md for finding kinds, pragma
form, the protocol models, and bound semantics.
"""

from __future__ import annotations

import ast
import copy
import io
import json
import os
import tokenize
from collections import deque
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Set, Tuple

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass

# ---------------------------------------------------------------------------
# shared helpers (poolcheck's comment/dotted idioms, local so the
# dependency points poolcheck -> racecheck, never back)

_DIRECTIVES = ("race-ok",)

RACE_ROOTS = ("serving.py", os.path.join("paged", "scheduler.py"),
              os.path.join("spec", "server.py"), "disagg",
              "serving_autopilot.py", "obs")

# methods that run before (or outside) any concurrent phase of the
# object's life — construction and pickling are single-threaded by
# contract, so their lock-free writes are not races
_LIFECYCLE_METHODS = {"__init__", "__new__", "__getstate__",
                      "__setstate__", "__reduce__", "__del__",
                      "__deepcopy__", "__copy__"}


def default_lint_paths() -> List[str]:
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(base, p) for p in RACE_ROOTS]


def _dotted(node: ast.AST) -> Optional[tuple]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _directive_of(txt: str) -> Optional[str]:
    if "fflint:" not in txt:
        return None
    d = txt.split("fflint:", 1)[1].strip()
    for name in _DIRECTIVES:
        if d.startswith(name):
            return name
    return None


def _comment_map(src: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    return out


class _RFileLint:
    """Per-file lint state: comments, race-ok pragma bookkeeping,
    findings (the poolcheck _FileLint shape, pass_name racecheck)."""

    def __init__(self, rel: str, src: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.comments = _comment_map(src)
        self.used_pragmas: Set[int] = set()
        self.findings: List[Finding] = []

    def add(self, severity: str, code: str, lineno: int, msg: str,
            *extra_linenos: int):
        for ln in (lineno,) + extra_linenos:
            if _directive_of(self.comments.get(ln, "")) is not None:
                self.used_pragmas.add(ln)
                return
        self.findings.append(Finding(
            "racecheck", severity, code, f"{self.rel}:{lineno}", msg))

    def stale_pragmas(self):
        for ln, txt in sorted(self.comments.items()):
            if _directive_of(txt) is not None \
                    and ln not in self.used_pragmas:
                self.findings.append(Finding(
                    "racecheck", "info", "stale-pragma",
                    f"{self.rel}:{ln}",
                    "'# fflint: race-ok' pragma no longer suppresses "
                    "any racecheck finding — delete it"))


# ---------------------------------------------------------------------------
# the lock model (shared with poolcheck's unlocked-cross-thread-read)

def _is_lock_attr(name: str) -> bool:
    return name.startswith("_") and name.endswith("lock")


def _lock_with_attrs(node: ast.With) -> List[str]:
    out = []
    for item in node.items:
        d = _dotted(item.context_expr)
        if d and len(d) == 2 and d[0] == "self" and _is_lock_attr(d[1]):
            out.append(d[1])
    return out


# blocking-call matchers for lock-held-device-sync: name -> a predicate
# on the dotted base (None = any base); `join`/`result` need a
# thread/future-looking receiver so `", ".join(...)` stays quiet
_BLOCKING = {
    "device_get": None,
    "block_until_ready": None,
    "wait": None,
    "sleep": None,
    "join": lambda base: any("thread" in seg.lower() for seg in base),
    "result": lambda base: any("fut" in seg.lower() for seg in base),
}


class Access(NamedTuple):
    field: str
    lineno: int
    held: FrozenSet[str]      # lock attrs held at the access


class CallSite(NamedTuple):
    dotted: tuple
    lineno: int
    held: FrozenSet[str]
    # the call is a `return <call>` — nothing in this method runs after
    # it, so it can never be the EARLIER half of an atomicity split
    in_return: bool = False


class Region(NamedTuple):
    """One `with self.<lock>:` block: its own field traffic plus the
    self-method calls made inside it (expanded one level by rules)."""

    attr: str
    lineno: int
    held_before: FrozenSet[str]
    reads: FrozenSet[str]
    writes: FrozenSet[str]
    calls: Tuple[str, ...]    # same-class method names called inside


class MethodSummary:
    __slots__ = ("name", "lineno", "reads", "writes", "regions", "calls",
                 "blocking", "thread_targets")

    def __init__(self, name: str, lineno: int):
        self.name = name
        self.lineno = lineno
        self.reads: List[Access] = []
        self.writes: List[Access] = []
        self.regions: List[Region] = []
        self.calls: List[CallSite] = []
        self.blocking: List[Tuple[str, int, FrozenSet[str]]] = []
        self.thread_targets: List[str] = []

    def self_calls(self) -> List[str]:
        return [c.dotted[1] for c in self.calls
                if len(c.dotted) == 2 and c.dotted[0] == "self"]


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body: field accesses with the held-lock
    set, with-regions, calls, blocking calls, Thread targets. Nested
    defs are separate execution contexts (scanned on demand when they
    turn out to be Thread targets)."""

    def __init__(self, summary: MethodSummary):
        self.s = summary
        self.held: List[str] = []
        self.open_regions: List[dict] = []
        self._in_return = False

    # -- bookkeeping -------------------------------------------------------

    def _heldset(self) -> FrozenSet[str]:
        return frozenset(self.held)

    def _read(self, field: str, lineno: int):
        self.s.reads.append(Access(field, lineno, self._heldset()))
        for r in self.open_regions:
            r["reads"].add(field)

    def _write(self, field: str, lineno: int):
        self.s.writes.append(Access(field, lineno, self._heldset()))
        for r in self.open_regions:
            r["writes"].add(field)

    def _write_target(self, t: ast.AST, lineno: int):
        for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                   else [t]):
            base = el
            is_sub = isinstance(el, ast.Subscript)
            if is_sub:
                base = el.value
            d = _dotted(base)
            if d and len(d) == 2 and d[0] == "self":
                self._write(d[1], lineno)
                if is_sub:           # self._x[k] = v reads _x to index it
                    self._read(d[1], lineno)
            elif is_sub:
                self.visit(el.value)
            if is_sub and el.slice is not None:
                self.visit(el.slice)

    # -- visitors ----------------------------------------------------------

    def visit_FunctionDef(self, node):
        return  # nested defs are deferred contexts

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node):
        attrs = _lock_with_attrs(node)
        if not attrs:
            self.generic_visit(node)
            return
        rec = dict(attrs=attrs, lineno=node.lineno,
                   held_before=self._heldset(),
                   reads=set(), writes=set(), calls=[])
        self.held.extend(attrs)
        self.open_regions.append(rec)
        for stmt in node.body:
            self.visit(stmt)
        self.open_regions.pop()
        del self.held[-len(attrs):]
        for a in attrs:
            self.s.regions.append(Region(
                a, rec["lineno"], rec["held_before"],
                frozenset(rec["reads"]), frozenset(rec["writes"]),
                tuple(rec["calls"])))

    def visit_Assign(self, node):
        for t in node.targets:
            self._write_target(t, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._write_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_AugAssign(self, node):
        self._write_target(node.target, node.lineno)
        d = _dotted(node.target.value if isinstance(
            node.target, ast.Subscript) else node.target)
        if d and len(d) == 2 and d[0] == "self":
            self._read(d[1], node.lineno)  # x += 1 reads then writes
        self.visit(node.value)

    def visit_Delete(self, node):
        for t in node.targets:
            self._write_target(t, node.lineno)

    def visit_Return(self, node):
        if node.value is not None:
            self._in_return = True
            self.visit(node.value)
            self._in_return = False

    def visit_Call(self, node):
        d = _dotted(node.func)
        if d:
            self.s.calls.append(CallSite(d, node.lineno, self._heldset(),
                                         self._in_return))
            for r in self.open_regions:
                if len(d) == 2 and d[0] == "self":
                    r["calls"].append(d[1])
            name = d[-1]
            pred = _BLOCKING.get(name)
            if name in _BLOCKING and (pred is None or pred(d[:-1])) \
                    and self.held:
                self.s.blocking.append(
                    (".".join(d), node.lineno, self._heldset()))
            if name == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        td = _dotted(kw.value)
                        if td and td[0] == "self" and len(td) == 2:
                            self.s.thread_targets.append(td[1])
                        elif isinstance(kw.value, ast.Name):
                            self.s.thread_targets.append(kw.value.id)
            # a self-method call reads no field; self._x.m() reads _x
            if d[0] == "self" and len(d) >= 3:
                self._read(d[1], node.lineno)
        else:
            self.visit(node.func)
        for a in node.args:
            self.visit(a)
        for kw in node.keywords:
            self.visit(kw.value)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Load):
            d = _dotted(node)
            if d and d[0] == "self" and len(d) >= 2:
                self._read(d[1], node.lineno)
                return
        self.generic_visit(node)


def _scan_method(node, name: Optional[str] = None) -> MethodSummary:
    s = MethodSummary(name or node.name, node.lineno)
    scan = _MethodScan(s)
    for stmt in node.body:
        scan.visit(stmt)
    return s


def _owned_fields(node: ast.ClassDef) -> Set[str]:
    """poolcheck's historical `owned` semantics, verbatim: fields
    assigned anywhere inside a PRIVATE method (nested defs included)."""
    owned: Set[str] = set()
    for meth in node.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        private = meth.name.startswith("_") \
            and not meth.name.startswith("__")
        if not private:
            continue
        for sub in ast.walk(meth):
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for t in targets:
                    for el in (t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else [t]):
                        base = el.value if isinstance(
                            el, ast.Subscript) else el
                        d = _dotted(base)
                        if d and len(d) == 2 and d[0] == "self":
                            owned.add(d[1])
    return owned


class ClassModel:
    __slots__ = ("name", "rel", "node", "bases", "lock_attrs", "methods",
                 "owned", "entry_names")

    def __init__(self, rel: str, node: ast.ClassDef):
        self.name = node.name
        self.rel = rel
        self.node = node
        self.bases = [d[-1] for d in
                      (_dotted(b) for b in node.bases) if d]
        self.owned = _owned_fields(node)
        self.lock_attrs: Dict[str, str] = {}  # attr -> "lock"|"rlock"
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call):
                d = _dotted(sub.value.func)
                if d and d[-1] in ("Lock", "RLock"):
                    for t in sub.targets:
                        td = _dotted(t)
                        if td and len(td) == 2 and td[0] == "self":
                            self.lock_attrs[td[1]] = \
                                "rlock" if d[-1] == "RLock" else "lock"
        self.methods: Dict[str, MethodSummary] = {}
        self.entry_names: Set[str] = set()
        for meth in node.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            s = _scan_method(meth)
            self.methods[meth.name] = s
            # Thread(target=<nested fn>) — scan the nested body as a
            # pseudo-method in loop context (the autopilot controller)
            for tgt in s.thread_targets:
                if tgt in self.methods or any(
                        m.name == tgt for m in node.body
                        if isinstance(m, ast.FunctionDef)):
                    self.entry_names.add(tgt)
                    continue
                for sub in ast.walk(meth):
                    if isinstance(sub, ast.FunctionDef) \
                            and sub.name == tgt:
                        pname = f"{meth.name}.<locals>.{tgt}"
                        self.methods[pname] = _scan_method(sub, pname)
                        self.entry_names.add(pname)

    @property
    def threaded(self) -> bool:
        return bool(self.entry_names) or any(
            s.regions for s in self.methods.values())

    def public_method_nodes(self):
        for meth in self.node.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and not meth.name.startswith("_"):
                yield meth


class LockModel:
    """All classes across the scanned files, with the hierarchy closed
    both ways (a subclass's loop thread races the base's public
    readers, and vice versa — poolcheck's family closure)."""

    def __init__(self, classes: Dict[str, ClassModel]):
        self.classes = classes
        anc: Dict[str, Set[str]] = {}

        def ancestors(name: str, seen: Set[str]) -> Set[str]:
            for b in classes[name].bases if name in classes else ():
                if b in classes and b not in seen:
                    seen.add(b)
                    ancestors(b, seen)
            return seen

        family: Dict[str, Set[str]] = {}
        for name in classes:
            family[name] = {name} | ancestors(name, set())
        for name, fam in family.items():
            for a in list(fam):
                family.setdefault(a, {a}).add(name)
        self._family: Dict[str, Set[str]] = {}
        for name in classes:
            group: Set[str] = set()
            for member in family.get(name, {name}):
                group |= family.get(member, {member})
            self._family[name] = group

    def family(self, name: str) -> Set[str]:
        return self._family.get(name, {name})

    def _members(self, name: str) -> List[ClassModel]:
        return [self.classes[m] for m in sorted(self.family(name))
                if m in self.classes]

    def family_threaded(self, name: str) -> bool:
        return any(cm.threaded for cm in self._members(name))

    def family_owned(self, name: str) -> Set[str]:
        out: Set[str] = set()
        for cm in self._members(name):
            out |= cm.owned
        return out

    def family_lock_attrs(self, name: str) -> Set[str]:
        out: Set[str] = set()
        for cm in self._members(name):
            out |= set(cm.lock_attrs)
            for s in cm.methods.values():
                for r in s.regions:
                    out.add(r.attr)
        return out

    def lock_kind(self, name: str, attr: str) -> str:
        for cm in self._members(name):
            if attr in cm.lock_attrs:
                return cm.lock_attrs[attr]
        return "lock"

    def lock_id(self, name: str, attr: str) -> str:
        """Stable cross-file identity: the family member that assigns
        the lock names it (else the alphabetically-first member)."""
        owners = [cm.name for cm in self._members(name)
                  if attr in cm.lock_attrs]
        owner = sorted(owners)[0] if owners else min(self.family(name))
        return f"{owner}.{attr}"

    def family_methods(self, name: str) -> Dict[str, List[Tuple[ClassModel, MethodSummary]]]:
        out: Dict[str, List[Tuple[ClassModel, MethodSummary]]] = {}
        for cm in self._members(name):
            for mname, s in cm.methods.items():
                out.setdefault(mname, []).append((cm, s))
        return out

    def family_entries(self, name: str) -> Set[str]:
        out: Set[str] = set()
        for cm in self._members(name):
            out |= cm.entry_names
        return out

    def family_guarded(self, name: str) -> Dict[str, Set[str]]:
        """field -> the set of lock ids it is written under, anywhere
        in the family (lifecycle methods excluded)."""
        out: Dict[str, Set[str]] = {}
        for cm in self._members(name):
            for mname, s in cm.methods.items():
                if mname.split(".")[0] in _LIFECYCLE_METHODS:
                    continue
                for acc in s.writes:
                    for attr in acc.held:
                        out.setdefault(acc.field, set()).add(
                            self.lock_id(name, attr))
        return out

    def _reach(self, name: str, starts: Set[str]) -> Set[str]:
        meths = self.family_methods(name)
        seen = set(m for m in starts if m in meths)
        frontier = list(seen)
        while frontier:
            m = frontier.pop()
            for _cm, s in meths.get(m, ()):
                for callee in s.self_calls():
                    if callee in meths and callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)
        return seen

    def contexts(self, name: str) -> Dict[str, str]:
        """method -> 'loop' | 'caller' | 'both' | 'lifecycle' for the
        whole family. Loop = reachable from a Thread entry point;
        caller = reachable from the public surface."""
        meths = self.family_methods(name)
        entries = self.family_entries(name)
        loop = self._reach(name, entries)
        public = {m for m in meths
                  if not m.startswith("_") or m == "__call__"}
        caller = self._reach(name, public)
        out: Dict[str, str] = {}
        for m in meths:
            if m.split(".")[0] in _LIFECYCLE_METHODS:
                out[m] = "lifecycle"
            elif m in loop and m in caller:
                out[m] = "both"
            elif m in loop:
                out[m] = "loop"
            else:
                out[m] = "caller"
        return out


def build_lock_model(units: List[Tuple[str, ast.Module]]) -> LockModel:
    """units = [(rel_path, parsed module)]. Collects every class; later
    files win name collisions (poolcheck's historical flat-dict
    behavior)."""
    classes: Dict[str, ClassModel] = {}
    for rel, tree in units:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                classes[node.name] = ClassModel(rel, node)
    return LockModel(classes)

# ---------------------------------------------------------------------------
# static rules


def _rule_unguarded_writes(model: LockModel, fl_by_rel: Dict[str, _RFileLint]):
    seen_families: Set[frozenset] = set()
    for name in sorted(model.classes):
        fam = frozenset(model.family(name))
        if fam in seen_families:
            continue
        seen_families.add(fam)
        guarded = model.family_guarded(name)
        if not guarded:
            continue
        ctxs = model.contexts(name)
        entries = model.family_entries(name)
        meths = model.family_methods(name)
        # which contexts touch each guarded field (reads or writes)
        touch: Dict[str, Set[str]] = {f: set() for f in guarded}
        for mname, impls in meths.items():
            if ctxs.get(mname) == "lifecycle":
                continue
            for _cm, s in impls:
                for acc in s.reads + s.writes:
                    if acc.field in touch:
                        touch[acc.field].add(ctxs.get(mname, "caller"))
        for mname, impls in meths.items():
            if ctxs.get(mname) == "lifecycle":
                continue
            for cm, s in impls:
                for acc in s.writes:
                    if acc.field not in guarded:
                        continue
                    ids = {model.lock_id(name, a) for a in acc.held}
                    if ids & guarded[acc.field]:
                        continue
                    wctx = ctxs.get(mname, "caller")
                    if entries:
                        others = touch[acc.field] - {wctx}
                        if wctx != "both" and not others:
                            continue  # single-context field: no race
                    locks = ", ".join(sorted(guarded[acc.field]))
                    fl = fl_by_rel.get(cm.rel)
                    if fl is None:
                        continue
                    fl.add(
                        "error", "race-unguarded-write", acc.lineno,
                        f"in {cm.name}.{mname}(): writes "
                        f"self.{acc.field} lock-free, but that field is "
                        f"guarded by {locks} on other paths and is "
                        "reachable from another thread context — take "
                        "the lock, or annotate a deliberate relaxed "
                        "write '# fflint: race-ok (reason)'",
                        s.lineno)


def _lock_order_edges(model: LockModel):
    """(lock_id_from, lock_id_to, rel, lineno, note) edges: lexical
    nesting plus one-level call resolution (a call made while holding a
    lock, to any scanned method that directly acquires another)."""
    # lock ids directly acquired per method name, for name-resolution
    acquires_by_name: Dict[str, List[Tuple[str, str]]] = {}
    for cname, cm in model.classes.items():
        for mname, s in cm.methods.items():
            for r in s.regions:
                acquires_by_name.setdefault(mname, []).append(
                    (model.lock_id(cname, r.attr),
                     model.lock_kind(cname, r.attr)))
    edges: List[Tuple[str, str, str, int, str]] = []
    for cname in sorted(model.classes):
        cm = model.classes[cname]
        for mname, s in cm.methods.items():
            for r in s.regions:
                if r.held_before:
                    to_id = model.lock_id(cname, r.attr)
                    for a in r.held_before:
                        from_id = model.lock_id(cname, a)
                        if from_id != to_id:
                            edges.append((from_id, to_id, cm.rel,
                                          r.lineno,
                                          f"{cname}.{mname} nests "
                                          f"{r.attr} under {a}"))
            for c in s.calls:
                if not c.held:
                    continue
                callee = c.dotted[-1]
                if callee.startswith("__"):
                    continue
                held_ids = {model.lock_id(cname, a) for a in c.held}
                kinds = {model.lock_id(cname, a):
                         model.lock_kind(cname, a) for a in c.held}
                same_object = (len(c.dotted) == 2
                               and c.dotted[0] == "self")
                for to_id, _to_kind in acquires_by_name.get(callee, ()):
                    for from_id in sorted(held_ids):
                        if from_id == to_id \
                                and kinds.get(from_id) == "rlock":
                            continue  # reentrant: not a self-deadlock
                        if from_id == to_id and not same_object:
                            # name-resolved onto a DIFFERENT object (for
                            # example self._inner.submit while holding
                            # our own lock in a same-named method): that
                            # instance's lock is not this lock
                            continue
                        edges.append((from_id, to_id, cm.rel, c.lineno,
                                      f"{cname}.{mname} holds "
                                      f"{from_id} and calls "
                                      f"{'.'.join(c.dotted)} which "
                                      f"acquires {to_id}"))
    return edges


def _rule_lock_order(model: LockModel, fl_by_rel: Dict[str, _RFileLint]):
    edges = _lock_order_edges(model)
    graph: Dict[str, Set[str]] = {}
    witness: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for f, t, rel, ln, note in edges:
        graph.setdefault(f, set()).add(t)
        graph.setdefault(t, set())
        witness.setdefault((f, t), (rel, ln, note))
    # Tarjan SCC — a cycle is an SCC of size >1, or a self-edge
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    for scc in sccs:
        cyc = sorted(scc)
        self_loop = len(cyc) == 1 and cyc[0] in graph.get(cyc[0], ())
        if len(cyc) < 2 and not self_loop:
            continue
        ws = sorted((witness[(f, t)], f, t)
                    for f in cyc for t in graph.get(f, ())
                    if t in cyc and (f, t) in witness)
        (rel, ln, _note), _f, _t = ws[0]
        detail = "; ".join(f"{f} -> {t} ({witness[(f, t)][0]}:"
                           f"{witness[(f, t)][1]}, "
                           f"{witness[(f, t)][2]})"
                           for (_w, f, t) in ws)
        fl = fl_by_rel.get(rel)
        if fl is None:
            fl = next(iter(fl_by_rel.values()))
        fl.add(
            "error", "lock-order-cycle", ln,
            f"locks {{{', '.join(cyc)}}} are acquired in conflicting "
            f"orders — a cross-thread deadlock is reachable: {detail}. "
            "Impose one acquisition order (or annotate "
            "'# fflint: race-ok (reason)' at a witness site)")


def _rule_lock_held_blocking(model: LockModel,
                             fl_by_rel: Dict[str, _RFileLint]):
    blocking_methods: Dict[str, List[Tuple[str, str, int]]] = {}
    for cname, cm in model.classes.items():
        for mname, s in cm.methods.items():
            for desc, ln, _held in s.blocking:
                blocking_methods.setdefault(mname, []).append(
                    (cname, desc, ln))
    for cname in sorted(model.classes):
        cm = model.classes[cname]
        fl = fl_by_rel.get(cm.rel)
        if fl is None:
            continue
        for mname, s in cm.methods.items():
            for desc, ln, held in s.blocking:
                fl.add(
                    "warning", "lock-held-device-sync", ln,
                    f"in {cname}.{mname}(): {desc}() blocks while "
                    f"holding {', '.join(sorted(held))} — every other "
                    "thread contending for the lock stalls behind the "
                    "sync (the drain-stall class); move it outside the "
                    "critical section, or annotate "
                    "'# fflint: race-ok (reason)'",
                    s.lineno)
            for c in s.calls:
                if not c.held:
                    continue
                callee = c.dotted[-1]
                if callee.startswith("__") or callee in _BLOCKING:
                    continue
                for ocls, desc, oln in blocking_methods.get(callee, ()):
                    fl.add(
                        "warning", "lock-held-device-sync", c.lineno,
                        f"in {cname}.{mname}(): calls "
                        f"{'.'.join(c.dotted)}() while holding "
                        f"{', '.join(sorted(c.held))}, and "
                        f"{ocls}.{callee}() blocks on {desc}() "
                        f"({ocls}:{oln}) — the lock is held across a "
                        "blocking sync; move the call outside the "
                        "critical section, or annotate "
                        "'# fflint: race-ok (reason)'",
                        s.lineno)
                    break  # one finding per call site


def _region_events(model: LockModel, name: str, cm: ClassModel,
                   s: MethodSummary):
    """Ordered same-lock acquisition events inside one method: direct
    regions, plus calls (lock not held) to same-family methods that
    acquire it. Read/write sets expand same-family calls one level."""
    meths = model.family_methods(name)

    def expand(reads: Set[str], writes: Set[str], calls) -> Tuple[Set[str], Set[str]]:
        r, w = set(reads), set(writes)
        for callee in calls:
            for _cm2, s2 in meths.get(callee, ()):
                r |= {a.field for a in s2.reads}
                w |= {a.field for a in s2.writes}
        return r, w

    events: List[Tuple[str, int, Set[str], Set[str], bool]] = []
    for reg in s.regions:
        r, w = expand(set(reg.reads), set(reg.writes), reg.calls)
        events.append((reg.attr, reg.lineno, r, w, False))
    for c in s.calls:
        if len(c.dotted) != 2 or c.dotted[0] != "self":
            continue
        callee = c.dotted[1]
        if callee == s.name:
            continue
        for _cm2, s2 in meths.get(callee, ()):
            for reg in s2.regions:
                if reg.attr in c.held:
                    continue
                r, w = expand(set(reg.reads), set(reg.writes), reg.calls)
                events.append((reg.attr, c.lineno, r, w, c.in_return))
    events.sort(key=lambda e: e[1])
    return events


def _rule_atomicity_split(model: LockModel,
                          fl_by_rel: Dict[str, _RFileLint]):
    for name in sorted(model.classes):
        cm = model.classes[name]
        fl = fl_by_rel.get(cm.rel)
        if fl is None:
            continue
        guarded = model.family_guarded(name)
        if not guarded:
            continue
        for mname, s in cm.methods.items():
            if mname.split(".")[0] in _LIFECYCLE_METHODS:
                continue
            events = _region_events(model, name, cm, s)
            by_attr: Dict[str, List[Tuple[int, Set[str], Set[str],
                                          bool]]] = {}
            for attr, ln, r, w, term in events:
                by_attr.setdefault(attr, []).append((ln, r, w, term))
            for attr, evs in by_attr.items():
                if len(evs) < 2:
                    continue
                lid = model.lock_id(name, attr)
                fields = {f for f, ids in guarded.items() if lid in ids}
                for i, (ln1, r1, _w1, term1) in enumerate(evs):
                    if term1:
                        continue  # `return call()`: nothing runs after
                    for ln2, _r2, w2, _t2 in evs[i + 1:]:
                        split = sorted(r1 & w2 & fields)
                        if not split:
                            continue
                        fl.add(
                            "warning", "atomicity-split", ln2,
                            f"in {name}.{mname}(): reads "
                            f"self.{split[0]} under {attr} (line {ln1}) "
                            "then releases and re-acquires it to write "
                            "the same field — the check-then-act is not "
                            "atomic; merge into one critical section, "
                            "or annotate '# fflint: race-ok (reason)'",
                            s.lineno)
                        break
                    else:
                        continue
                    break


def _collect_file_lints(paths: List[str],
                        rel_override: Optional[str] = None
                        ) -> List[_RFileLint]:
    files: List[Tuple[str, str]] = []
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, names in os.walk(p):
                for fn in sorted(names):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        files.append((full, os.path.relpath(full, base)))
        elif os.path.exists(p):
            files.append((p, rel_override or os.path.basename(p)))
    out: List[_RFileLint] = []
    for full, rel in files:
        with open(full) as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=full)
        except SyntaxError as e:
            fl = _RFileLint(rel, "", ast.Module(body=[], type_ignores=[]))
            fl.findings.append(Finding(
                "racecheck", "error", "syntax-error",
                f"{rel}:{e.lineno}", str(e)))
            out.append(fl)
            continue
        out.append(_RFileLint(rel, src, tree))
    return out


def _lint(fls: List[_RFileLint]) -> List[Finding]:
    model = build_lock_model([(fl.rel, fl.tree) for fl in fls])
    fl_by_rel = {fl.rel: fl for fl in fls}
    _rule_unguarded_writes(model, fl_by_rel)
    _rule_lock_order(model, fl_by_rel)
    _rule_lock_held_blocking(model, fl_by_rel)
    _rule_atomicity_split(model, fl_by_rel)
    out: List[Finding] = []
    for fl in fls:
        fl.stale_pragmas()
        out += fl.findings
    out.sort(key=lambda f: f.where)
    return out


def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    return _lint(_collect_file_lints([path], rel_override=rel))


def lint_paths(paths: List[str]) -> List[Finding]:
    return _lint(_collect_file_lints(paths))

# ---------------------------------------------------------------------------
# dynamic arm: bounded interleaving model checking over abstract
# labeled-transition-system models of the three cross-thread protocols

DEFAULT_SWITCH_BOUND = 8

PROTOCOL_INVARIANTS = {
    "single-owner": "a submitted, unfinished request is owned by "
                    "exactly one location (queue, worker slot, handoff "
                    "in-hand) at every instant",
    "future-dropped": "every submitted request's future is resolved — "
                      "never stranded in a detached server or orphaned "
                      "mid-handoff",
    "future-double-resolve": "a request's future is resolved exactly "
                             "once",
    "tier-partition": "a KV payload lives in at most one of {source "
                      "pool, tier, fetcher in-flight, destination "
                      "pool} — the partition holds mid-fetch",
    "payload-conservation": "every payload is accounted for: resident, "
                            "spilled, in flight, fetched, or counted "
                            "dropped (the poolcheck conservation "
                            "mirror)",
    "free-accounting": "free + resident pages equal the pool size on "
                       "both sides of the tier (the poolcheck "
                       "free-accounting mirror)",
    "lru-capacity": "the tier never exceeds its capacity; overflow "
                    "drops the LRU-oldest entry and counts it",
    "swap-during-handoff": "the controller never detaches a server "
                           "while a handoff is in flight on its loop "
                           "thread",
    "dispatch-buffer-owner": "an in-flight megastep's token buffer has "
                             "exactly one owner at every instant — the "
                             "device until the fence retires it, host "
                             "bookkeeping only after",
    "stale-page-table": "overlapped admission takes only FREE pages; "
                        "no page referenced by the in-flight "
                        "dispatch's table is freed or reassigned "
                        "before its replay lands",
    "deadlock": "some thread can always make progress until the "
                "protocol completes",
}


class Action(NamedTuple):
    """One enabled transition: thread id, label, and the shared-state
    footprint the DPOR independence relation is computed from."""

    tid: str
    label: str
    reads: FrozenSet[str]
    writes: FrozenSet[str]


def _independent(a: Action, b: Action) -> bool:
    return (a.tid != b.tid
            and not (a.writes & (b.reads | b.writes))
            and not (b.writes & a.reads))


class ProtocolModel:
    """Base for the abstract protocol LTS models: per-thread program
    counters, enabled() actions with declared footprints, state-scope
    check() plus terminal check_final()/check_stuck()."""

    NAME = "?"

    def __init__(self, mutations: Tuple[str, ...] = ()):
        self.mutations = tuple(mutations)

    def clone(self):
        return copy.deepcopy(self)

    def check(self) -> List[str]:
        return []

    def check_final(self) -> List[str]:
        return []

    def check_stuck(self) -> List[str]:
        return [f"deadlock: no thread can make progress and the "
                f"{self.NAME} protocol has not completed"]


class HandoffModel(ProtocolModel):
    """Protocol 1 — the prefill→decode handoff through the shared tier
    (disagg/workers.py PrefillWorker._on_prefill_complete feeding
    PagedGenerationServer.submit_request): the prefill loop publishes
    the tail, spills the request's pages, frees + clears the slot with
    the request in hand, then enqueues it on the decode side, whose
    admission fetches the payload back out of the tier."""

    NAME = "handoff"
    N = 2

    def __init__(self, mutations: Tuple[str, ...] = ()):
        super().__init__(mutations)
        self.fut = ["pending"] * self.N
        self.resolved_n = [0] * self.N
        self.client_next = 0
        self.prefill_q: List[int] = []
        self.pslot: Optional[List[int]] = None  # [rid, pc]
        self.in_hand: Optional[int] = None
        self.decode_q: List[int] = []
        self.dslot: Optional[List[int]] = None  # [rid, pc]
        self.kv_prefill: Set[int] = set()
        self.tier: Set[int] = set()
        self.kv_decode: Set[int] = set()

    def enabled(self) -> List[Action]:
        acts: List[Action] = []
        if self.client_next < self.N:
            acts.append(Action("client", f"submit({self.client_next})",
                               frozenset(), frozenset({"prefill_q"})))
        # the prefill loop is one sequential thread: enqueue the request
        # in hand, else advance the slot, else take the next submission
        if self.in_hand is not None:
            acts.append(Action("prefill", f"enqueue({self.in_hand})",
                               frozenset(),
                               frozenset({"decode_q", "in_hand"})))
        elif self.pslot is not None:
            r, pc = self.pslot
            step = [("compute", frozenset(),
                     frozenset({"pslot", f"kv{r}"})),
                    ("publish_tail", frozenset(), frozenset({"pslot"})),
                    ("spill", frozenset({f"kv{r}"}),
                     frozenset({"pslot", "tier", f"kv{r}"})),
                    ("free_clear", frozenset(),
                     frozenset({"pslot", "in_hand"}))][pc]
            acts.append(Action("prefill", f"{step[0]}({r})",
                               step[1], step[2]))
        elif self.prefill_q:
            acts.append(Action("prefill", "take",
                               frozenset({"prefill_q"}),
                               frozenset({"prefill_q", "pslot"})))
        if self.dslot is None:
            if self.decode_q:
                acts.append(Action("decode", "take",
                                   frozenset({"decode_q"}),
                                   frozenset({"decode_q", "dslot"})))
        else:
            r, pc = self.dslot
            if pc == 0:
                acts.append(Action("decode", f"fetch({r})",
                                   frozenset({"tier"}),
                                   frozenset({"tier", "dslot",
                                              f"kv{r}"})))
            else:
                acts.append(Action("decode", f"finish({r})",
                                   frozenset(),
                                   frozenset({f"fut{r}", "dslot",
                                              f"kv{r}"})))
        return acts

    def apply(self, action: Action):
        lbl = action.label
        op = lbl.split("(")[0]
        arg = int(lbl[:-1].split("(")[1]) if "(" in lbl else None
        if op == "submit":
            self.prefill_q.append(arg)
            self.client_next += 1
        elif op == "take" and action.tid == "prefill":
            self.pslot = [self.prefill_q.pop(0), 0]
        elif op == "compute":
            self.kv_prefill.add(arg)
            self.pslot[1] = 1
        elif op == "publish_tail":
            self.pslot[1] = 2
        elif op == "spill":
            self.kv_prefill.discard(arg)
            self.tier.add(arg)
            self.pslot[1] = 3
        elif op == "free_clear":
            self.in_hand = self.pslot[0]
            self.pslot = None
        elif op == "enqueue":
            self.decode_q.append(arg)
            if "double_submit" in self.mutations:
                # SEEDED DEFECT: the handoff retries after a spurious
                # error and submits the SAME request object twice — two
                # decode-side owners now share one future
                self.decode_q.append(arg)
            self.in_hand = None
        elif op == "take":
            self.dslot = [self.decode_q.pop(0), 0]
        elif op == "fetch":
            self.tier.discard(arg)
            self.kv_decode.add(arg)
            self.dslot[1] = 1
        elif op == "finish":
            self.kv_decode.discard(arg)
            self.resolved_n[arg] += 1
            self.fut[arg] = "resolved"
            self.dslot = None

    def _owners(self, r: int) -> int:
        n = self.prefill_q.count(r) + self.decode_q.count(r)
        if self.pslot is not None and self.pslot[0] == r:
            n += 1
        if self.in_hand == r:
            n += 1
        if self.dslot is not None and self.dslot[0] == r:
            n += 1
        return n

    def check(self) -> List[str]:
        v: List[str] = []
        for r in range(self.N):
            own = self._owners(r)
            if self.fut[r] == "resolved":
                if own:
                    v.append(f"single-owner: finished request {r} is "
                             f"still owned by {own} location(s)")
                if self.resolved_n[r] > 1:
                    v.append(f"future-double-resolve: request {r} "
                             f"resolved {self.resolved_n[r]} times")
            elif r < self.client_next and own != 1:
                v.append(f"single-owner: request {r} is owned by {own} "
                         "locations (queues/slots/handoff) — must be "
                         "exactly one")
            places = sum((r in self.kv_prefill, r in self.tier,
                          r in self.kv_decode))
            if places > 1:
                v.append(f"tier-partition: request {r}'s KV is present "
                         f"in {places} locations at once")
        return v

    def done(self) -> bool:
        return (self.client_next == self.N and not self.prefill_q
                and not self.decode_q and self.pslot is None
                and self.dslot is None and self.in_hand is None)

    def check_final(self) -> List[str]:
        v = [f"future-dropped: request {r}'s future is still pending "
             "at protocol completion"
             for r in range(self.N) if self.fut[r] != "resolved"]
        if self.tier:
            v.append("payload-conservation: the tier holds orphan "
                     f"payloads {sorted(self.tier)} at completion")
        return v

    def key(self) -> tuple:
        return (self.client_next, tuple(self.prefill_q),
                tuple(self.pslot or ()), self.in_hand,
                tuple(self.decode_q), tuple(self.dslot or ()),
                tuple(self.fut), tuple(self.resolved_n),
                tuple(sorted(self.kv_prefill)),
                tuple(sorted(self.tier)),
                tuple(sorted(self.kv_decode)))


class TierPoolModel(ProtocolModel):
    """Protocol 2 — concurrent spill/fetch/admission on a pool pair
    through one capacity-bounded LRU tier (disagg/host_tier.py +
    paged/pool.py spill_oldest/prefetch/_fetch_full): the spiller
    thread moves pages out of the prefill pool under pressure while
    the fetcher pops payloads mid-flight into the decode pool; fetch
    is deliberately two steps (pop, then commit) so the mid-fetch
    partition is a checked state, not an argument."""

    NAME = "tierpool"
    HASHES = ("h0", "h1", "h2")
    FETCHES = ("h0", "h2")
    TIER_CAP = 2
    POOL_D = 2

    def __init__(self, mutations: Tuple[str, ...] = ()):
        super().__init__(mutations)
        self.pool_p = list(self.HASHES)
        self.free_p = 0
        self.tier: List[str] = []      # LRU order, oldest first
        self.dropped: List[str] = []
        self.pool_d: List[str] = []
        self.free_d = self.POOL_D
        self.in_flight: Optional[str] = None
        self.spill_i = 0
        self.fetch_i = 0
        self.fetch_pc = 0              # 0 = lookup/pop, 1 = commit
        self.misses = 0

    def enabled(self) -> List[Action]:
        acts: List[Action] = []
        if self.spill_i < len(self.HASHES):
            h = self.HASHES[self.spill_i]
            acts.append(Action("spiller", f"spill({h})",
                               frozenset({"pool_p", "tier"}),
                               frozenset({"pool_p", "tier", "dropped"})))
        if self.fetch_i < len(self.FETCHES):
            h = self.FETCHES[self.fetch_i]
            if self.fetch_pc == 1:
                acts.append(Action("fetcher", f"commit({h})",
                                   frozenset({"in_flight"}),
                                   frozenset({"in_flight", "pool_d"})))
            elif h in self.tier:
                acts.append(Action("fetcher", f"lookup({h})",
                                   frozenset({"tier"}),
                                   frozenset({"tier", "in_flight"})))
            elif h in self.dropped:
                acts.append(Action("fetcher", f"miss({h})",
                                   frozenset({"tier", "dropped"}),
                                   frozenset({"misses"})))
            # else: still resident on the prefill side — the fetcher
            # blocks until the spiller moves it (or drops it)
        return acts

    def apply(self, action: Action):
        op = action.label.split("(")[0]
        h = action.label[:-1].split("(")[1]
        if op == "spill":
            self.pool_p.remove(h)
            self.free_p += 1
            self.tier.append(h)
            if len(self.tier) > self.TIER_CAP:
                self.dropped.append(self.tier.pop(0))  # LRU drop
            self.spill_i += 1
        elif op == "lookup":
            if "fetch_no_remove" not in self.mutations:
                self.tier.remove(h)
            # SEEDED DEFECT (fetch_no_remove): the fetch COPIES the
            # payload instead of moving it — resident ⊎ spilled breaks
            # the instant the commit lands
            self.in_flight = h
            self.fetch_pc = 1
        elif op == "miss":
            self.misses += 1
            self.fetch_i += 1
        elif op == "commit":
            self.pool_d.append(self.in_flight)
            self.free_d -= 1
            self.in_flight = None
            self.fetch_pc = 0
            self.fetch_i += 1

    def check(self) -> List[str]:
        v: List[str] = []
        for h in self.HASHES:
            places = sum((h in self.pool_p, h in self.tier,
                          h == self.in_flight, h in self.pool_d))
            if places > 1:
                v.append(f"tier-partition: payload {h} is in {places} "
                         "of {prefill pool, tier, in-flight, decode "
                         "pool} at once — the mid-fetch partition is "
                         "broken")
            elif places + (1 if h in self.dropped else 0) != 1:
                v.append(f"payload-conservation: payload {h} is in no "
                         "location and was never counted dropped")
        if self.free_p + len(self.pool_p) != len(self.HASHES):
            v.append(f"free-accounting: prefill pool free={self.free_p}"
                     f" + resident={len(self.pool_p)} != "
                     f"{len(self.HASHES)}")
        if self.free_d + len(self.pool_d) != self.POOL_D:
            v.append(f"free-accounting: decode pool free={self.free_d} "
                     f"+ resident={len(self.pool_d)} != {self.POOL_D}")
        if len(self.tier) > self.TIER_CAP:
            v.append(f"lru-capacity: tier holds {len(self.tier)} "
                     f"payloads over capacity {self.TIER_CAP}")
        return v

    def done(self) -> bool:
        return (self.spill_i == len(self.HASHES)
                and self.fetch_i == len(self.FETCHES))

    def check_final(self) -> List[str]:
        if self.misses + len(self.pool_d) != len(self.FETCHES):
            return ["payload-conservation: fetches + misses do not "
                    f"cover the fetch script ({len(self.pool_d)} "
                    f"fetched, {self.misses} missed, "
                    f"{len(self.FETCHES)} attempted)"]
        return []

    def key(self) -> tuple:
        return (tuple(self.pool_p), self.free_p, tuple(self.tier),
                tuple(self.dropped), tuple(self.pool_d), self.free_d,
                self.in_flight, self.spill_i, self.fetch_i,
                self.fetch_pc, self.misses)


class SwapModel(ProtocolModel):
    """Protocol 3 — autopilot drain-and-swap under live submits
    (serving_autopilot.py swap_to vs submit, both under `_swap_lock`;
    serving.py detach_for_swap): the controller warms the successor,
    takes the lock, stops the old loop, joins it at a safe point,
    collects + absorbs the carried queue, starts the successor and
    cuts `inner` over — while a client submits through the same lock
    and a worker thread serves whichever server is running."""

    NAME = "swap"
    N = 2
    SCRIPT = ("warm", "acq", "stop_old", "join", "collect", "absorb",
              "start_new", "cutover", "rel")

    def __init__(self, mutations: Tuple[str, ...] = ()):
        super().__init__(mutations)
        self.holder: Optional[str] = None
        self.inner = "old"
        self.q: Dict[str, List[int]] = {"old": [], "new": []}
        self.running = {"old": True, "new": False}
        self.carried: List[int] = []
        self.collected = False
        self.joined_dirty: Optional[int] = None
        self.fut = ["pending"] * self.N
        self.resolved_n = [0] * self.N
        self.client_i = 0
        self.client_pc = 0             # 0 = acq, 1 = enq, 2 = rel
        self.ctrl_pc = 0
        self.in_hand: Optional[Tuple[str, int]] = None

    def enabled(self) -> List[Action]:
        acts: List[Action] = []
        if self.client_i < self.N:
            if "unlocked_submit" in self.mutations:
                # SEEDED DEFECT: submit skips the swap lock entirely —
                # it can land in the old server inside the detach window
                acts.append(Action(
                    "client", f"enq_unlocked({self.client_i})",
                    frozenset({"inner"}),
                    frozenset({f"q_{self.inner}"})))
            elif self.client_pc == 0:
                if self.holder is None:
                    acts.append(Action("client", "acq",
                                       frozenset({"L"}),
                                       frozenset({"L"})))
            elif self.client_pc == 1:
                acts.append(Action("client", f"enq({self.client_i})",
                                   frozenset({"inner"}),
                                   frozenset({f"q_{self.inner}"})))
            else:
                acts.append(Action("client", "rel", frozenset(),
                                   frozenset({"L"})))
        if self.ctrl_pc < len(self.SCRIPT):
            step = self.SCRIPT[self.ctrl_pc]
            if step == "warm":
                acts.append(Action("controller", "warm", frozenset(),
                                   frozenset({"warmed"})))
            elif step == "acq":
                if self.holder is None:
                    acts.append(Action("controller", "acq",
                                       frozenset({"L"}),
                                       frozenset({"L"})))
            elif step == "stop_old":
                acts.append(Action("controller", "stop_old",
                                   frozenset(),
                                   frozenset({"run_old"})))
            elif step == "join":
                if self.in_hand is None \
                        or "no_safepoint_join" in self.mutations:
                    # SEEDED DEFECT (no_safepoint_join): detach without
                    # waiting for the loop's safe point — a request
                    # mid-handoff on the loop thread is left orphaned
                    acts.append(Action("controller", "join",
                                       frozenset({"in_hand"}),
                                       frozenset({"joined"})))
            elif step == "collect":
                acts.append(Action("controller", "collect",
                                   frozenset({"q_old"}),
                                   frozenset({"q_old", "carried"})))
            elif step == "absorb":
                acts.append(Action("controller", "absorb",
                                   frozenset({"carried"}),
                                   frozenset({"q_new", "carried"})))
            elif step == "start_new":
                acts.append(Action("controller", "start_new",
                                   frozenset(),
                                   frozenset({"run_new"})))
            elif step == "cutover":
                acts.append(Action("controller", "cutover",
                                   frozenset(), frozenset({"inner"})))
            else:
                acts.append(Action("controller", "rel", frozenset(),
                                   frozenset({"L"})))
        if self.in_hand is not None:
            acts.append(Action("worker", f"resolve({self.in_hand[1]})",
                               frozenset({"in_hand"}),
                               frozenset({"fut", "in_hand"})))
        else:
            for s in ("old", "new"):
                if self.running[s] and self.q[s]:
                    acts.append(Action("worker", f"pop({s})",
                                       frozenset({f"q_{s}",
                                                  f"run_{s}"}),
                                       frozenset({f"q_{s}",
                                                  "in_hand"})))
        return acts

    def apply(self, action: Action):
        lbl, tid = action.label, action.tid
        op = lbl.split("(")[0]
        if tid == "client":
            if op == "acq":
                self.holder = "client"
                self.client_pc = 1
            elif op in ("enq", "enq_unlocked"):
                self.q[self.inner].append(self.client_i)
                if op == "enq_unlocked":
                    self.client_i += 1
                else:
                    self.client_pc = 2
            else:
                self.holder = None
                self.client_pc = 0
                self.client_i += 1
        elif tid == "controller":
            if op == "acq":
                self.holder = "controller"
            elif op == "stop_old":
                self.running["old"] = False
            elif op == "join":
                if self.in_hand is not None:
                    self.joined_dirty = self.in_hand[1]
            elif op == "collect":
                self.carried = list(self.q["old"])
                self.q["old"] = []
                self.collected = True
            elif op == "absorb":
                self.q["new"].extend(self.carried)
                self.carried = []
            elif op == "start_new":
                self.running["new"] = True
            elif op == "cutover":
                self.inner = "new"
            elif op == "rel":
                self.holder = None
            self.ctrl_pc += 1
        else:
            if op == "pop":
                s = lbl[:-1].split("(")[1]
                self.in_hand = (s, self.q[s].pop(0))
            else:
                r = int(lbl[:-1].split("(")[1])
                self.resolved_n[r] += 1
                self.fut[r] = "resolved"
                self.in_hand = None

    def check(self) -> List[str]:
        v: List[str] = []
        if self.collected and self.q["old"] \
                and not self.running["old"]:
            v.append("future-dropped: request(s) "
                     f"{self.q['old']} enqueued into the detached old "
                     "server after its queue was collected — the "
                     "submit bypassed the swap lock and the future "
                     "can never resolve")
        if self.joined_dirty is not None:
            v.append("swap-during-handoff: the old server was "
                     f"detached while request {self.joined_dirty} was "
                     "mid-handoff on its loop thread")
        for r in range(self.N):
            own = (self.q["old"].count(r) + self.q["new"].count(r)
                   + self.carried.count(r)
                   + (1 if self.in_hand is not None
                      and self.in_hand[1] == r else 0))
            if self.fut[r] == "resolved":
                if self.resolved_n[r] > 1:
                    v.append(f"future-double-resolve: request {r} "
                             f"resolved {self.resolved_n[r]} times")
                if own:
                    v.append(f"single-owner: finished request {r} is "
                             f"still owned by {own} location(s)")
            elif r < self.client_i and own != 1:
                v.append(f"single-owner: request {r} is owned by {own} "
                         "locations — must be exactly one")
        return v

    def done(self) -> bool:
        return (self.ctrl_pc == len(self.SCRIPT)
                and self.client_i == self.N and self.in_hand is None
                and not self.q["old"] and not self.q["new"]
                and not self.carried)

    def check_final(self) -> List[str]:
        return [f"future-dropped: request {r}'s future is still "
                "pending at protocol completion"
                for r in range(self.N) if self.fut[r] != "resolved"]

    def key(self) -> tuple:
        return (self.holder, self.inner, tuple(self.q["old"]),
                tuple(self.q["new"]), tuple(self.running.items()),
                tuple(self.carried), self.collected, self.joined_dirty,
                tuple(self.fut), tuple(self.resolved_n), self.client_i,
                self.client_pc, self.ctrl_pc, self.in_hand)


class DispatchModel(ProtocolModel):
    """Protocol 4 — the double-buffered megastep handoff
    (paged/scheduler.py _mixed_megastep under overlap_dispatch=True):
    the host dispatches a fused megastep asynchronously, runs the next
    tick's admission work while the device computes, then FENCES on one
    device_get before replaying the token buffer into bookkeeping. Two
    invariants carry the overlap: the token buffer has a single owner
    at every instant (device until the fence retires it, host replay
    after), and the overlapped admission window only takes FREE pages —
    no page the in-flight dispatch's table references is ever freed or
    reassigned before the replay lands."""

    NAME = "dispatch"
    N = 2  # megastep rounds

    def __init__(self, mutations: Tuple[str, ...] = ()):
        super().__init__(mutations)
        self.round = 0
        self.host_pc = 0           # 0 dispatch, 1 overlap, 2 fence, 3 replay
        self.buf = "idle"          # idle | inflight | ready | fenced
        self.submitted = 0
        self.pending = 0
        self.free: List[int] = [10, 11]
        self.live: List[int] = [0]     # pages the running slot holds
        self.admitted: List[int] = []  # admitted mid-overlap, live next round
        self.live_at_dispatch: Tuple[int, ...] = ()
        self.bad_read = False

    def enabled(self) -> List[Action]:
        acts: List[Action] = []
        if self.submitted < 1:
            acts.append(Action("client", "submit", frozenset(),
                               frozenset({"pending"})))
        # the device retires the in-flight dispatch: reads the page
        # table / pool rows the host snapshot referenced, fills the
        # token buffer
        if self.buf == "inflight":
            acts.append(Action("device", f"compute({self.round})",
                               frozenset({"live"}),
                               frozenset({"buf"})))
        if self.round < self.N:
            if self.host_pc == 0 and self.buf == "idle":
                acts.append(Action("host", f"dispatch({self.round})",
                                   frozenset({"live"}),
                                   frozenset({"buf"})))
            elif self.host_pc == 1:
                acts.append(Action("host", "overlap_admit",
                                   frozenset({"pending", "free"}),
                                   frozenset({"pending", "free",
                                              "live"})))
            elif self.host_pc == 2:
                if self.buf == "ready" \
                        or "read_before_fence" in self.mutations:
                    # SEEDED DEFECT (read_before_fence): bookkeeping
                    # proceeds without waiting for the device_get — the
                    # replay reads a token buffer the device still owns
                    acts.append(Action("host", "fence",
                                       frozenset({"buf"}),
                                       frozenset({"buf"})))
            else:
                acts.append(Action("host", f"replay({self.round})",
                                   frozenset({"buf"}),
                                   frozenset({"buf", "live"})))
        return acts

    def apply(self, action: Action):
        op = action.label.split("(")[0]
        if op == "submit":
            self.pending += 1
            self.submitted += 1
        elif op == "compute":
            self.buf = "ready"
        elif op == "dispatch":
            self.live_at_dispatch = tuple(self.live)
            self.buf = "inflight"
            self.host_pc = 1
        elif op == "overlap_admit":
            if self.pending:
                if "admit_steals_live_page" in self.mutations \
                        and self.live:
                    # SEEDED DEFECT: admission grabs a page the
                    # in-flight dispatch's table still references —
                    # the replay lands against a stale page table
                    self.admitted.append(self.live.pop())
                    self.pending -= 1
                elif self.free:
                    self.admitted.append(self.free.pop())
                    self.pending -= 1
            self.host_pc = 2
        elif op == "fence":
            if self.buf == "inflight":
                self.bad_read = True
            self.buf = "fenced"
            self.host_pc = 3
        elif op == "replay":
            self.buf = "idle"
            self.live += self.admitted  # next dispatch's table sees them
            self.admitted = []
            self.round += 1
            self.host_pc = 0

    def check(self) -> List[str]:
        v: List[str] = []
        if self.bad_read:
            v.append("dispatch-buffer-owner: host bookkeeping read the "
                     "token buffer while the megastep was still in "
                     "flight — the fence did not retire it first")
        if self.buf in ("inflight", "ready"):
            gone = set(self.live_at_dispatch) - set(self.live)
            if gone:
                v.append("stale-page-table: page(s) "
                         f"{sorted(gone)} referenced by the in-flight "
                         "dispatch's table were reassigned before the "
                         "replay landed")
        return v

    def done(self) -> bool:
        return self.round == self.N and self.buf == "idle" \
            and self.submitted == 1

    def check_final(self) -> List[str]:
        total = len(self.free) + len(self.live) + len(self.admitted)
        if total != 3:
            return ["free-accounting: free + live + admitted pages "
                    f"number {total}, pool holds 3"]
        return []

    def key(self) -> tuple:
        return (self.round, self.host_pc, self.buf, self.submitted,
                self.pending, tuple(self.free), tuple(self.live),
                tuple(self.admitted), self.live_at_dispatch,
                self.bad_read)


PROTOCOLS = {m.NAME: m for m in
             (HandoffModel, TierPoolModel, SwapModel, DispatchModel)}


class InterleaveResult:
    """Outcome of one bounded interleaving exploration."""

    def __init__(self, model: str, explored: int, distinct: int,
                 hits: List[Tuple[str, str, Tuple[str, ...]]],
                 truncated: bool, bound: int):
        self.model = model
        self.explored = explored
        self.distinct = distinct
        self.hits = hits            # (invariant, detail, minimal trace)
        self.truncated = truncated
        self.bound = bound


def explore_interleavings(factory, max_switches: int = DEFAULT_SWITCH_BOUND,
                          max_states: int = 500_000,
                          max_findings: int = 4,
                          prune: bool = True) -> InterleaveResult:
    """BFS over every thread interleaving of the model up to
    `max_switches` context switches, with sleep-set pruning (disable
    via prune=False — tests assert the distinct-state set is identical
    either way, the soundness cross-check). check() runs on every
    generated state BEFORE dedup, so no violation is pruned away; the
    first trace reaching each invariant is minimal by BFS order."""
    root = factory()
    hits: List[Tuple[str, str, Tuple[str, ...]]] = []

    def record(found: List[str], trace: Tuple[str, ...]):
        for msg in found:
            name = msg.split(":", 1)[0]
            if all(h[0] != name for h in hits):
                hits.append((name, msg, trace))

    record(root.check(), ())
    frontier: deque = deque([(root, (), None, 0, frozenset())])
    visited: Dict[tuple, List[Tuple[int, FrozenSet[Action]]]] = {}
    distinct: Set[tuple] = {root.key()}
    explored = 0
    while frontier and len(hits) < max_findings \
            and explored < max_states:
        state, trace, last, sw, sleep = frontier.popleft()
        explored += 1
        acts = state.enabled()
        if not acts:
            if state.done():
                record(state.check_final(), trace)
            else:
                record(state.check_stuck(), trace)
            continue
        local_done: List[Action] = []
        for a in acts:
            if prune and a in sleep:
                continue
            nsw = sw + (1 if last is not None and a.tid != last else 0)
            if nsw > max_switches:
                continue
            child = state.clone()
            child.apply(a)
            ctrace = trace + (f"{a.tid}:{a.label}",)
            found = child.check()
            if found:
                record(found, ctrace)
                local_done.append(a)
                continue  # a broken state's successors prove nothing
            child_sleep = frozenset(
                b for b in (set(sleep) | set(local_done))
                if _independent(a, b)) if prune else frozenset()
            k = (child.key(), a.tid)
            dom = visited.get(k)
            if dom is not None and any(
                    psw <= nsw and pset <= child_sleep
                    for psw, pset in dom):
                local_done.append(a)
                continue
            visited.setdefault(k, []).append((nsw, child_sleep))
            distinct.add(child.key())
            frontier.append((child, ctrace, a.tid, nsw, child_sleep))
            local_done.append(a)
    return InterleaveResult(
        root.NAME, explored, len(distinct), hits,
        truncated=bool(frontier) and explored >= max_states,
        bound=max_switches)


def replay_interleaving(factory, trace) -> List[str]:
    """Re-execute a counterexample interleaving from the initial state
    and return every violation it produces (empty = does not
    reproduce). Each step is 'tid:label' as emitted in traces."""
    state = factory()
    out: List[str] = list(state.check())
    for step in trace:
        tid, label = step.split(":", 1)
        match = [a for a in state.enabled()
                 if a.tid == tid and a.label == label]
        if not match:
            out.append(f"replay-diverged: {step} not enabled")
            return out
        state.apply(match[0])
        out += state.check()
    if not state.enabled():
        out += state.check_final() if state.done() \
            else state.check_stuck()
    return out

# ---------------------------------------------------------------------------
# pass registration


def _interleaving_findings(ctx) -> List[Finding]:
    findings: List[Finding] = []
    mutations = tuple(getattr(ctx, "racecheck_mutations", ()) or ())
    bound = getattr(ctx, "racecheck_switch_bound", None) \
        or DEFAULT_SWITCH_BOUND
    trace_dir = getattr(ctx, "racecheck_trace_dir", None)
    summary: Dict[str, object] = {"switch_bound": bound, "models": {}}
    total_explored = 0
    total_distinct = 0
    for name in sorted(PROTOCOLS):
        model_cls = PROTOCOLS[name]
        res = explore_interleavings(
            lambda cls=model_cls: cls(mutations=mutations),
            max_switches=bound)
        total_explored += res.explored
        total_distinct += res.distinct
        summary["models"][name] = {
            "explored": res.explored,
            "distinct_states": res.distinct,
            "violations": len(res.hits),
            "truncated": res.truncated,
        }
        for inv, detail, trace in res.hits:
            spec = PROTOCOL_INVARIANTS.get(inv, detail)
            findings.append(Finding(
                "racecheck", "error", f"ilv-{inv}",
                f"racecheck:model/{name}",
                f"protocol invariant violated in the {name} model "
                f"under mutations {list(mutations)}: {detail}. "
                f"Invariant: {spec}. Minimal interleaving "
                f"({len(trace)} steps, switch bound {bound}): "
                + " -> ".join(trace)))
            if trace_dir:
                os.makedirs(trace_dir, exist_ok=True)
                path = os.path.join(
                    trace_dir, f"interleave-{name}-{inv}.json")
                with open(path, "w") as f:
                    json.dump({"model": name, "invariant": inv,
                               "mutations": list(mutations),
                               "switch_bound": bound,
                               "detail": detail,
                               "trace": list(trace),
                               "replay": ("flexflow_tpu.analysis."
                                          "racecheck."
                                          "replay_interleaving")},
                              f, indent=2)
        if res.truncated:
            findings.append(Finding(
                "racecheck", "warning", "ilv-truncated",
                f"racecheck:model/{name}",
                f"exploration of the {name} model was truncated at "
                f"{res.explored} states before exhausting switch "
                f"bound {bound} — coverage is partial"))
    summary["explored"] = total_explored
    summary["distinct_states"] = total_distinct
    ctx.racecheck_summary = summary
    findings.append(Finding(
        "racecheck", "info", "interleavings-explored",
        "racecheck:model",
        f"explored {total_explored} states "
        f"({total_distinct} distinct) across {len(PROTOCOLS)} "
        f"protocol models at context-switch bound {bound}; "
        f"{len(PROTOCOL_INVARIANTS)} invariant kinds asserted at "
        "every state"))
    return findings


@register_pass("racecheck")
def racecheck_pass(ctx) -> List[Finding]:
    paths = getattr(ctx, "racecheck_paths", None) or \
        default_lint_paths()
    findings = lint_paths(paths)
    n_err = sum(1 for f in findings if f.severity == "error")
    findings.append(Finding(
        "racecheck", "info", "lock-lint-summary", "racecheck:lint",
        f"lock-discipline lint over {len(RACE_ROOTS)} roots: "
        f"{len(findings)} finding(s), {n_err} error(s)"))
    if not getattr(ctx, "racecheck_lint_only", False):
        findings += _interleaving_findings(ctx)
    return findings
