"""Rule-corpus satisfiability pass.

For each rule in the substitution corpus, statically classify its
`when`/`where` guards against the op-type alphabet and attr domains, then
confirm with a dynamic witness (search.soundness.instantiate_rule — the
same instantiation the soundness suite uses, so statically-fireable ⊇
instantiable holds by construction):

  fireable             — a concrete matching graph exists (witness found)
  inert_unsatisfiable  — guards can never hold (unknown predicate,
                         attr_eq on a nonexistent field, unknown unary
                         kind, unknown mesh axis, ...) or no instantiation
                         profile realizes the pattern; per-rule reasons
                         are recorded

Fireable rules are additionally classified for reachability on the
BASELINE configs (direct pattern match on the built PCGs, unioned with
the committed coverage snapshot's observed fires): a fireable rule that
matches no baseline structure is `unreachable_on_baselines` — inert in
practice, but not a defect (info, not error).

This pass subsumes the counting logic that lived in
tools/rule_coverage.py; the classification is written into
docs/rule_coverage.json next to the search-measured fires/profit data.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass

# mesh-axis vocabulary the repo's meshes can carry (make_mesh callers);
# a requires_axis outside it gates the rule off every buildable mesh
KNOWN_AXES = ("data", "data_sub", "model", "seq", "expert", "pipe")

# unary kinds with a registered lowering (ops/jax_ops._element_unary);
# a unary_kind guard outside this set matches no executable node
UNARY_KINDS = frozenset({
    "exp", "sin", "cos", "relu", "gelu", "sigmoid", "tanh", "elu",
    "rsqrt", "silu", "identity", "pow", "scalar_add", "scalar_sub",
    "scalar_multiply", "scalar_truediv",
})


def _attr_fields(cls) -> frozenset:
    """Valid attribute names of an attrs class: dataclass fields plus
    properties (kdim/num_kv are properties)."""
    names = set()
    if dataclasses.is_dataclass(cls):
        names |= {f.name for f in dataclasses.fields(cls)}
    for k in dir(cls):
        if not k.startswith("_") and isinstance(getattr(cls, k), property):
            names.add(k)
    return frozenset(names)


def _attrs_class(op_name: str):
    from flexflow_tpu.ffconst import OpType
    from flexflow_tpu.ops import attrs as A
    from flexflow_tpu.search.xfer_engine import ATTRS_CLASSES

    # ops the engine can match but whose attrs class is not in the
    # rewrite-side registry
    extra = {
        OpType.RING_ATTENTION: A.RingAttentionAttrs,
        OpType.GATHER: A.GatherAttrs,
        OpType.TOPK: A.TopKAttrs,
    }
    try:
        op = OpType[op_name]
    except KeyError:
        return None
    return ATTRS_CLASSES.get(op) or extra.get(op)


def _static_issues(rule: Dict):
    """Guard conditions that can never hold, split into
    (matcher_issues, domain_issues):

    - matcher issues make find_matches reject every candidate (unknown
      predicate, attr_eq on a nonexistent field) — a dynamic witness
      contradicting one is a bug in THIS analyzer;
    - domain issues admit a synthetic match the instantiation harness
      can build but no EXECUTABLE graph can carry (a unary kind with no
      registered lowering, an unknown activation, a mesh axis no config
      builds) — authoritative even when a synthetic witness matches.
    """
    from flexflow_tpu.ffconst import ActiMode, OpType
    from flexflow_tpu.search.xfer_engine import (
        NODE_PREDICATES,
        WHERE_PREDICATES,
    )

    matcher: List[str] = []
    domain: List[str] = []
    ax = rule.get("requires_axis")
    if ax and ax not in KNOWN_AXES:
        domain.append(
            f"requires_axis={ax!r} is not a mesh axis any config builds "
            f"({', '.join(KNOWN_AXES)})")
    for spec in rule.get("src", {}).get("nodes", ()):
        nid = spec.get("id", "?")
        op_name = spec.get("type")
        if op_name:
            try:
                OpType[op_name]
            except KeyError:
                matcher.append(f"src node {nid!r}: unknown op type "
                               f"{op_name!r}")
                continue
        cls = _attrs_class(op_name) if op_name else None
        fields = _attr_fields(cls) if cls is not None else None
        for pname, parg in (spec.get("when") or {}).items():
            if pname not in NODE_PREDICATES:
                matcher.append(
                    f"src node {nid!r}: unknown predicate {pname!r} "
                    "(matcher rejects every candidate)")
                continue
            if pname == "attr_eq" and fields is not None:
                if (not isinstance(parg, (list, tuple)) or not parg
                        or not all(
                            isinstance(p, (list, tuple)) and len(p) == 2
                            for p in (parg
                                      if isinstance(parg[0], (list, tuple))
                                      else [parg]))):
                    matcher.append(
                        f"src node {nid!r}: malformed attr_eq argument "
                        f"{parg!r} (want [field, value] or a list of "
                        "such pairs)")
                    continue
                pairs = parg if isinstance(parg[0], (list, tuple)) \
                    else [parg]
                for f, v in pairs:
                    if f not in fields and v is not None:
                        matcher.append(
                            f"src node {nid!r}: attr_eq on field {f!r} "
                            f"which {cls.__name__} does not define")
            elif pname == "unary_kind":
                bad = [k for k in parg if k not in UNARY_KINDS]
                if bad:
                    domain.append(
                        f"src node {nid!r}: unary_kind {bad} has no "
                        "registered lowering — no executable node "
                        "carries it")
            elif pname in ("activation", "activation_in"):
                names = [parg] if isinstance(parg, str) else list(parg)
                bad = [n for n in names if n not in ActiMode.__members__]
                if bad:
                    domain.append(
                        f"src node {nid!r}: unknown activation {bad}")
    for w in rule.get("where", ()):
        if w.get("kind") not in WHERE_PREDICATES:
            matcher.append(
                f"unknown where predicate {w.get('kind')!r} "
                "(match check always fails)")
    return matcher, domain


def _dst_issues(rule: Dict) -> List[str]:
    """Rewrite-side hygiene: a dst node with literal attrs must have a
    registered attrs class, else apply_match raises mid-search."""
    from flexflow_tpu.ffconst import OpType
    from flexflow_tpu.search.xfer_engine import ATTRS_CLASSES

    out = []
    for spec in rule.get("dst", {}).get("nodes", ()):
        attrs = spec.get("attrs")
        if attrs is None or (isinstance(attrs, dict) and "$copy" in attrs):
            continue
        try:
            op = OpType[spec["type"]]
        except KeyError:
            out.append(f"dst node {spec.get('id')!r}: unknown op type "
                       f"{spec.get('type')!r}")
            continue
        if op not in ATTRS_CLASSES:
            out.append(
                f"dst node {spec.get('id')!r}: no attrs class registered "
                f"for {op.name} — apply_match would raise at rewrite time")
    return out


def _witness(rule: Dict) -> Optional[int]:
    """Smallest instantiation profile whose concrete graph the rule
    matches (the soundness suite's instantiation, minus the numeric
    replay), or None."""
    from flexflow_tpu.search.soundness import instantiate_rule
    from flexflow_tpu.search.xfer_engine import find_matches

    for nd in (2, 3, 4):
        try:
            inst = instantiate_rule(rule, profile_nd=nd)
            # find_matches inside the try too: a malformed guard can
            # crash a predicate (the analyzer must classify such a rule
            # inert, not die on it)
            if inst is not None and find_matches(rule, inst[0]):
                return nd
        except Exception:
            continue
    return None


def classify_rule(rule: Dict) -> Dict:
    """Classification record for one rule (no baseline reachability —
    that needs the built graphs, see classify_corpus)."""
    matcher, domain = _static_issues(rule)
    dst = _dst_issues(rule)
    rec: Dict = {"requires_axis": rule.get("requires_axis")}
    if domain:
        # a guard over values outside the executable domain can still be
        # matched by a synthetic instantiation — the domain issue wins
        rec["status"] = "inert_unsatisfiable"
        rec["reasons"] = domain + matcher
    else:
        nd = _witness(rule)
        if nd is not None:
            rec["status"] = "fireable"
            rec["witness_profile_nd"] = nd
            if matcher:
                # dynamic witness is authoritative for matcher-level
                # claims; a contradiction means THIS analyzer is wrong
                # about a guard — surface it
                rec["static_dynamic_disagreement"] = matcher
        else:
            rec["status"] = "inert_unsatisfiable"
            rec["reasons"] = matcher or [
                "no instantiation profile (2d/3d/4d) realizes a matching "
                "graph for the src pattern under its when/where guards"
            ]
    if dst:
        rec["dst_issues"] = dst
    return rec


def classify_corpus(rules: List[Dict],
                    baseline_graphs=None,
                    coverage_snapshot: Optional[Dict] = None) -> Dict[str, Dict]:
    """{rule_name: classification}. With `baseline_graphs`
    ([(config_name, Graph)]) fireable rules get `baseline_reach`:
    "fires_on_baselines" when the pattern matches a built BASELINE PCG
    directly or the committed coverage snapshot recorded a fire during
    search (rewritten intermediate graphs can expose structure the
    initial graph lacks), else "unreachable_on_baselines"."""
    from flexflow_tpu.search.xfer_engine import find_matches

    snapshot_fired = set()
    for fires in (coverage_snapshot or {}).get("fires_by_config",
                                               {}).values():
        snapshot_fired |= set(fires)

    out: Dict[str, Dict] = {}
    for rule in rules:
        rec = classify_rule(rule)
        if rec["status"] == "fireable" and baseline_graphs is not None:
            matched = []
            for cfg_name, g in baseline_graphs:
                try:
                    if find_matches(rule, g):
                        matched.append(cfg_name)
                except Exception:
                    pass
            rec["matched_baseline_configs"] = matched
            rec["snapshot_fired"] = rule["name"] in snapshot_fired
            rec["baseline_reach"] = (
                "fires_on_baselines"
                if matched or rec["snapshot_fired"]
                else "unreachable_on_baselines")
        out[rule["name"]] = rec
    return out


def classification_counts(classification: Dict[str, Dict]) -> Dict[str, int]:
    """Histogram of a classify_corpus result by effective status
    (baseline_reach when present, else status) — the single accounting
    used by the fflint CLI, --write-coverage, and tools/rule_coverage.py."""
    counts: Dict[str, int] = {}
    for rec in classification.values():
        key = rec.get("baseline_reach") or rec["status"]
        counts[key] = counts.get(key, 0) + 1
    return counts


@register_pass("rulesat")
def rulesat_pass(ctx: AnalysisContext) -> List[Finding]:
    if ctx.rules is None:
        return []
    cls = classify_corpus(ctx.rules, baseline_graphs=ctx.baseline_graphs,
                          coverage_snapshot=ctx.coverage_snapshot)
    ctx.rule_classification = cls
    findings: List[Finding] = []
    unreachable = []
    for name, rec in cls.items():
        if rec["status"] == "inert_unsatisfiable":
            findings.append(Finding(
                "rulesat", "error", "rule-unsatisfiable", name,
                "rule can never fire: " + "; ".join(rec["reasons"])))
        if rec.get("dst_issues"):
            findings.append(Finding(
                "rulesat", "error", "rule-dst-unbuildable", name,
                "; ".join(rec["dst_issues"])))
        if rec.get("static_dynamic_disagreement"):
            findings.append(Finding(
                "rulesat", "warning", "static-dynamic-disagreement", name,
                "static guard analysis deems the rule unsatisfiable but a "
                "concrete witness matches — the static rules here need "
                "fixing: " + "; ".join(rec["static_dynamic_disagreement"])))
        if rec.get("baseline_reach") == "unreachable_on_baselines":
            unreachable.append(name)
    if unreachable:
        findings.append(Finding(
            "rulesat", "info", "rules-unreachable-on-baselines",
            "corpus",
            f"{len(unreachable)}/{len(cls)} fireable rules match no "
            "BASELINE config structure (directly or in the recorded "
            "search fires) — sound but inert in practice; per-rule "
            "records in the classification output"))
    return findings
