"""Finding -> SARIF 2.1.0 serialization for tools/fflint.py --sarif.

One `run` per invocation; each Finding becomes a `result` with
  ruleId  = "<pass>/<code>"           (e.g. "hloaudit/hlo-hbm-budget")
  level   = error | warning | note    (info maps to note)
  location: a physical file/line when `where` looks like "path:123"
      (the hostsync pass), else a logical location carrying the subject
      string (config:entry:node for hloaudit, config:node for
      consistency, rule names for rulesat).

CI uploads the artifact (see .github/workflows/tests.yml) so code-scanning
UIs and reviewers get the same machine-readable findings the exit code
gates on.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List

from flexflow_tpu.analysis import Finding, Report

_LEVEL = {"error": "error", "warning": "warning", "info": "note"}

_FILE_LINE_RE = re.compile(r"^([\w./\-]+\.py):(\d+)$")


_SEV_RANK = {"info": 0, "warning": 1, "error": 2}


def _rules(findings: List[Finding]) -> List[Dict]:
    # a rule's default level is the MAX severity observed for it, so the
    # metadata is order-independent for mixed-severity rules (e.g.
    # hlo-entry-failed is warning for train/eval, info for decode)
    worst: Dict[str, str] = {}
    for f in findings:
        rid = f"{f.pass_name}/{f.code}"
        if _SEV_RANK[f.severity] >= _SEV_RANK.get(worst.get(rid), -1):
            worst[rid] = f.severity
    return [{
        "id": rid,
        "name": rid.split("/", 1)[1],
        "defaultConfiguration": {"level": _LEVEL[sev]},
    } for rid, sev in sorted(worst.items())]


def _location(f: Finding) -> Dict:
    m = _FILE_LINE_RE.match(f.where)
    if m:
        return {
            "physicalLocation": {
                "artifactLocation": {"uri": m.group(1)},
                "region": {"startLine": int(m.group(2))},
            }
        }
    return {
        "logicalLocations": [
            {"fullyQualifiedName": f.where, "kind": "member"}
        ]
    }


def report_to_sarif(report: Report) -> Dict:
    findings = report.findings
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "fflint",
                    "informationUri":
                        "https://github.com/flexflow/FlexFlow",
                    "rules": _rules(findings),
                }
            },
            "results": [{
                "ruleId": f"{f.pass_name}/{f.code}",
                "level": _LEVEL[f.severity],
                "message": {"text": f"{f.where}: {f.message}"},
                "locations": [_location(f)],
            } for f in findings],
        }],
    }


def write_sarif(report: Report, path: str) -> None:
    with open(path, "w") as fh:
        json.dump(report_to_sarif(report), fh, indent=1, sort_keys=True)
