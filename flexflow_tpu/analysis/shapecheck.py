"""shapecheck — static launch-shape-space auditor.

Every distinct input shape hitting one of Executor's `jax.jit` entry
points is a fresh XLA compilation. The serving hot paths are built so
that the set of reachable launch shapes per served config is CLOSED and
small (ragged windows capped at PREFILL_WINDOW_ROWS, pow2 prefill
buckets, fixed spec-tree node counts, one megastep program per ticks
knob) — a shape-polymorphic regression turns that into a compile storm
that blows TTFT SLOs in production. This pass proves the closure holds,
three ways:

  1. AST/dataflow arm: walks the launch sites in `paged/scheduler.py`,
     `spec/server.py`, `serving.py`, and `runtime/executor.py`, and
     classifies every symbolic width feeding a launch as *clamped*
     (derived through an explicit bound — `min(..., CAP)`, a pow2
     `_bucket`, or a config constant/attribute) or *unbounded* (derived
     from request-sized data like `len(prompt)` with no clamp).

  shape-space-unbounded (error)   a launch width taints back to
      request-sized data with no clamp on the path — every new request
      length compiles a fresh XLA program. The finding names the taint
      chain line by line.
  shape-space-over-budget (warning) a served config's enumerated
      shape space exceeds the compile budget (`--shape-budget`,
      default DEFAULT_SHAPE_BUDGET) — legal, but warmup pays one
      compile per shape, so the catalog size is an SLO input.
  shape-catalog-unsound (error)   a runtime compile event landed on a
      shape absent from the static catalog (check_soundness — the CI
      gate that keeps the enumeration honest).
  stale-pragma (info)             a '# fflint: shape-ok' pragma that no
      longer suppresses anything.

  2. Enumeration arm: `enumerate_catalog(...)` computes, per served
     config, the closed set of reachable launch shapes per jit entry
     point and the upper bound on distinct compilations — the
     machine-readable catalog lands in `stats.shapecheck` and drives
     `Executor.warm_launch_shapes` (obs/compile_tracker.py is the
     matching runtime arm).

  3. Soundness arm: `check_soundness(catalog, events)` diffs observed
     compile events (CompileTracker.observed()) against the catalog —
     steady-state serving after warmup must observe ZERO events, and
     every warmup event must be enumerated.

Suppression: a flagged launch line (or its enclosing loop header)
carrying `# fflint: shape-ok` / `# fflint: ignore` is skipped.
`jit_entry_points(path)` reports the jit call sites the pass saw, so a
gate test can assert the rule engaged (a clean scan proves nothing if
no entry point was seen).
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from typing import Dict, List, Optional, Sequence, Set, Tuple

from flexflow_tpu.analysis import AnalysisContext, Finding, register_pass

# The scheduler's packed-prefill window cap (paged/scheduler.py
# PREFILL_WINDOW_ROWS). Mirrored as a plain int so the pass never
# imports the serving stack (fflint must run on a bare checkout);
# tests/test_analysis.py asserts the two constants agree.
PREFILL_WINDOW_ROWS = 8

# Default upper bound on distinct compilations per served config before
# shape-space-over-budget fires (override via --shape-budget /
# AnalysisContext.shapecheck_budget).
DEFAULT_SHAPE_BUDGET = 64

# The four launch-shape-bearing hot-path files the AST arm audits,
# relative to the flexflow_tpu package root.
DEFAULT_SUBJECTS = ("paged/scheduler.py", "spec/server.py", "serving.py",
                    "runtime/executor.py")

# Methods whose call sites ARE ragged launches: positional index of the
# symbolic width argument (after self).
_LAUNCH_WIDTH_ARG = {"_launch": 1}

# Calls that CLAMP their argument into a closed family regardless of
# taint: the pow2 bucket maps any take into {8, 16, ..., bucket(cap)}.
_BUCKET_CALLS = {"_bucket", "bucket"}

# Calls whose result is request-sized data — the taint sources.
_UNBOUNDED_CALLS = {"len"}


def default_src_paths() -> List[str]:
    base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(base, p) for p in DEFAULT_SUBJECTS]


# ---------------------------------------------------------------------------
# AST/dataflow arm


def _dotted(node: ast.AST) -> Optional[tuple]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _short(node: ast.AST, limit: int = 48) -> str:
    try:
        txt = ast.unparse(node)
    except Exception:
        txt = type(node).__name__
    return txt if len(txt) <= limit else txt[:limit - 3] + "..."


def _is_directive(txt: str) -> bool:
    if "fflint:" not in txt:
        return False
    directive = txt.split("fflint:", 1)[1].strip()
    return directive.startswith("shape-ok") or directive.startswith("ignore")


def _is_own_directive(txt: str) -> bool:
    """Only shape-ok pragmas are OURS to flag stale — a shared
    '# fflint: ignore' may be earning its keep for another pass."""
    if "fflint:" not in txt:
        return False
    return txt.split("fflint:", 1)[1].strip().startswith("shape-ok")


def _comment_map(src: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass  # ast.parse already succeeded; a tokenizer hiccup only
        # costs pragma visibility, never findings
    return out


def _suppressed(comments: Dict[int, str], *linenos: int) -> Optional[int]:
    for ln in linenos:
        if _is_directive(comments.get(ln, "")):
            return ln
    return None


# taint = (unbounded: bool, chain: [(lineno, description), ...]).
_CLAMPED = (False, [])


class _TaintScanner(ast.NodeVisitor):
    """Intra-function dataflow over the symbolic widths feeding launch
    sites. Deliberately OPTIMISTIC at unknowns (params, attributes,
    unrecognized calls default to clamped): the error is reserved for a
    width that DEFINITELY taints back to request-sized data — same
    direct-body, low-noise contract as the hostsync pass."""

    def __init__(self, findings, rel, comments, fn_name,
                 used_pragmas: Set[int]):
        self.findings = findings
        self.rel = rel
        self.comments = comments
        self.fn_name = fn_name
        self.loop_stack: List[int] = []
        self.used_pragmas = used_pragmas
        self.state: Dict[str, tuple] = {}

    # -- classification ---------------------------------------------------

    def _classify(self, node: ast.AST) -> tuple:
        if isinstance(node, ast.Constant):
            return _CLAMPED
        if isinstance(node, ast.Name):
            return self.state.get(node.id, _CLAMPED)
        if isinstance(node, ast.Attribute):
            # self.prefill_chunk / self.spec.max_nodes / module constants:
            # config-derived, bounded by construction
            return _CLAMPED
        if isinstance(node, ast.Call):
            return self._classify_call(node)
        if isinstance(node, ast.BinOp):
            lu, lc = self._classify(node.left)
            ru, rc = self._classify(node.right)
            return (lu or ru, lc + rc)
        if isinstance(node, ast.UnaryOp):
            return self._classify(node.operand)
        if isinstance(node, ast.IfExp):
            bu, bc = self._classify(node.body)
            ou, oc = self._classify(node.orelse)
            return (bu or ou, bc + oc)
        return _CLAMPED

    def _classify_call(self, node: ast.Call) -> tuple:
        d = _dotted(node.func)
        fname = d[-1] if d else None
        if fname in _UNBOUNDED_CALLS:
            return (True, [(node.lineno, _short(node))])
        if fname in _BUCKET_CALLS:
            # pow2 bucketing maps any input into a closed family — an
            # explicit bound in the ISSUE's sense. (An uncapped bucket of
            # a raw length is still one compile per pow2 class; the
            # enumeration arm prices that family, it is not a storm.)
            return _CLAMPED
        if fname == "min":
            results = [self._classify(a) for a in node.args]
            if any(not u for u, _ in results):
                return _CLAMPED  # one clamped operand bounds the min
            chain = [c for u, ch in results if u for c in ch]
            return (bool(chain), chain)
        if fname in ("max", "sum"):
            # max/sum are unbounded as soon as ONE operand is
            results = [self._classify(a) for a in node.args]
            chain = [c for u, ch in results if u for c in ch]
            return (bool(chain), chain)
        return _CLAMPED

    # -- statement walking ------------------------------------------------

    def _assign_name(self, name: str, value: ast.AST, lineno: int):
        u, chain = self._classify(value)
        if u and (not chain or chain[-1][0] != lineno):
            chain = chain + [(lineno, f"{name} = {_short(value)}")]
        self.state[name] = (u, chain)

    def visit_Assign(self, node):
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self._assign_name(tgt.id, node.value, node.lineno)
            elif isinstance(tgt, ast.Tuple) and isinstance(node.value,
                                                           ast.Tuple) \
                    and len(tgt.elts) == len(node.value.elts):
                for t, v in zip(tgt.elts, node.value.elts):
                    if isinstance(t, ast.Name):
                        self._assign_name(t.id, v, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            prev = self.state.get(node.target.id, _CLAMPED)
            u, chain = self._classify(node.value)
            self.state[node.target.id] = (prev[0] or u, prev[1] + chain)
        self.generic_visit(node)

    # nested defs are separate scopes (same contract as hostsync)
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def _loop(self, node):
        self.loop_stack.append(node.lineno)
        self.generic_visit(node)
        self.loop_stack.pop()

    visit_For = visit_While = _loop

    def _add(self, severity, code, lineno, msg):
        used = _suppressed(self.comments, lineno, *self.loop_stack)
        if used is not None:
            self.used_pragmas.add(used)
            return
        self.findings.append(Finding(
            "shapecheck", severity, code, f"{self.rel}:{lineno}",
            f"in {self.fn_name}(): {msg}"))

    def visit_Call(self, node):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _LAUNCH_WIDTH_ARG:
            idx = _LAUNCH_WIDTH_ARG[node.func.attr]
            width = None
            if len(node.args) > idx:
                width = node.args[idx]
            else:
                for kw in node.keywords:
                    if kw.arg == "window":
                        width = kw.value
            if width is not None:
                u, chain = self._classify(width)
                if u:
                    steps = chain + [(node.lineno,
                                      f"launch width {_short(width)}")]
                    trace = " -> ".join(
                        f"line {ln}: {d}" for ln, d in steps)
                    self._add(
                        "error", "shape-space-unbounded", node.lineno,
                        f"launch width {_short(width)!r} derives from "
                        "request-sized data with no clamp — every new "
                        "value compiles a fresh XLA program (a compile "
                        "storm under real traffic); bound it with "
                        "min(..., CAP), a pow2 bucket, or a config "
                        f"constant. taint: {trace}")
        self.generic_visit(node)


def jit_entry_points(path: str) -> List[Dict]:
    """Every `jax.jit(...)` call site in `path`, with the enclosing
    function scope ({scope, line} per site). A gate test pairs this with
    scan_file: a clean scan only proves closure when the entry points
    were actually seen."""
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    out: List[Dict] = []

    def walk(node: ast.AST, scope: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, child.name)
                continue
            if isinstance(child, ast.Call):
                d = _dotted(child.func)
                if d and d[-1] == "jit":
                    out.append({"scope": scope, "line": child.lineno})
            walk(child, scope)

    walk(tree, "<module>")
    return out


def scan_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    rel = rel or os.path.basename(path)
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("shapecheck", "error", "syntax-error",
                        f"{rel}:{e.lineno}", str(e))]
    comments = _comment_map(src)
    findings: List[Finding] = []
    used_pragmas: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scanner = _TaintScanner(findings, rel, comments, node.name,
                                    used_pragmas)
            for child in node.body:
                scanner.visit(child)
    for ln, txt in sorted(comments.items()):
        if _is_own_directive(txt) and ln not in used_pragmas:
            findings.append(Finding(
                "shapecheck", "info", "stale-pragma", f"{rel}:{ln}",
                "'# fflint: shape-ok' pragma no longer suppresses any "
                "finding — delete it (stale annotations rot into blanket "
                "noise)"))
    findings.sort(key=lambda f: f.where)
    return findings


def scan_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, files in os.walk(p):
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        rel = os.path.relpath(
                            full, os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
                        findings += scan_file(full, rel)
        elif os.path.exists(p):
            findings += scan_file(p, os.path.basename(p))
    return findings


# ---------------------------------------------------------------------------
# Enumeration arm — the closed launch-shape catalog per served config


def _pow2_buckets(n: int) -> List[int]:
    """Reachable `_bucket(take)` values for take in 1..n: {8, ..., bucket(n)}."""
    vals = []
    b = 8
    while b < n:
        vals.append(b)
        b *= 2
    vals.append(b)
    return vals


def _dense_prefill_lens(max_len: int) -> List[int]:
    """Dense admission pads to min(_bucket(len(seq)), max_len)."""
    vals = {b for b in _pow2_buckets(max_len) if b < max_len}
    vals.add(max_len)
    return sorted(vals)


def _packed_prefill_shapes(slots: int, chunk: int,
                           cap: int = PREFILL_WINDOW_ROWS) -> Set[Tuple[int, int]]:
    """Closed (n_items, window) family of the scheduler's ragged-packed
    prefill tick: W = min(cap, largest take this tick); each planned
    slot's take splits into ceil(take/W) pieces, all packed into ONE
    launch; the shared per-tick token budget bounds sum(take) by
    prefill_chunk. For W < cap, W IS the largest take, so every take
    fits one piece and n_items <= 1 + min(slots-1, chunk-W). At W == cap
    takes may exceed the window and split, so n_items is bounded by the
    worst split: k-1 single-row takes plus one take of the remaining
    budget."""
    shapes: Set[Tuple[int, int]] = set()
    for W in range(1, min(cap, chunk) + 1):
        bmax = 1 + min(slots - 1, chunk - W)
        if W == cap:
            for k in range(1, min(slots, chunk) + 1):
                big = chunk - (k - 1)
                if big >= W:
                    bmax = max(bmax, (k - 1) + -(-big // W))
        for B in range(1, bmax + 1):
            shapes.add((B, W))
    return shapes


def enumerate_catalog(*, slots: int, max_len: int, paged: bool = True,
                      page_size: int = 64,
                      prefill_chunk: int = 64, ragged_pack: bool = True,
                      megastep_ticks: int = 1,
                      megastep_mixed: bool = False,
                      spec_max_nodes: Optional[int] = None,
                      spec_depth: Optional[int] = None,
                      num_pages: Optional[int] = None,
                      kv_dtype: str = "auto",
                      window_rows: int = PREFILL_WINDOW_ROWS) -> Dict:
    """The closed set of reachable launch shapes per jit entry point for
    ONE served config, plus the config echo `Executor.warm_launch_shapes`
    needs to rebuild the launch arguments (table width, pool size,
    dtype). Shapes are the CompileTracker's canonical signatures — the
    ids/window dims of each entry's symbolic argument — so observed
    compile events diff directly against the catalog
    (check_soundness)."""
    slots = int(slots)
    max_len = int(max_len)
    entries: Dict[str, Dict] = {}

    def entry(name: str, shapes) -> None:
        uniq = sorted({tuple(int(x) for x in s) for s in shapes})
        entries[name] = {"shapes": [list(s) for s in uniq],
                         "count": len(uniq)}

    if paged:
        ragged: Set[Tuple[int, int]] = {(slots, 1)}  # decode tick
        if ragged_pack:
            ragged |= _packed_prefill_shapes(slots, int(prefill_chunk),
                                             int(window_rows))
        else:
            ragged |= {(1, W) for W in _pow2_buckets(int(prefill_chunk))}
        if spec_max_nodes:
            T = int(spec_max_nodes)
            if ragged_pack:
                # verify packs only drafting + sampled-root slots —
                # idle/mid-prefill slots pack nothing
                ragged |= {(b, T) for b in range(1, slots + 1)}
            else:
                ragged |= {(slots, T)}
        entry("ragged_step", ragged)
        if megastep_ticks > 1 and not megastep_mixed:
            entry("megastep", [(slots, int(megastep_ticks))])
        if megastep_mixed:
            # the universal megastep compiles ONE program per config:
            # its launch window is the derived max over the prefill
            # window and the on-device drafted chain (depth+1); it
            # replaces the pure-decode megastep even at ticks == 1
            # (the fusion of mixed rows is the point, not the tick
            # count)
            wl = max(min(int(window_rows), int(prefill_chunk)),
                     (int(spec_depth) if spec_depth else 0) + 1)
            entry("megastep_mixed",
                  [(slots, int(megastep_ticks), wl)])
        if spec_max_nodes:
            depth = int(spec_depth) if spec_depth else 1
            entry("paged_commit", [(slots, depth + 1)])
    else:
        dense = {(slots, 1)}
        dense |= {(1, L) for L in _dense_prefill_lens(max_len)}
        entry("decode_step", dense)
    # the shared sampling program sees (slots, V) decode rows and (1, V)
    # first-token rows; V is a model property, so the catalog keys the
    # batch dim only
    entry("pick_tokens", [(slots,), (1,)])

    slack = int(spec_max_nodes) if spec_max_nodes else 0
    table_cols = -(-(max_len + slack) // int(page_size)) if paged else 0
    if paged and num_pages is None:
        num_pages = slots * table_cols + 1
    return {
        "version": 1,
        "config": {
            "slots": slots, "max_len": max_len, "paged": bool(paged),
            "page_size": int(page_size) if paged else None,
            "prefill_chunk": int(prefill_chunk) if paged else None,
            "ragged_pack": bool(ragged_pack),
            "megastep_ticks": int(megastep_ticks),
            "megastep_mixed": bool(megastep_mixed),
            "spec_max_nodes": int(spec_max_nodes) if spec_max_nodes else None,
            "spec_depth": int(spec_depth) if spec_depth else None,
            "num_pages": int(num_pages) if num_pages else None,
            "table_cols": table_cols,
            "kv_dtype": str(kv_dtype),
            "window_rows": int(window_rows),
        },
        "entries": entries,
        "total_compilations": sum(e["count"] for e in entries.values()),
    }


def catalog_for_strategy(strategy, *, slots: int, max_len: int) -> Dict:
    """enumerate_catalog for a search/servesearch.ServeStrategy — the
    `tools/servesearch.py explain` compile_cost line prices this."""
    sp = strategy.spec_config()
    kw = strategy.to_server_kwargs(slots=slots, max_len=max_len)
    return enumerate_catalog(
        slots=slots, max_len=max_len, paged=True,
        page_size=kw["page_size"], prefill_chunk=kw["prefill_chunk"],
        ragged_pack=kw["ragged_pack"],
        megastep_ticks=kw["megastep_ticks"],
        megastep_mixed=kw.get("megastep_mixed", False),
        spec_max_nodes=sp.max_nodes if sp else None,
        spec_depth=sp.depth if sp else None,
        num_pages=kw["num_pages"], kv_dtype=kw["kv_dtype"])


def union_catalogs(*catalogs: Dict) -> Dict:
    """Merge launch-shape catalogs into one whose entries enumerate the
    UNION of every input's shapes — the catalog a drain-and-swap
    cutover is judged against (serving_autopilot): while requests from
    both sides are in flight, a compile event is sound if EITHER
    strategy's enumeration reaches it. Configs are kept as a list for
    provenance; total_compilations is recomputed over the union (shapes
    shared by both sides count once — warmed once, reused across the
    swap)."""
    if not catalogs:
        raise ValueError("union_catalogs needs at least one catalog")
    merged: Dict[str, Set[Tuple[int, ...]]] = {}
    configs = []
    for cat in catalogs:
        configs.append(cat.get("config", {}))
        for name, ent in cat.get("entries", {}).items():
            merged.setdefault(name, set()).update(
                tuple(int(x) for x in s) for s in ent.get("shapes", ()))
    entries = {name: {"shapes": [list(s) for s in sorted(shapes)],
                      "count": len(shapes)}
               for name, shapes in sorted(merged.items())}
    return {
        "version": 1,
        "config": {"union": configs},
        "entries": entries,
        "total_compilations": sum(e["count"] for e in entries.values()),
    }


def check_soundness(catalog: Dict, events: Sequence[Dict]) -> List[Finding]:
    """Diff observed compile events (CompileTracker.observed()) against a
    static catalog: any event whose (entry, shape) is not enumerated is a
    `shape-catalog-unsound` error naming the witness — the gate that
    keeps the enumeration honest (and that a deliberately shrunk catalog
    must fail)."""
    findings: List[Finding] = []
    entries = catalog.get("entries", {})
    for ev in events:
        name = ev.get("entry", "<unknown>")
        shape = tuple(int(x) for x in ev.get("shape", ()))
        known = {tuple(s) for s in entries.get(name, {}).get("shapes", ())}
        if shape not in known:
            findings.append(Finding(
                "shapecheck", "error", "shape-catalog-unsound",
                f"shapecheck:catalog/{name}",
                f"observed compile event for entry '{name}' at shape "
                f"{shape} is absent from the static catalog "
                f"(enumerated: {sorted(known) or 'no shapes'}) — the "
                f"enumeration missed a reachable launch shape; witness "
                f"event: {dict(ev)}"))
    return findings


# ---------------------------------------------------------------------------
# Registered pass

# The served configs the repo-level pass prices: the serve_generation
# defaults each decode path ships with (BASELINE-shaped, small enough to
# enumerate instantly). Override via AnalysisContext.shapecheck_configs.
DEFAULT_CONFIGS = {
    "paged_base": dict(slots=4, max_len=128, page_size=16,
                       prefill_chunk=32, ragged_pack=True),
    "paged_megastep": dict(slots=4, max_len=128, page_size=16,
                           prefill_chunk=32, megastep_ticks=8),
    "paged_mixed": dict(slots=4, max_len=128, page_size=16,
                        prefill_chunk=32, megastep_ticks=8,
                        megastep_mixed=True),
    "paged_spec": dict(slots=4, max_len=128, page_size=16,
                       prefill_chunk=32, spec_max_nodes=9, spec_depth=4),
    "paged_legacy": dict(slots=4, max_len=128, page_size=16,
                         prefill_chunk=32, ragged_pack=False),
    "dense": dict(slots=4, max_len=128, paged=False),
}


@register_pass("shapecheck")
def shapecheck_pass(ctx: AnalysisContext) -> List[Finding]:
    paths = ctx.src_paths if ctx.src_paths is not None else default_src_paths()
    findings = scan_paths(paths)
    budget = (int(ctx.shapecheck_budget) if ctx.shapecheck_budget
              else DEFAULT_SHAPE_BUDGET)
    configs = (ctx.shapecheck_configs if ctx.shapecheck_configs is not None
               else DEFAULT_CONFIGS)
    catalogs: Dict[str, Dict] = {}
    for name in sorted(configs):
        cat = enumerate_catalog(**configs[name])
        catalogs[name] = cat
        total = cat["total_compilations"]
        if total > budget:
            per = ", ".join(f"{e}={d['count']}"
                            for e, d in sorted(cat["entries"].items()))
            findings.append(Finding(
                "shapecheck", "warning", "shape-space-over-budget",
                f"shapecheck:config/{name}",
                f"config '{name}' reaches {total} distinct compilations "
                f"(> budget {budget}; {per}) — warmup pays one compile "
                "per shape, so either shrink the knobs (prefill_chunk, "
                "slots) or raise --shape-budget deliberately"))
    inventory: Dict[str, List[Dict]] = {}
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            try:
                inventory[os.path.basename(p)] = jit_entry_points(p)
            except SyntaxError:
                pass  # scan_file already reported it
    ctx.shapecheck_summary = {
        "budget": budget,
        "catalogs": catalogs,
        "entry_points": inventory,
    }
    findings.sort(key=lambda f: (f.severity != "error", f.where))
    return findings
