"""Runtime configuration.

Reference analog: `FFConfig` (include/flexflow/config.h:92-160) and its argv
parser (`FFModel::parse_args`, model.cc:3556-3719). GPU-count/Legion flags
become device-mesh configuration; the search/profiling/fusion flags carry
over with the same names where they make sense on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from flexflow_tpu.ffconst import CompMode, DataType, ParamSyncType


@dataclasses.dataclass
class FFConfig:
    # ---- training loop ----
    batch_size: int = 64
    epochs: int = 1
    seed: int = 42
    # truncated-sequence iteration config (reference FFIterationConfig
    # config.h:162-167): forward/backward may run a shorter seq length
    seq_length: Optional[int] = None

    # ---- devices / mesh ----
    # number of devices to use (None = all visible jax devices); the
    # reference analog is `-ll:gpu` × numNodes
    num_devices: Optional[int] = None
    # explicit mesh shape: ordered {axis_name: size}; None = let compile()
    # derive it from the chosen strategy (e.g. {"data": 8} for pure DP)
    mesh_shape: Optional[Dict[str, int]] = None

    # ---- numerics ----
    compute_dtype: DataType = DataType.FLOAT
    param_sync: ParamSyncType = ParamSyncType.PSUM

    # ---- strategy search (reference model.cc:3599-3719 flags) ----
    search_budget: int = 0
    search_alpha: float = 1.05
    # search already requires search_budget > 0; this flag force-disables it
    # (reference --only-data-parallel, model.cc:3609 — off by default there too)
    only_data_parallel: bool = False
    # SOAP dimension gates for the search space (reference
    # --enable-parameter-parallel / --enable-attribute-parallel,
    # model.cc:3613-3617). The reference defaults these off; TPU-native
    # default is on — weight/head sharding is the normal operating mode,
    # set False to restrict the search to sample parallelism.
    enable_parameter_parallel: bool = True
    enable_attribute_parallel: bool = True
    # per-op submesh placement (reference MachineView{start_device_id,
    # stride}, machine_view.h:14-96): split the data axis into
    # data x data_sub so ops whose batch dim cannot divide the full data
    # group shard over a DEVICE SUBSET (replicated across the rest)
    # instead of degrading to full replication; the view space offers
    # both the full-group and subset points (search/space.py)
    enable_submesh: bool = False
    memory_search: bool = False
    # search for a machine bigger than the one running (reference
    # --search-num-workers, model.cc:3692); extra chips extend `data`
    search_num_devices: Optional[int] = None
    machine_model_file: Optional[str] = None
    # measure real per-op shard times on the local device and use them in
    # the search cost model (reference measure_operator_cost discipline,
    # simulator.cc:537); cache file avoids re-measuring across runs
    measure_costs: bool = False
    # after the model-based search, compile the top-k candidate strategies'
    # REAL train steps and keep the empirically fastest (SURVEY §7: XLA
    # fusion makes op-sum != program time, so the final ranking is timed,
    # not modeled). 0/1 = off; costs k-1 extra compiles at compile() time.
    validate_top_k: int = 0
    measure_cache_file: Optional[str] = None
    # cost strategies with the native event-driven simulator instead of the
    # summed-table estimate (Simulator::simulate_runtime analog): the Unity
    # search ranks every candidate with the PER-DEVICE task simulator
    # (search/eventsim.py -> ffsim_tasksim_*), and the playoff pool re-rank
    # / MCMC objective use it too. Default ON; degrades to the serial sum
    # when libffsim is unavailable. --no-simulator disables.
    use_simulator: bool = True
    import_strategy_file: Optional[str] = None
    export_strategy_file: Optional[str] = None
    export_strategy_computation_graph_file: Optional[str] = None
    include_costs_dot_graph: bool = False

    # periodic training checkpoints (net-new vs the reference, SURVEY.md
    # §5.4): every `checkpoint_every` steps fit() writes
    # checkpoint_dir/step_N (orbax if available, else npz) + latest.json
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0

    # ---- execution ----
    profiling: bool = False
    # capture a jax profiler trace of fit() into this dir (view with
    # tensorboard / xprof — the -lg:prof analog, SURVEY.md §5.1)
    profiler_trace_dir: Optional[str] = None
    # jax transfer guard level during fit ("log" | "disallow"): surfaces
    # accidental host<->device transfers in the step loop (the
    # race-detection analog, SURVEY.md §5.2 — purity is by construction,
    # transfers are the remaining foot-gun)
    transfer_guard: Optional[str] = None
    # rematerialization: "attention" wraps attention ops in jax.checkpoint so
    # S×S probs are recomputed in backward instead of saved (HBM for FLOPs —
    # net-new vs the reference, which has no remat); "hidden" instead
    # recomputes MLP hidden activations (SwiGLU gate/up/silu/mul, expanding
    # Linear+activation chains) — the dominant saved-activation HBM at LLM
    # shapes for ~2% extra FLOPs; "none" disables
    remat: str = "attention"
    # op fusion: on TPU XLA fuses inside one jitted program for free; this
    # flag only controls whether the PCG keeps explicit FusedOp groups for
    # search costing (reference --fusion, model.cc:2965)
    perform_fusion: bool = False
    comp_mode: CompMode = CompMode.TRAINING
    # donate params/opt-state buffers to the jitted step (halves HBM)
    donate_buffers: bool = True

    # populated by FFModel at compile time
    _devices: Optional[List] = dataclasses.field(default=None, repr=False)

    @property
    def devices(self) -> List:
        if self._devices is None:
            import jax

            devs = jax.devices()
            n = self.num_devices or len(devs)
            self._devices = devs[:n]
        return self._devices

    @property
    def workers_per_node(self) -> int:
        return len(self.devices)

    @classmethod
    def from_args(cls, argv: Sequence[str]) -> "FFConfig":
        """Parse reference-style command-line flags (model.cc:3556-3719)."""
        cfg = cls()
        args = list(argv)
        i = 0

        def take() -> str:
            nonlocal i
            i += 1
            if i >= len(args):
                raise ValueError(f"flag {args[i - 1]!r} requires a value")
            return args[i]

        while i < len(args):
            a = args[i]
            if a in ("-b", "--batch-size"):
                cfg.batch_size = int(take())
            elif a in ("-e", "--epochs"):
                cfg.epochs = int(take())
            elif a == "--seed":
                cfg.seed = int(take())
            elif a == "--checkpoint-dir":
                cfg.checkpoint_dir = take()
            elif a == "--checkpoint-every":
                cfg.checkpoint_every = int(take())
            elif a in ("--devices", "-ll:gpu", "-ll:tpu"):
                cfg.num_devices = int(take())
            elif a == "--mesh":
                # e.g. --mesh data=2,model=4 (net-new: explicit mesh axes)
                cfg.mesh_shape = {
                    k: int(v)
                    for k, v in (p.split("=") for p in take().split(","))
                }
            elif a == "--budget" or a == "--search-budget":
                cfg.search_budget = int(take())
            elif a == "--validate-top-k":
                cfg.validate_top_k = int(take())
            elif a == "--alpha" or a == "--search-alpha":
                cfg.search_alpha = float(take())
            elif a == "--only-data-parallel":
                cfg.only_data_parallel = True
            elif a == "--search":
                cfg.only_data_parallel = False
            elif a == "--enable-parameter-parallel":
                cfg.enable_parameter_parallel = True
            elif a == "--enable-attribute-parallel":
                # the reference sets parameter-parallel here too (noted as an
                # upstream bug in SURVEY.md §2.3); we keep them independent
                cfg.enable_attribute_parallel = True
            elif a == "--enable-submesh":
                cfg.enable_submesh = True
            elif a == "--simulator":
                cfg.use_simulator = True
            elif a == "--no-simulator":
                cfg.use_simulator = False
            elif a == "--profiler-trace":
                cfg.profiler_trace_dir = take()
            elif a == "--transfer-guard":
                cfg.transfer_guard = take()
            elif a == "--memory-search":
                cfg.memory_search = True
            elif a == "--search-num-devices":
                cfg.search_num_devices = int(take())
            elif a == "--machine-model-file":
                cfg.machine_model_file = take()
            elif a == "--import-strategy" or a == "--import":
                cfg.import_strategy_file = take()
            elif a == "--export-strategy" or a == "--export":
                cfg.export_strategy_file = take()
            elif a == "--compgraph":
                cfg.export_strategy_computation_graph_file = take()
            elif a == "--include-costs-dot-graph":
                cfg.include_costs_dot_graph = True
            elif a == "--profiling":
                cfg.profiling = True
            elif a == "--fusion":
                cfg.perform_fusion = True
            elif a == "--inference":
                cfg.comp_mode = CompMode.INFERENCE
            # unknown flags are ignored (the reference passes extras to Legion)
            i += 1
        return cfg
