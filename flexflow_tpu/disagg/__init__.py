"""Disaggregated serving (docs/disaggregation.md): host-memory KV tier
behind the page pool's LRU dead list, prefill/decode worker split with
per-request page adoption through the tier, and a prefix-affinity
router fronting N serving instances."""

from flexflow_tpu.disagg.host_tier import HostTier
from flexflow_tpu.disagg.router import PrefixAffinityRouter
from flexflow_tpu.disagg.workers import DisaggPair, PrefillWorker

__all__ = ["HostTier", "PrefixAffinityRouter", "DisaggPair",
           "PrefillWorker"]
