"""Host-memory KV tier behind the PagePool's LRU dead list.

Pages are CONTENT-ADDRESSED (paged/pool.py): a full page is named by the
sha1 chain hash of the entire token prefix it closes. That makes a host
tier almost free to express — spilling a page is a dict move keyed by
its hash (`device_get` of the page's rows, including the int8 scale
sidecar leaves, into host numpy), and fetching it back is a `device_put`
into a freshly allocated page plus re-registration under the same hash.
No address translation, no per-owner fixups: hashes are stable across
defrag, preemption, even across POOLS — which is exactly what the
prefill/decode KV-transfer path (disagg/workers.py) rides.

Tier state machine (docs/disaggregation.md):

    resident (in pool._full, has a device page)
        │ LRU eviction under allocation pressure /
        │ explicit handoff spill (PagePool.spill_request)
        ▼
    spilled (in HostTier, hash -> host payload; registered-but-
        │    not-resident: NO device page, NOT in pool._full)
        │ lookup hit on the spilled hash / prefetch
        ▼
    resident again (fresh page, payload device_put back, re-registered)

An entry is in EXACTLY one place at a time: the pool unregisters before
it spills, and a fetch POPS the tier entry before re-registering — the
"resident ⊎ spilled partitions the hash index" invariant
(analysis/pool_invariants.py `tier-partition`). The tier itself is
bounded (capacity_pages) with LRU eviction of its own: a spill beyond
capacity drops the OLDEST tier entry — that prefix misses and recomputes,
the same failure mode as an untiered pool, just much further away.

The tier holds OPAQUE payloads. The pool moves them via the
reader/writer closures handed to `PagePool.attach_tier` — the scheduler
supplies device closures (paged/scheduler.py `_tier_read_page` /
`_tier_write_page`), the poolcheck model supplies bookkeeping mirrors,
and plain pool unit tests can use anything hashable. Payloads carry the
scale sidecar alongside the K/V rows ("scales travel with their page").

A HostTier SHARED between two servers' pools is the KV-transfer channel
of the prefill/decode split: the prefill worker spills a finished
request's pages into it and the decode worker's admission lookup fetches
them out — per-request page adoption through host RAM, generalizing
`adopt_pool_from`'s whole-pool swap.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple


class HostTier:
    """Bounded host-RAM store of spilled KV pages, keyed by the pool's
    prefix chain hashes. Thread-safe: the prefill worker's loop spills
    while the decode worker's loop fetches."""

    def __init__(self, capacity_pages: int = 1024):
        if capacity_pages < 1:
            raise ValueError(
                f"capacity_pages must be >= 1, got {capacity_pages}")
        self.capacity_pages = int(capacity_pages)
        # hash -> opaque payload (the page's rows + scale sidecar, in
        # whatever form the attached reader produced), oldest first
        self._entries: "OrderedDict[str, object]" = OrderedDict()
        self._lock = threading.Lock()
        # counters (scraped into ff_kv_spill_pages_total /
        # ff_kv_fetch_pages_total and the host-tier gauges)
        self.spilled_pages_total = 0
        self.fetched_pages_total = 0
        self.dropped_pages_total = 0   # tier-capacity evictions
        self.fetch_seconds_total = 0.0  # device_put side, timed by caller

    # -- query ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def occupancy_pages(self) -> int:
        with self._lock:
            return len(self._entries)

    def contains(self, chain_hash: str) -> bool:
        with self._lock:
            return chain_hash in self._entries

    def hashes(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def peek(self, chain_hash: str):
        """Read a payload WITHOUT popping it (invariant checks; the
        serving path always uses fetch's move semantics)."""
        with self._lock:
            return self._entries.get(chain_hash)

    # -- spill / fetch ------------------------------------------------------

    def spill(self, chain_hash: str, payload) -> None:
        """Store one page's payload under its chain hash (latest wins —
        identical hash means identical content by construction). Evicts
        its own oldest entry beyond capacity; the pool has already
        unregistered the hash, so residency is never double-counted."""
        with self._lock:
            self._entries.pop(chain_hash, None)
            self._entries[chain_hash] = payload
            self.spilled_pages_total += 1
            while len(self._entries) > self.capacity_pages:
                self._entries.popitem(last=False)
                self.dropped_pages_total += 1

    def fetch(self, chain_hash: str):
        """POP one payload (move semantics: the caller re-registers the
        hash as resident, so the entry must leave the tier). Returns
        None when the hash is absent (raced a capacity drop)."""
        with self._lock:
            payload = self._entries.pop(chain_hash, None)
            if payload is not None:
                self.fetched_pages_total += 1
            return payload

    def unfetch(self, chain_hash: str, payload) -> None:
        """Roll back a fetch whose device page allocation failed: the
        payload returns to the tier (front of the LRU order — it was the
        oldest claim on the entry) and the fetch is uncounted."""
        with self._lock:
            self._entries[chain_hash] = payload
            self._entries.move_to_end(chain_hash, last=False)
            self.fetched_pages_total -= 1

    def drop(self, chain_hash: str) -> None:
        """Discard a tier entry whose hash just became resident some
        other way (a writer recomputed and re-registered the prefix) —
        keeps resident ⊎ spilled a true partition."""
        with self._lock:
            if self._entries.pop(chain_hash, None) is not None:
                self.dropped_pages_total += 1

    def observe_fetch_seconds(self, dt: float) -> None:
        with self._lock:
            self.fetch_seconds_total += max(0.0, float(dt))

    # locks don't survive copy/pickle — the poolcheck model deep-copies
    # its tier at every BFS expansion, so rebuild the lock on the copy.
    # _entries is snapshotted INSIDE the lock: deepcopy walks the
    # returned state after this method exits, and a concurrent spill
    # mutating the live OrderedDict mid-walk is a crash, not a copy
    def __getstate__(self):
        with self._lock:
            d = self.__dict__.copy()
            d["_entries"] = OrderedDict(self._entries)
        del d["_lock"]
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = threading.Lock()

    # -- metrics ------------------------------------------------------------

    def metrics(self) -> Dict:
        """Occupancy + lifetime counters (the /v2 host_tier block and
        the Prometheus gauges read this)."""
        with self._lock:
            n = len(self._entries)
            fetched = self.fetched_pages_total
            return {
                "capacity_pages": self.capacity_pages,
                "occupancy_pages": n,
                "occupancy_ratio": n / self.capacity_pages,
                "spilled_pages_total": self.spilled_pages_total,
                "fetched_pages_total": fetched,
                "dropped_pages_total": self.dropped_pages_total,
                "fetch_seconds_total": self.fetch_seconds_total,
                "fetch_latency_s_avg": (self.fetch_seconds_total / fetched
                                        if fetched else 0.0),
            }
