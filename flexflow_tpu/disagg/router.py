"""Prefix-affinity router over N serving instances
(docs/disaggregation.md "Router policy").

Prefix caching only pays when requests sharing a prompt prefix land on
the SAME pool — spread them round-robin and every instance recomputes
the prefix from scratch. The router keys each request by its first
page-aligned chain hash (the pool's own content address, so the router
and the cache agree byte-for-byte on what "same prefix" means) and
pins that key to one instance:

  affinity   — a prefix key routes to the instance that served it
               first, forever (sticky map; deterministic across runs
               given the same arrival order). An affinity hit routes
               there even under load: a tier fetch or LRU hit is far
               cheaper than recomputing the prefix elsewhere.
  placement  — a NEVER-seen prefix goes to the least-loaded instance:
               load = router-tracked in-flight requests plus a
               reqlog-derived service-time estimate (mean decode
               seconds over the instance's recent records), so a slow
               instance sheds new prefixes while it drains.
  spill-aware admission — page pressure (low pool free_pages) only
               counts against an instance when its host tier cannot
               absorb it: with tier headroom, admission just spills
               cold pages to host RAM instead of preempting, so the
               router keeps routing there. An instance that is BOTH
               page-starved and tier-full is skipped for new prefixes.

Every routed request is stamped `routed_to=<instance name>` before
enqueue, so the per-instance reqlogs reconstruct the routing decision
offline (tools/ffreplay, servesearch --replay).

The router is plain bookkeeping over instance.submit_request — it
holds no model state, so it fronts any mix of PagedGenerationServer,
SpeculativePagedServer, or DisaggPair-shaped instances that expose
`pool`, `submit_request`, and `stop`.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np


class PrefixAffinityRouter:
    """Shard requests across `instances` by prefix chain hash."""

    # free-page ratio below which an instance is "under page pressure"
    PRESSURE_FLOOR = 0.1
    # reqlog records consulted for the service-time load estimate
    LOAD_WINDOW = 64

    def __init__(self, instances: Sequence,
                 names: Optional[Sequence[str]] = None):
        if not instances:
            raise ValueError("router needs at least one instance")
        self._instances = list(instances)
        n = len(self._instances)
        self._names = (list(names) if names is not None
                       else [f"s{i}" for i in range(n)])
        if len(self._names) != n:
            raise ValueError(
                f"{n} instances but {len(self._names)} names")
        sizes = {inst.pool.page_size for inst in self._instances}
        if len(sizes) != 1:
            raise ValueError(
                f"instances disagree on page_size ({sorted(sizes)}) — "
                "their chain hashes would never match")
        self._affinity: Dict[str, int] = {}
        self._inflight = [0] * n
        self.routed_total = [0] * n
        self.affinity_hits = 0
        self.affinity_misses = 0
        self._lock = threading.Lock()

    # -- policy ------------------------------------------------------------

    def _prefix_key(self, prompt: np.ndarray) -> str:
        """The pool's FIRST page-aligned chain hash — the root every
        shared prefix runs through. A prompt shorter than one page has
        no full block; its whole token string is the key instead."""
        chain = self._instances[0].pool.chain_hashes(prompt)
        if chain:
            return chain[0]
        return "short:" + hashlib.sha1(
            np.asarray(prompt, np.int32).tobytes()).hexdigest()

    def _load(self, i: int) -> float:
        """In-flight requests weighted by the instance's recent mean
        request service time (reqlog-derived; 0 when no records yet) —
        two queued requests on a slow instance outweigh three on a
        fast one."""
        inst = self._instances[i]
        svc = 0.0
        log = getattr(inst, "request_log", None)
        if log:
            recent = log.tail(self.LOAD_WINDOW)
            if recent:
                svc = sum(
                    max(0.0, (r["done_ns"] - r["admit_ns"]) / 1e9)
                    for r in recent) / len(recent)
        return self._inflight[i] * (1.0 + svc)

    def _pressured(self, i: int) -> bool:
        """Page-starved AND nowhere to spill: free pages below the
        floor and the tier (if any) at capacity. With tier headroom the
        pool sheds cold pages to host RAM instead of preempting, so
        pressure alone never diverts traffic."""
        pool = self._instances[i].pool
        if pool.free_pages / max(1, pool.num_pages) >= self.PRESSURE_FLOOR:
            return False
        tier = pool.tier
        return tier is None or len(tier) >= tier.capacity_pages

    def _route_locked(self, key: str) -> int:
        """Routing policy body; caller holds self._lock."""
        i = self._affinity.get(key)
        if i is not None:
            self.affinity_hits += 1
            return i
        self.affinity_misses += 1
        candidates = [j for j in range(len(self._instances))
                      if not self._pressured(j)]
        if not candidates:
            candidates = list(range(len(self._instances)))
        i = min(candidates, key=lambda j: (self._load(j), j))
        self._affinity[key] = i
        return i

    def route_index(self, prompt) -> int:
        """Pick (and pin) the instance for `prompt`. Deterministic:
        sticky map first, then min (load, index) over unpressured
        instances, then min over all."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        key = self._prefix_key(prompt)
        with self._lock:
            return self._route_locked(key)

    # -- serving surface ----------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: int,
               temperature: float = 0.0):
        from flexflow_tpu.serving import _GenRequest

        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("prompt must contain at least one token")
        req = _GenRequest(prompt, max_new_tokens, temperature)
        key = self._prefix_key(prompt)
        # route + stamp + count under ONE acquisition: a concurrent
        # submit must never observe the routing decision without the
        # load bump that goes with it (stale-load window)
        with self._lock:
            i = self._route_locked(key)
            req.routed_to = self._names[i]
            self._inflight[i] += 1
            self.routed_total[i] += 1
        req.future.add_done_callback(lambda _f, i=i: self._done(i))
        try:
            self._instances[i].submit_request(req)
        except BaseException:
            # compensating decrement for a request that never enqueued —
            # no decision spans the lock release, so the split is benign
            self._done(i)  # fflint: race-ok (compensating decrement)
            raise
        return req.future

    def _done(self, i: int) -> None:
        with self._lock:
            self._inflight[i] = max(0, self._inflight[i] - 1)

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0):
        return self.submit(prompt_ids, max_new_tokens,
                           temperature).result()

    def stop(self):
        for inst in self._instances:
            inst.stop()

    def metrics(self) -> Dict:
        with self._lock:
            return {
                "instances": list(self._names),
                "routed_total": list(self.routed_total),
                "inflight": list(self._inflight),
                "affinity_prefixes": len(self._affinity),
                "affinity_hits": self.affinity_hits,
                "affinity_misses": self.affinity_misses,
            }
