"""Prefill/decode disaggregation: a PrefillWorker that runs admission +
chunked prefill ONLY, handing each request off to a decode worker the
moment its prefill completes, with the KV transferred through a shared
host tier (docs/disaggregation.md "Handoff protocol").

Why this shape: production serving splits prefill from decode because
the two phases want different resources — prefill is compute-bound and
batches wide, decode is memory-bound and batches deep. The pieces were
already here: pages are content-addressed (paged/pool.py), so a
request's KV is fully named by its prefix chain hashes; preempt-resume
already proves that "publish pages, free them, re-admit from
seq_tokens()" is token-identical; and PR 16's adopt_pool_from showed a
pool can take over another pool's content wholesale. The handoff below
is per-REQUEST page adoption: the prefill worker spills the finished
request's full pages into the shared HostTier (a dict move keyed by
chain hash, scales riding along), hands the live _GenRequest — future,
first sampled token, counters intact — to the decode worker's queue,
and the decode worker's ordinary admission lookup transparently fetches
the pages back out of the tier. No new resume machinery: the decode
side IS the proven preempt-resume path, just entered on a different
server.

Handoff protocol, step by step (PrefillWorker._on_prefill_complete):

  1. prefill finishes a request's last chunk; the base scheduler has
     already published the tail, sampled the FIRST token (its row is
     committed), and run _finish_if_done — a request that finished
     outright (max_new=1, instant EOS) never reaches the hook;
  2. _publish_tail again: with the first token appended, every full
     prompt page is now hash-registered (the partial tail stays a
     local COW hint — its rows are recomputed decode-side);
  3. pool.spill_request: every full-registered page of the request
     moves into the shared tier and leaves THIS pool's hash index
     (resident ⊎ spilled stays a partition on both pools);
  4. free + clear the slot — the pages return to the free list, the
     prefill worker's capacity is immediately reusable;
  5. decode_server.submit_request(req): the untouched request object
     (same Future the client holds) enters the decode worker's queue;
     its admission lookup walks the chain hashes, finds them in the
     tier, and _fetch_full lands each page in the decode pool. At
     most the tail rows and the clamped last token are recomputed —
     exactly the preempt-resume contract, so greedy output is
     token-identical to a monolithic server by construction.

Thread-safety: the hook runs on the prefill worker's loop thread;
submit_request only takes the decode server's queue lock; the tier's
own lock covers the spill/fetch race. Neither pool is ever touched
from the other worker's thread — the tier is the ONLY shared state.

DisaggPair wires the whole thing: one shared HostTier, a PrefillWorker,
a decode-side PagedGenerationServer, and a submit()/generate()/stop()
surface that looks like a single server.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from flexflow_tpu.disagg.host_tier import HostTier
from flexflow_tpu.paged.scheduler import PagedGenerationServer
from flexflow_tpu.serving import _GenRequest


class PrefillWorker(PagedGenerationServer):
    """A paged server that never decodes: every admitted request runs
    chunked prefill, then hands off through the shared host tier to the
    `handoff` callable (normally a decode server's submit_request)."""

    def __init__(self, ff, *, handoff: Callable[[_GenRequest], object],
                 host_tier, **kwargs):
        if handoff is None:
            raise ValueError("PrefillWorker needs a handoff target "
                             "(decode_server.submit_request)")
        if host_tier is None or host_tier == 0:
            raise ValueError(
                "PrefillWorker needs a host_tier — the tier IS the "
                "KV-transfer channel to the decode worker")
        if not kwargs.get("prefix_cache", True):
            raise ValueError(
                "PrefillWorker requires prefix_cache=True: the handoff "
                "rides the content-addressed hash chain")
        self._handoff = handoff
        self.handoffs = 0
        super().__init__(ff, host_tier=host_tier, **kwargs)

    def _on_prefill_complete(self, slot: int):
        req = self._active[slot]
        if not self._kv_quant_debug:
            self._close_canary(req)
        # with the first token appended, publish so every FULL page is
        # hash-registered — spill_request only moves registered pages
        self._publish_tail(req)
        req.spilled_pages += self.pool.spill_request(req.pages)
        self.pool.free(list(reversed(req.pages)))  # leaf-first
        req.pages = []
        self._reset_prefill_state(req)
        self._tables[slot] = 0
        self._mark_tables_dirty()
        self._mark_temps_dirty()
        self._active[slot] = None
        if slot in self._admit_order:
            self._admit_order.remove(slot)
        self.handoffs += 1
        try:
            self._handoff(req)
        except BaseException as e:  # decode worker stopped mid-handoff
            if not req.future.done():
                req.future.set_exception(e)


class DisaggPair:
    """One disaggregated serving unit: PrefillWorker + decode-side
    PagedGenerationServer sharing a HostTier, presented through the
    single-server submit()/generate()/stop() surface. Both pools must
    store the same kv dtype (the tier moves raw payloads), so the pair
    constructor configures both sides from one set of knobs."""

    def __init__(self, ff, *, tier_pages: int = 1024,
                 host_tier: Optional[HostTier] = None,
                 prefill_slots: Optional[int] = None,
                 prefill_num_pages: Optional[int] = None,
                 decode_num_pages: Optional[int] = None,
                 **kwargs):
        self.host_tier = (host_tier if host_tier is not None
                          else HostTier(tier_pages))
        if not kwargs.get("prefix_cache", True):
            raise ValueError("DisaggPair requires prefix_cache=True")
        decode_kw = dict(kwargs)
        decode_kw["num_pages"] = decode_num_pages or kwargs.get("num_pages")
        self.decode = PagedGenerationServer(
            ff, host_tier=self.host_tier, **decode_kw)
        prefill_kw = dict(kwargs)
        prefill_kw["num_pages"] = (prefill_num_pages
                                   or kwargs.get("num_pages"))
        if prefill_slots is not None:
            prefill_kw["slots"] = prefill_slots
        self.prefill = PrefillWorker(
            ff, handoff=self.decode.submit_request,
            host_tier=self.host_tier, **prefill_kw)

    # -- single-server surface -------------------------------------------

    def submit(self, prompt_ids, max_new_tokens: int,
               temperature: float = 0.0):
        return self.prefill.submit(prompt_ids, max_new_tokens, temperature)

    def submit_request(self, req: _GenRequest):
        return self.prefill.submit_request(req)

    @property
    def pool(self):
        """Admission-side pool — what a fronting router inspects for
        page pressure and chain hashes."""
        return self.prefill.pool

    @property
    def request_log(self):
        """Decode-side reqlog: requests COMPLETE on the decode worker,
        so that is where the service-time records live."""
        return self.decode.request_log

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0):
        return self.submit(prompt_ids, max_new_tokens,
                           temperature).result()

    def stop(self):
        # prefill first: no new handoffs can arrive at a live decode
        # queue after its producer is down
        self.prefill.stop()
        self.decode.stop()

    @property
    def handoffs(self) -> int:
        return self.prefill.handoffs

    def metrics(self) -> Dict:
        return {
            "prefill": self.prefill.metrics(),
            "decode": self.decode.metrics(),
            "host_tier": self.host_tier.metrics(),
            "handoffs": self.prefill.handoffs,
        }
