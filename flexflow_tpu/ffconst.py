"""Framework-wide enums.

Mirrors the public enum surface of the reference's `include/flexflow/ffconst.h`
(op types, activation modes, aggregation modes, loss/metrics types, parameter
sync modes) re-expressed for a JAX/TPU backend: DataType carries a jnp dtype,
ParamSyncType distinguishes replicated-psum vs sharded optimizer state instead
of PS/NCCL.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp


class DataType(enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.value)

    @property
    def size_bytes(self) -> int:
        return jnp.dtype(self.value).itemsize

    @classmethod
    def from_jnp(cls, dtype) -> "DataType":
        return cls(jnp.dtype(dtype).name)


class _Coercible:
    """Mixin for enums the layer builders accept as enum | str | None.
    Coercion happens at the builder boundary so attrs always carry the
    enum (lowerings and search predicates compare against enum members —
    a stored str would silently fail those comparisons)."""

    @classmethod
    def coerce(cls, value):
        if value is None and hasattr(cls, "NONE"):
            return cls.NONE
        if isinstance(value, cls):
            return value
        return cls(str(value).lower())


class ActiMode(_Coercible, enum.Enum):
    NONE = "none"
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    GELU = "gelu"
    SILU = "silu"


class AggrMode(_Coercible, enum.Enum):
    """Embedding aggregation (reference: AGGR_MODE_{NONE,SUM,AVG})."""

    NONE = "none"
    SUM = "sum"
    AVG = "avg"


class PoolType(_Coercible, enum.Enum):
    MAX = "max"
    AVG = "avg"


class LossType(enum.Enum):
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR_AVG_REDUCE = "mean_squared_error_avg_reduce"
    MEAN_SQUARED_ERROR_SUM_REDUCE = "mean_squared_error_sum_reduce"
    IDENTITY = "identity"


class MetricsType(enum.Enum):
    ACCURACY = "accuracy"
    CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
    SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
    MEAN_SQUARED_ERROR = "mean_squared_error"
    ROOT_MEAN_SQUARED_ERROR = "root_mean_squared_error"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"


class ParamSyncType(enum.Enum):
    """Gradient/parameter synchronization mode.

    Reference `ParameterSyncType::{NONE,PS,NCCL}` (config.h:55-59). On TPU the
    allreduce is a psum emitted by the SPMD partitioner; SHARDED keeps
    optimizer state sharded over the data axis (ZeRO-style reduce-scatter),
    which has no reference analog but is the idiomatic TPU upgrade.
    """

    NONE = "none"
    PSUM = "psum"
    SHARDED = "sharded"


class CompMode(enum.Enum):
    TRAINING = "training"
    INFERENCE = "inference"


class OpType(enum.Enum):
    """Operator types — the PCG node vocabulary.

    Covers every op in the reference's `src/ops/` + `src/parallel_ops/`
    (SURVEY.md §2.2/§2.3) plus TPU-native additions (RING_ATTENTION,
    ALL_TO_ALL for sequence parallelism; PIPELINE implemented, not a stub).
    """

    # sources
    INPUT = "input"
    WEIGHT = "weight"
    NOOP = "noop"
    # dense/conv
    CONV2D = "conv2d"
    LINEAR = "linear"
    EMBEDDING = "embedding"
    BATCH_MATMUL = "batch_matmul"
    # attention
    MULTIHEAD_ATTENTION = "multihead_attention"
    RING_ATTENTION = "ring_attention"
    # elementwise
    ELEMENT_BINARY = "element_binary"
    ELEMENT_UNARY = "element_unary"
    # shape
    RESHAPE = "reshape"
    FLAT = "flat"
    TRANSPOSE = "transpose"
    REVERSE = "reverse"
    CONCAT = "concat"
    SPLIT = "split"
    # norm / misc
    POOL2D = "pool2d"
    BATCH_NORM = "batch_norm"
    LAYER_NORM = "layer_norm"
    RMS_NORM = "rms_norm"
    SOFTMAX = "softmax"
    DROPOUT = "dropout"
    CAST = "cast"
    GATHER = "gather"
    REDUCE_SUM = "reduce_sum"
    MEAN = "mean"
    # recurrent (reference legacy NMT app, nmt/rnn.h)
    LSTM = "lstm"
    # MoE
    TOPK = "topk"
    GROUP_BY = "group_by"
    AGGREGATE = "aggregate"
    AGGREGATE_SPEC = "aggregate_spec"
    CACHE = "cache"
    EXPERTS = "experts"
    # fused
    FUSED = "fused"
    # parallel ops (first-class PCG nodes, SURVEY.md §2.3)
    REPARTITION = "repartition"
    COMBINE = "combine"
    REPLICATE = "replicate"
    REDUCTION = "reduction"
    ALL_TO_ALL = "all_to_all"
    FUSED_PARALLEL = "fused_parallel"
    PIPELINE = "pipeline"
    # loss/metrics pseudo-ops
    LOSS = "loss"
    METRICS = "metrics"


# Ops whose lowering is a pure resharding (no math).
PARALLEL_OP_TYPES = frozenset(
    {
        OpType.REPARTITION,
        OpType.COMBINE,
        OpType.REPLICATE,
        OpType.REDUCTION,
        OpType.ALL_TO_ALL,
        OpType.FUSED_PARALLEL,
        # NOTE: PIPELINE is NOT here — it was a stub enum in the reference
        # but is a real compute composite in this framework (ops/attrs.py
        # PipelineAttrs), priced like any op plus bubble/ppermute terms.
    }
)
