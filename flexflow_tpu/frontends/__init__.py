"""Frontends: import models from torch.fx, Keras-style APIs, and ONNX into
the FFModel layer graph (reference python/flexflow/{torch,keras,onnx},
SURVEY.md §2.7)."""
