"""HuggingFace checkpoint importer — fine-tune a real pretrained HF model.

Reference analog: examples/python/pytorch/mt5 (fine-tuning a HuggingFace
model through the torch frontend, python/flexflow/torch/model.py:2408).
The fx-trace route (frontends/torch_fx.py) cannot consume stock
`transformers` models in this environment: HF forwards carry ~30 keyword
arguments and torch.fx's root patching (`_patch_function`) fails on
Python 3.12 with `co_varnames is too small` — for both the plain tracer
and transformers' own HFTracer. So HF import is STRUCTURED instead:
the architecture is rebuilt from the HF config through the native model
builders (models/llama.py) and every checkpoint tensor is mapped onto
the corresponding framework weight. This is also the TPU-honest design:
the imported model runs the framework's own fused/flash lowerings rather
than a replayed torch op graph.

Supported: Llama-family causal LMs (LlamaForCausalLM and lookalikes with
q/k/v/o_proj + gate/up/down_proj + RMSNorm) and GPT-2 (GPT2LMHeadModel:
pre-LN, learned positions, fused c_attn, tanh-GELU). `import_hf_causal_lm`
dispatches on config.model_type, builds the graph; `copy_hf_weights`
pushes the checkpoint into a compiled model; logits parity against the
torch reference is tested in tests/test_hf_import.py.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def hf_to_llama_config(hf_cfg):
    """Map a transformers LlamaConfig(-like) onto the native LlamaConfig.
    Raises on config flags the import would silently get wrong (biases,
    non-silu activations, decoupled head_dim) — lookalike checkpoints
    must fail loudly, not produce wrong logits."""
    from flexflow_tpu.models.llama import LlamaConfig

    for flag in ("attention_bias", "mlp_bias"):
        if getattr(hf_cfg, flag, False):
            raise ValueError(
                f"unsupported HF config: {flag}=True (bias tensors would "
                "be silently dropped)")
    act = getattr(hf_cfg, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise ValueError(f"unsupported HF config: hidden_act={act!r} "
                         "(the native Llama MLP is gated silu)")
    hd = getattr(hf_cfg, "head_dim", None)
    if hd not in (None, hf_cfg.hidden_size // hf_cfg.num_attention_heads):
        raise ValueError(
            f"unsupported HF config: head_dim={hd} decoupled from "
            f"hidden_size//num_attention_heads")
    scaling = getattr(hf_cfg, "rope_scaling", None)
    if scaling not in (None, {}) and (
            not isinstance(scaling, dict)
            or scaling.get("rope_type", scaling.get("type")) != "default"):
        raise ValueError(
            f"unsupported HF config: rope_scaling={scaling!r} (positions "
            "would be rotated with unscaled theta — Llama-3.1-style "
            "scaled RoPE is not implemented)")
    prf = getattr(hf_cfg, "partial_rotary_factor", 1.0)
    if prf not in (None, 1.0):
        raise ValueError(
            f"unsupported HF config: partial_rotary_factor={prf}")
    return LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        layers=hf_cfg.num_hidden_layers,
        heads=hf_cfg.num_attention_heads,
        kv_heads=getattr(hf_cfg, "num_key_value_heads",
                         hf_cfg.num_attention_heads),
        hidden=hf_cfg.intermediate_size,
        norm_eps=getattr(hf_cfg, "rms_norm_eps", 1e-5),
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
    )


def hf_to_gpt2_config(hf_cfg):
    from flexflow_tpu.models.gpt2 import GPT2Config

    act = getattr(hf_cfg, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        # exact-erf 'gelu' would silently drift: the lowering uses the
        # tanh approximation (jax.nn.gelu default)
        raise ValueError(f"unsupported GPT-2 activation {act!r} "
                         "(only tanh-approximate GELU is faithful)")
    if getattr(hf_cfg, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError("unsupported GPT-2 config: "
                         "scale_attn_by_inverse_layer_idx=True")
    if getattr(hf_cfg, "reorder_and_upcast_attn", False):
        raise ValueError("unsupported GPT-2 config: "
                         "reorder_and_upcast_attn=True")
    if not getattr(hf_cfg, "scale_attn_weights", True):
        raise ValueError("unsupported GPT-2 config: "
                         "scale_attn_weights=False (attention is built "
                         "with the standard 1/sqrt(head_dim) scale)")
    return GPT2Config(
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.n_embd,
        layers=hf_cfg.n_layer,
        heads=hf_cfg.n_head,
        inner=getattr(hf_cfg, "n_inner", None) or 0,
        ln_eps=getattr(hf_cfg, "layer_norm_epsilon", 1e-5),
    )


def import_hf_causal_lm(hf_model, ff, batch_size: Optional[int] = None,
                        seq_len: int = 128):
    """Build the framework graph for `hf_model` (a Llama-family or GPT-2
    *LMHeadModel/*ForCausalLM). Call ff.compile(...) then
    copy_hf_weights()."""
    mt = getattr(hf_model.config, "model_type", "llama")
    if mt == "gpt2":
        from flexflow_tpu.models.gpt2 import build_gpt2

        n_pos = getattr(hf_model.config, "n_positions", None)
        if n_pos is not None and seq_len > n_pos:
            raise ValueError(
                f"seq_len={seq_len} exceeds the checkpoint's learned "
                f"position table (n_positions={n_pos})")
        cfg = hf_to_gpt2_config(hf_model.config)
        build_gpt2(ff, cfg, batch_size=batch_size, seq_len=seq_len)
        return cfg
    from flexflow_tpu.models.llama import build_llama

    cfg = hf_to_llama_config(hf_model.config)
    build_llama(ff, cfg, batch_size=batch_size, seq_len=seq_len)
    return cfg


def _t(p) -> np.ndarray:
    return p.detach().cpu().numpy().astype(np.float32)


def copy_hf_weights(hf_model, ff) -> int:
    """Push every HF checkpoint tensor into the compiled model; returns
    the number of weights copied. torch nn.Linear stores [out, in] — the
    framework's dense kernel is [in, out] and attention weights are the
    3-D [E,H,D]/[H,D,E] layouts of ops/jax_ops.qkv_project. GPT-2's
    Conv1D already stores [in, out]."""
    if getattr(hf_model.config, "model_type", "llama") == "gpt2":
        return _copy_gpt2_weights(hf_model, ff)
    cfg = hf_model.config
    H = cfg.num_attention_heads
    Hkv = getattr(cfg, "num_key_value_heads", H)
    E = cfg.hidden_size
    hd = E // H
    base = hf_model.model  # LlamaModel inside the *ForCausalLM
    copied = 0

    def put(name, arr, weight_name):
        nonlocal copied
        ff.set_weight(name, np.ascontiguousarray(arr), weight_name)
        copied += 1

    put("tok_emb", _t(base.embed_tokens.weight), "kernel")
    for i, layer in enumerate(base.layers):
        at = layer.self_attn
        put(f"l{i}_attn", _t(at.q_proj.weight).T.reshape(E, H, hd), "wq")
        put(f"l{i}_attn", _t(at.k_proj.weight).T.reshape(E, Hkv, hd), "wk")
        put(f"l{i}_attn", _t(at.v_proj.weight).T.reshape(E, Hkv, hd), "wv")
        put(f"l{i}_attn", _t(at.o_proj.weight).T.reshape(H, hd, E), "wo")
        put(f"l{i}_attn_norm", _t(layer.input_layernorm.weight), "scale")
        put(f"l{i}_mlp_norm", _t(layer.post_attention_layernorm.weight),
            "scale")
        put(f"l{i}_gate", _t(layer.mlp.gate_proj.weight).T, "kernel")
        put(f"l{i}_up", _t(layer.mlp.up_proj.weight).T, "kernel")
        put(f"l{i}_down", _t(layer.mlp.down_proj.weight).T, "kernel")
    put("final_norm", _t(base.norm.weight), "scale")
    if cfg.tie_word_embeddings:
        _warn_untied()
        head = base.embed_tokens.weight
    else:
        head = hf_model.lm_head.weight
    put("lm_head", _t(head).T, "kernel")
    return copied


def _warn_untied():
    import warnings

    warnings.warn(
        "tie_word_embeddings checkpoint: the embedding is COPIED into "
        "a separate lm_head parameter — fine-tuning trains them "
        "independently (the tie invariant is not preserved)")


def _copy_gpt2_weights(hf_model, ff) -> int:
    cfg = hf_model.config
    H, E = cfg.n_head, cfg.n_embd
    hd = E // H
    base = hf_model.transformer
    wpe_node = next((n for n in ff.graph.nodes if n.name == "wpe"), None)
    if wpe_node is None:
        raise ValueError(
            "graph has no 'wpe' node — was the model built by "
            "import_hf_causal_lm/build_gpt2 before compile?")
    seq_len = wpe_node.outputs[0].dims[0].size
    copied = 0

    def put(name, arr, weight_name):
        nonlocal copied
        ff.set_weight(name, np.ascontiguousarray(arr), weight_name)
        copied += 1

    put("wte", _t(base.wte.weight), "kernel")
    put("wpe", _t(base.wpe.weight)[:seq_len], "weight")
    for i, blk in enumerate(base.h):
        put(f"h{i}_ln1", _t(blk.ln_1.weight), "scale")
        put(f"h{i}_ln1", _t(blk.ln_1.bias), "bias")
        # fused c_attn (Conv1D [E, 3E]): columns are q|k|v
        w = _t(blk.attn.c_attn.weight)
        bqkv = _t(blk.attn.c_attn.bias)
        for j, nm in enumerate("qkv"):
            put(f"h{i}_attn", w[:, j * E:(j + 1) * E].reshape(E, H, hd),
                f"w{nm}")
            put(f"h{i}_attn", bqkv[j * E:(j + 1) * E].reshape(H, hd),
                f"b{nm}")
        put(f"h{i}_attn", _t(blk.attn.c_proj.weight).reshape(H, hd, E),
            "wo")
        put(f"h{i}_attn", _t(blk.attn.c_proj.bias), "bo")
        put(f"h{i}_ln2", _t(blk.ln_2.weight), "scale")
        put(f"h{i}_ln2", _t(blk.ln_2.bias), "bias")
        put(f"h{i}_fc", _t(blk.mlp.c_fc.weight), "kernel")
        put(f"h{i}_fc", _t(blk.mlp.c_fc.bias), "bias")
        put(f"h{i}_proj", _t(blk.mlp.c_proj.weight), "kernel")
        put(f"h{i}_proj", _t(blk.mlp.c_proj.bias), "bias")
    put("ln_f", _t(base.ln_f.weight), "scale")
    put("ln_f", _t(base.ln_f.bias), "bias")
    if getattr(cfg, "tie_word_embeddings", True):
        _warn_untied()  # stock GPT-2 ties lm_head to wte
        head = base.wte.weight
    else:
        head = hf_model.lm_head.weight
    put("lm_head", _t(head).T, "kernel")
    return copied
