"""Keras-style frontend (reference python/flexflow/keras/).

Sequential and functional models whose layers record into an FFModel at
compile time; optimizer/loss/metric string names map like tf.keras.
"""

from flexflow_tpu.frontends.keras.layers import (
    Activation,
    Add,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Input,
    MaxPooling2D,
    AveragePooling2D,
)
from flexflow_tpu.frontends.keras.models import Model, Sequential

__all__ = [
    "Sequential",
    "Model",
    "Input",
    "Dense",
    "Conv2D",
    "MaxPooling2D",
    "AveragePooling2D",
    "Flatten",
    "Dropout",
    "Embedding",
    "Concatenate",
    "Add",
    "Activation",
]

from flexflow_tpu.frontends.keras import callbacks, datasets, optimizers  # noqa: E402

__all__ += ["callbacks", "datasets", "optimizers"]
