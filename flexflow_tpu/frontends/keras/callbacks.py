"""Keras-style callbacks (reference python/flexflow/keras/callbacks.py:
Callback/History/LearningRateScheduler/EarlyStopping surface).

Driven by the keras models' fit(): one framework epoch per iteration with
on_epoch_begin/end hooks; logs carry loss/accuracy from PerfMetrics.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


class Callback:
    model = None  # set by fit()

    def on_train_begin(self, logs: Optional[Dict] = None):
        pass

    def on_train_end(self, logs: Optional[Dict] = None):
        pass

    def on_epoch_begin(self, epoch: int, logs: Optional[Dict] = None):
        pass

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None):
        pass


class History(Callback):
    """Records per-epoch logs (reference keras History)."""

    def __init__(self):
        self.history: Dict[str, List[float]] = {}
        self.epoch: List[int] = []

    def on_epoch_end(self, epoch, logs=None):
        self.epoch.append(epoch)
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving."""

    def __init__(self, monitor: str = "loss", min_delta: float = 0.0,
                 patience: int = 0, mode: str = "auto"):
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        self.patience = patience
        self.mode = mode
        self.best: Optional[float] = None
        self.wait = 0
        self.stopped_epoch = 0
        self.stop_training = False

    def _better(self, cur: float, best: float) -> bool:
        if self.mode == "max" or (self.mode == "auto" and "acc" in self.monitor):
            return cur > best + self.min_delta
        return cur < best - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait > self.patience:
            self.stopped_epoch = epoch
            self.stop_training = True


class LearningRateScheduler(Callback):
    """schedule(epoch, lr) -> new lr; rebuilds the jitted step with the new
    optimizer (the TPU analog of the reference's per-epoch lr update)."""

    def __init__(self, schedule):
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        ff = self.model.ffmodel
        opt = ff._optimizer
        new_lr = float(self.schedule(epoch, opt.lr))
        if new_lr != opt.lr:
            ff._optimizer = dataclasses.replace(opt, lr=new_lr)
            ex = ff._executor
            ex.optimizer = ff._optimizer
            ex._train_step = None  # re-trace with the new lr


class ModelCheckpoint(Callback):
    """Periodic checkpoint via the runtime checkpoint module."""

    def __init__(self, filepath: str, save_freq: int = 1):
        self.filepath = filepath
        self.save_freq = save_freq

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            from flexflow_tpu.runtime.checkpoint import save_checkpoint

            save_checkpoint(self.filepath.format(epoch=epoch),
                            self.model.ffmodel)
