"""Dataset loaders with the keras API shape (reference
python/flexflow/keras/datasets: mnist/cifar10/reuters).

This image is zero-egress, so the loaders generate DETERMINISTIC SYNTHETIC
data with the real datasets' shapes/dtypes/class counts — each class is a
noisy prototype so models actually learn. Swap in real data by replacing
these functions; the shapes match keras exactly.
"""

from __future__ import annotations

import numpy as np


def _protos(n_classes: int, shape, seed: int):
    rs = np.random.RandomState(seed)
    return rs.rand(n_classes, *shape).astype(np.float32)


def _make(n: int, n_classes: int, shape, seed: int, noise: float = 0.15):
    rs = np.random.RandomState(seed + 1)
    y = rs.randint(0, n_classes, n)
    protos = _protos(n_classes, shape, seed)
    x = protos[y] + noise * rs.randn(n, *shape).astype(np.float32)
    x = np.clip(x, 0.0, 1.0)
    return (x * 255).astype(np.uint8), y.astype(np.int64)


class mnist:
    @staticmethod
    def load_data(n_train: int = 8192, n_test: int = 1024, seed: int = 0):
        """(x_train, y_train), (x_test, y_test) — x: uint8 (n, 28, 28)."""
        xtr, ytr = _make(n_train, 10, (28, 28), seed)
        xte, yte = _make(n_test, 10, (28, 28), seed + 100)
        return (xtr, ytr), (xte, yte)


class cifar10:
    @staticmethod
    def load_data(n_train: int = 8192, n_test: int = 1024, seed: int = 0):
        """(x_train, y_train), (x_test, y_test) — x: uint8 (n, 32, 32, 3),
        y: (n, 1) like keras."""
        xtr, ytr = _make(n_train, 10, (32, 32, 3), seed)
        xte, yte = _make(n_test, 10, (32, 32, 3), seed + 100)
        return (xtr, ytr[:, None]), (xte, yte[:, None])
