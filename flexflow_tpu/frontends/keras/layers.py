"""Keras-style layers (reference python/flexflow/keras/layers/*): thin
declarative records applied to an FFModel at compile time."""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

from flexflow_tpu.ffconst import ActiMode, DataType, PoolType

_ACT = {
    None: ActiMode.NONE,
    "linear": ActiMode.NONE,
    "relu": ActiMode.RELU,
    "sigmoid": ActiMode.SIGMOID,
    "tanh": ActiMode.TANH,
    "gelu": ActiMode.GELU,
    "silu": ActiMode.SILU,
}


def _resolve_act(activation):
    """-> (fused ActiMode, needs_softmax). Softmax is not a fused activation
    in the op library; it becomes a trailing softmax op."""
    if activation == "softmax":
        return ActiMode.NONE, True
    if activation not in _ACT:
        raise ValueError(f"unsupported Keras activation {activation!r}")
    return _ACT[activation], False


class Layer:
    name: Optional[str] = None

    def apply(self, ff, *tensors):
        raise NotImplementedError


@dataclasses.dataclass
class KTensor:
    """Symbolic tensor for the functional API."""

    layer: "Layer"
    inputs: Tuple["KTensor", ...] = ()
    shape: Optional[Tuple[int, ...]] = None

    def __call__(self, *a, **k):  # pragma: no cover
        raise TypeError("KTensor is not callable")


def Input(shape: Sequence[int], dtype: DataType = DataType.FLOAT,
          name: Optional[str] = None) -> KTensor:
    lay = _InputLayer(tuple(shape), dtype, name)
    return KTensor(lay, (), tuple(shape))


@dataclasses.dataclass
class _InputLayer(Layer):
    shape: Tuple[int, ...]
    dtype: DataType = DataType.FLOAT
    name: Optional[str] = None

    def apply(self, ff, batch_size):
        return ff.create_tensor((batch_size, *self.shape), self.dtype,
                                name=self.name or "input")


class _CallableLayer(Layer):
    def __call__(self, *inputs):
        ins = []
        for i in inputs:
            if isinstance(i, (list, tuple)):
                ins.extend(i)
            else:
                ins.append(i)
        return KTensor(self, tuple(ins))


@dataclasses.dataclass
class Dense(_CallableLayer):
    units: int
    activation: Optional[str] = None
    use_bias: bool = True
    kernel_initializer: Optional[object] = None
    name: Optional[str] = None

    def apply(self, ff, x):
        act, softmax = _resolve_act(self.activation)
        y = ff.dense(x, self.units, act, self.use_bias,
                     kernel_initializer=self.kernel_initializer, name=self.name)
        return ff.softmax(y) if softmax else y


@dataclasses.dataclass
class Conv2D(_CallableLayer):
    filters: int
    kernel_size: Union[int, Tuple[int, int]] = 3
    strides: Union[int, Tuple[int, int]] = 1
    padding: Union[str, int] = "valid"
    activation: Optional[str] = None
    use_bias: bool = True
    name: Optional[str] = None

    def apply(self, ff, x):
        k = self.kernel_size if isinstance(self.kernel_size, tuple) else (self.kernel_size,) * 2
        s = self.strides if isinstance(self.strides, tuple) else (self.strides,) * 2
        if self.padding == "same":
            p = (k[0] // 2, k[1] // 2)
        elif self.padding == "valid":
            p = (0, 0)
        else:
            p = (self.padding, self.padding)
        act, softmax = _resolve_act(self.activation)
        y = ff.conv2d(x, self.filters, k[0], k[1], s[0], s[1], p[0], p[1],
                      act, use_bias=self.use_bias, name=self.name)
        return ff.softmax(y) if softmax else y


@dataclasses.dataclass
class MaxPooling2D(_CallableLayer):
    pool_size: Union[int, Tuple[int, int]] = 2
    strides: Optional[Union[int, Tuple[int, int]]] = None
    name: Optional[str] = None
    _pool_type = PoolType.MAX

    def apply(self, ff, x):
        k = self.pool_size if isinstance(self.pool_size, tuple) else (self.pool_size,) * 2
        s = self.strides or k
        s = s if isinstance(s, tuple) else (s,) * 2
        return ff.pool2d(x, k[0], k[1], s[0], s[1], pool_type=self._pool_type,
                         name=self.name)


@dataclasses.dataclass
class AveragePooling2D(MaxPooling2D):
    _pool_type = PoolType.AVG


@dataclasses.dataclass
class Flatten(_CallableLayer):
    name: Optional[str] = None

    def apply(self, ff, x):
        return ff.flat(x, name=self.name)


@dataclasses.dataclass
class Dropout(_CallableLayer):
    rate: float = 0.5
    name: Optional[str] = None

    def apply(self, ff, x):
        return ff.dropout(x, self.rate, name=self.name)


@dataclasses.dataclass
class Embedding(_CallableLayer):
    input_dim: int
    output_dim: int
    name: Optional[str] = None

    def apply(self, ff, x):
        return ff.embedding(x, self.input_dim, self.output_dim, name=self.name)


@dataclasses.dataclass
class Activation(_CallableLayer):
    activation: str = "relu"
    name: Optional[str] = None

    def apply(self, ff, x):
        if self.activation == "softmax":
            return ff.softmax(x, name=self.name)
        fn = {"relu": ff.relu, "sigmoid": ff.sigmoid, "tanh": ff.tanh,
              "gelu": ff.gelu, "silu": ff.silu}[self.activation]
        return fn(x, name=self.name)


@dataclasses.dataclass
class Concatenate(_CallableLayer):
    axis: int = -1
    name: Optional[str] = None

    def apply(self, ff, *xs):
        return ff.concat(list(xs), self.axis, name=self.name)


@dataclasses.dataclass
class Add(_CallableLayer):
    name: Optional[str] = None

    def apply(self, ff, a, b):
        return ff.add(a, b, name=self.name)
