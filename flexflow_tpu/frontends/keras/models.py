"""Keras-style Sequential / functional Model (reference
python/flexflow/keras/models/base_model.py:31: compile :128, fit :198)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import LossType, MetricsType
from flexflow_tpu.frontends.keras.layers import KTensor, Layer, _InputLayer
from flexflow_tpu.model import FFModel
from flexflow_tpu.runtime.optimizer import AdamOptimizer, Optimizer, SGDOptimizer

_LOSSES = {
    "sparse_categorical_crossentropy": LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "categorical_crossentropy": LossType.CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
}

_METRICS = {
    "accuracy": MetricsType.ACCURACY,
    "sparse_categorical_crossentropy": MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
    "categorical_crossentropy": MetricsType.CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.MEAN_SQUARED_ERROR,
    "mse": MetricsType.MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.MEAN_ABSOLUTE_ERROR,
}

_OPTS = {
    "sgd": lambda: SGDOptimizer(lr=0.01),
    "adam": lambda: AdamOptimizer(lr=0.001),
}


class _BaseModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.ffmodel: Optional[FFModel] = None
        self._loss = None
        self._metrics: List[MetricsType] = []
        self._optimizer: Optional[Optimizer] = None

    def _build(self, batch_size: int):
        raise NotImplementedError

    def compile(self, optimizer="sgd", loss="sparse_categorical_crossentropy",
                metrics: Sequence[str] = ()):
        if isinstance(optimizer, str):
            optimizer = _OPTS[optimizer.lower()]()
        self._optimizer = optimizer
        self._loss = _LOSSES[loss] if isinstance(loss, str) else loss
        self._metrics = [_METRICS[m] if isinstance(m, str) else m for m in metrics]
        # always measure the loss itself so History/EarlyStopping see a
        # real "loss" value (keras semantics)
        loss_metric = {
            LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
                MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
            LossType.CATEGORICAL_CROSSENTROPY:
                MetricsType.CATEGORICAL_CROSSENTROPY,
            LossType.MEAN_SQUARED_ERROR_AVG_REDUCE:
                MetricsType.MEAN_SQUARED_ERROR,
            LossType.MEAN_SQUARED_ERROR_SUM_REDUCE:
                MetricsType.MEAN_SQUARED_ERROR,
        }.get(self._loss)
        if loss_metric is not None and loss_metric not in self._metrics:
            self._metrics.append(loss_metric)
        self.ffmodel = self._build(self.config.batch_size)
        self.ffmodel.compile(optimizer=self._optimizer, loss_type=self._loss,
                             metrics=self._metrics)
        return self

    def fit(self, x, y, epochs: int = 1, batch_size: Optional[int] = None,
            verbose: bool = True, callbacks: Sequence = ()):
        """Training loop with callback hooks (reference base_model.py:198).
        Always returns a History callback (keras convention)."""
        from flexflow_tpu.frontends.keras.callbacks import (
            EarlyStopping, History,
        )

        history = next((c for c in callbacks if isinstance(c, History)), None)
        if history is None:
            history = History()
            callbacks = list(callbacks) + [history]
        for cb in callbacks:
            cb.model = self
            cb.on_train_begin()
        for epoch in range(epochs):
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            pm = self.ffmodel.fit(x, y, epochs=1, batch_size=batch_size,
                                  verbose=verbose)
            n = max(pm.train_all, 1)
            loss_field = {
                LossType.SPARSE_CATEGORICAL_CROSSENTROPY: pm.sparse_cce_loss,
                LossType.CATEGORICAL_CROSSENTROPY: pm.cce_loss,
                LossType.MEAN_SQUARED_ERROR_AVG_REDUCE: pm.mse_loss,
                LossType.MEAN_SQUARED_ERROR_SUM_REDUCE: pm.mse_loss,
            }.get(self._loss, pm.sparse_cce_loss)
            logs = {"loss": loss_field / n}
            if MetricsType.ACCURACY in self._metrics:
                logs["accuracy"] = pm.train_correct / n
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs)
            if any(getattr(cb, "stop_training", False) for cb in callbacks):
                break
        for cb in callbacks:
            cb.on_train_end()
        return history

    def evaluate(self, x, y, batch_size: Optional[int] = None, verbose: bool = True):
        return self.ffmodel.eval(x, y, batch_size=batch_size, verbose=verbose)

    def predict(self, x, batch_size: Optional[int] = None):
        return self.ffmodel.predict(x, batch_size=batch_size)

    def summary(self) -> str:
        if self.ffmodel is None:
            return "<uncompiled>"
        lines = ["Layer (type)              Output shape"]
        for n in self.ffmodel.graph.topo_order():
            shape = str(n.outputs[0]) if n.outputs else "-"
            lines.append(f"{n.name:<25} {shape}")
        return "\n".join(lines)


class Sequential(_BaseModel):
    """reference keras Sequential (models/base_model.py)"""

    def __init__(self, layers: Optional[Sequence[Layer]] = None,
                 config: Optional[FFConfig] = None):
        super().__init__(config)
        self.layers: List[Layer] = list(layers or [])

    def add(self, layer: Layer):
        self.layers.append(layer)

    def _build(self, batch_size: int) -> FFModel:
        ff = FFModel(self.config)
        if not isinstance(self.layers[0], _InputLayer):
            raise ValueError("Sequential model must start with an Input layer "
                             "(use keras.Input(shape))")
        t = self.layers[0].apply(ff, batch_size)
        for lay in self.layers[1:]:
            t = lay.apply(ff, t)
        return ff

    def add_input(self, shape, **kw):
        from flexflow_tpu.frontends.keras.layers import _InputLayer

        self.layers.insert(0, _InputLayer(tuple(shape), **kw))


class Model(_BaseModel):
    """Functional API: Model(inputs=[...], outputs=out_ktensor)."""

    def __init__(self, inputs: Union[KTensor, Sequence[KTensor]], outputs: KTensor,
                 config: Optional[FFConfig] = None):
        super().__init__(config)
        self.inputs = [inputs] if isinstance(inputs, KTensor) else list(inputs)
        self.outputs = outputs

    def _build(self, batch_size: int) -> FFModel:
        ff = FFModel(self.config)
        cache: Dict[int, object] = {}

        def lower(kt: KTensor):
            if id(kt) in cache:
                return cache[id(kt)]
            if isinstance(kt.layer, _InputLayer):
                t = kt.layer.apply(ff, batch_size)
            else:
                ins = [lower(i) for i in kt.inputs]
                t = kt.layer.apply(ff, *ins)
            cache[id(kt)] = t
            return t

        for i in self.inputs:
            lower(i)
        lower(self.outputs)
        return ff
