"""keras_exp — tf.keras graph-walking frontend (experimental).

Reference analog: python/flexflow/keras_exp/models/model.py (~600 LoC) —
the variant that walks a REAL tf.keras model's graph instead of
re-implementing the keras API (which flexflow_tpu.frontends.keras does).

Design: the walker consumes the standard `model.to_json()` functional
config (Keras 3 format: per-layer `inbound_nodes` carrying
`__keras_tensor__.keras_history = [producer, node_idx, tensor_idx]`), so
importing a model needs NO tensorflow at all — hand the JSON produced
elsewhere to `KerasExpModel(json_config=...)`. With a live tf.keras model,
`KerasExpModel(model)` walks the same config and `copy_weights` pushes the
trained tf weights into the compiled FFModel.

Layout note: Conv/Pool layers must be `channels_first` (the PCG is NCHW,
like the reference); channels_last models raise with a clear message.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.model import FFModel, Tensor

_ACT = {
    "linear": ActiMode.NONE,
    "relu": ActiMode.RELU,
    "gelu": ActiMode.GELU,
    "sigmoid": ActiMode.SIGMOID,
    "tanh": ActiMode.TANH,
    "silu": ActiMode.SILU,
    "swish": ActiMode.SILU,
}


def _histories(obj) -> List[Tuple[str, int, int]]:
    """Collect keras_history refs from an inbound-node args tree in order."""
    out = []
    if isinstance(obj, dict):
        if obj.get("class_name") == "__keras_tensor__":
            h = obj["config"]["keras_history"]
            out.append((h[0], h[1], h[2]))
        else:
            for v in obj.values():
                out.extend(_histories(v))
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            out.extend(_histories(v))
    return out


def _norm_refs(entry) -> List[List]:
    """input_layers/output_layers come as [name, n, t] or [[name, n, t]...]."""
    if entry and isinstance(entry[0], str):
        return [entry]
    return list(entry)


class KerasExpModel:
    """Walks a tf.keras functional/sequential model (or its to_json()
    string) into FFModel layer calls."""

    def __init__(self, model=None, json_config: Optional[str] = None):
        if model is None and json_config is None:
            raise ValueError("pass a tf.keras model or a to_json() string")
        self.model = model
        if json_config is None:
            json_config = model.to_json()
        cfg = json.loads(json_config)
        if cfg.get("class_name") == "Sequential":
            cfg = self._sequential_to_functional(cfg)
        self.config = cfg["config"]
        self._names: List[str] = []  # ff layer names we created (weighted)

    @staticmethod
    def _sequential_to_functional(cfg: Dict) -> Dict:
        """Rewrite a Sequential config into functional form (each layer
        feeds the next). Keras 3 Sequentials built without an explicit
        Input often serialize with NO InputLayer entry — synthesize one so
        the first real layer is lowered instead of aliased to the input."""
        layers = list(cfg["config"]["layers"])
        if not layers or layers[0]["class_name"] != "InputLayer":
            layers.insert(0, {"class_name": "InputLayer",
                              "name": "_seq_input",
                              "config": {"name": "_seq_input"}})
        out = []
        prev = None
        for entry in layers:
            e = dict(entry)
            name = e.get("config", {}).get("name") or e.get("name")
            e["name"] = name
            if prev is None:
                e["inbound_nodes"] = []
            else:
                e["inbound_nodes"] = [{
                    "args": [{
                        "class_name": "__keras_tensor__",
                        "config": {"keras_history": [prev, 0, 0]},
                    }],
                }]
            out.append(e)
            prev = name
        first, last = out[0]["name"], out[-1]["name"]
        return {"config": {"layers": out,
                           "input_layers": [first, 0, 0],
                           "output_layers": [last, 0, 0]}}

    # ------------------------------------------------------------------

    def to_ff(self, ff: FFModel, input_tensors: Sequence[Tensor]) -> List[Tensor]:
        layers = {e.get("name") or e["config"]["name"]: e
                  for e in self.config["layers"]}
        inputs = _norm_refs(self.config["input_layers"])
        outputs = _norm_refs(self.config["output_layers"])
        if len(inputs) != len(input_tensors):
            raise ValueError(
                f"model has {len(inputs)} inputs, got {len(input_tensors)}"
            )
        env: Dict[str, Tensor] = {}
        for (name, _, _), t in zip(inputs, input_tensors):
            env[name] = t

        # topo walk: keras configs list layers in build order
        for entry in self.config["layers"]:
            name = entry.get("name") or entry["config"]["name"]
            if name in env:
                continue
            refs = _histories(entry.get("inbound_nodes", []))
            ins = [env[r[0]] for r in refs]
            env[name] = self._lower(ff, entry["class_name"],
                                    entry["config"], name, ins)
        return [env[name] for (name, _, _) in outputs]

    def _lower(self, ff: FFModel, cls: str, cfg: Dict, name: str,
               ins: List[Tensor]) -> Tensor:
        def act_of(key="activation"):
            a = cfg.get(key) or "linear"
            if isinstance(a, dict):  # serialized Activation object
                a = a.get("config", {}).get("name", "linear")
            if a == "softmax":
                return "softmax"
            if a not in _ACT:
                raise NotImplementedError(f"keras activation {a!r}")
            return _ACT[a]

        if cls == "Dense":
            act = act_of()
            if act == "softmax":
                t = ff.dense(ins[0], cfg["units"],
                             use_bias=cfg.get("use_bias", True), name=name)
                self._names.append(name)
                return ff.softmax(t, name=f"{name}_softmax")
            t = ff.dense(ins[0], cfg["units"], act,
                         use_bias=cfg.get("use_bias", True), name=name)
            self._names.append(name)
            return t
        if cls == "Conv2D":
            if cfg.get("data_format") != "channels_first":
                raise NotImplementedError(
                    "keras_exp lowers NCHW graphs; build the tf model with "
                    "data_format='channels_first' (the PCG is NCHW like the "
                    "reference)"
                )
            kh, kw = cfg["kernel_size"]
            sh, sw = cfg["strides"]
            pad = cfg.get("padding", "valid")
            ph, pw = (kh // 2, kw // 2) if pad == "same" else (0, 0)
            act = act_of()
            if act == "softmax":
                raise NotImplementedError(
                    "Conv2D(activation='softmax') is not lowered"
                )
            t = ff.conv2d(ins[0], cfg["filters"], kh, kw, sh, sw, ph, pw,
                          use_bias=cfg.get("use_bias", True),
                          activation=act, name=name)
            self._names.append(name)
            return t
        if cls in ("MaxPooling2D", "AveragePooling2D"):
            if cfg.get("data_format") != "channels_first":
                raise NotImplementedError("pooling must be channels_first")
            kh, kw = cfg["pool_size"]
            sh, sw = cfg["strides"] or (kh, kw)
            pad = cfg.get("padding", "valid")
            ph, pw = (kh // 2, kw // 2) if pad == "same" else (0, 0)
            pt = PoolType.MAX if cls == "MaxPooling2D" else PoolType.AVG
            return ff.pool2d(ins[0], kh, kw, sh, sw, ph, pw, pt, name=name)
        if cls == "GlobalAveragePooling2D":
            return ff.mean(ins[0], axes=(2, 3), name=name)
        if cls == "Flatten":
            return ff.flat(ins[0], name=name)
        if cls == "Dropout":
            return ff.dropout(ins[0], cfg["rate"], name=name)
        if cls == "Activation":
            a = act_of("activation")
            if a == "softmax":
                return ff.softmax(ins[0], name=name)
            if a == ActiMode.NONE:
                return ff.identity(ins[0], name=name)
            fn = {ActiMode.RELU: ff.relu, ActiMode.GELU: ff.gelu,
                  ActiMode.SIGMOID: ff.sigmoid, ActiMode.TANH: ff.tanh,
                  ActiMode.SILU: ff.silu}[a]
            return fn(ins[0], name=name)
        if cls == "ReLU":
            return ff.relu(ins[0], name=name)
        if cls == "Softmax":
            return ff.softmax(ins[0], axis=cfg.get("axis", -1), name=name)
        if cls == "Add":
            t = ins[0]
            for i, o in enumerate(ins[1:]):
                t = ff.add(t, o, name=f"{name}_{i}" if len(ins) > 2 else name)
            return t
        if cls == "Multiply":
            t = ins[0]
            for i, o in enumerate(ins[1:]):
                t = ff.multiply(t, o,
                                name=f"{name}_{i}" if len(ins) > 2 else name)
            return t
        if cls == "Concatenate":
            return ff.concat(ins, axis=cfg.get("axis", -1), name=name)
        if cls == "Embedding":
            t = ff.embedding(ins[0], cfg["input_dim"], cfg["output_dim"],
                             name=name)
            self._names.append(name)
            return t
        if cls == "BatchNormalization":
            t = ff.batch_norm(ins[0], relu=False, name=name)
            self._names.append(name)
            return t
        if cls == "LayerNormalization":
            t = ff.layer_norm(ins[0], axes=(-1,),
                              eps=cfg.get("epsilon", 1e-3), name=name)
            self._names.append(name)
            return t
        raise NotImplementedError(f"keras layer {cls} not supported")

    # ------------------------------------------------------------------

    def copy_weights(self, ff: FFModel) -> None:
        """Push the live tf model's trained weights into the compiled
        FFModel (requires construction from a model, not bare JSON)."""
        if self.model is None:
            raise ValueError("copy_weights needs the live tf.keras model")
        for name in self._names:
            layer = self.model.get_layer(name)
            ws = layer.get_weights()
            cls = type(layer).__name__
            if cls == "Dense":
                ff.set_weight(name, ws[0], "kernel")  # (in, out) matches
                if len(ws) > 1:
                    ff.set_weight(name, ws[1], "bias")
            elif cls == "Conv2D":
                # keras HWIO -> our OIHW
                ff.set_weight(name, ws[0].transpose(3, 2, 0, 1), "kernel")
                if len(ws) > 1:
                    ff.set_weight(name, ws[1], "bias")
            elif cls == "Embedding":
                ff.set_weight(name, ws[0], "kernel")
            elif cls == "BatchNormalization":
                gamma, beta, mean, var = ws
                ff.set_weight(name, gamma, "scale")
                ff.set_weight(name, beta, "bias")
                ff.set_weight(name, mean, "running_mean")
                ff.set_weight(name, var, "running_var")
            elif cls == "LayerNormalization":
                # get_weights() content depends on scale/center flags:
                # [gamma, beta], [gamma], [beta], or []
                lcfg = layer.get_config()
                idx = 0
                if lcfg.get("scale", True):
                    ff.set_weight(name, ws[idx], "scale")
                    idx += 1
                if lcfg.get("center", True):
                    ff.set_weight(name, ws[idx], "bias")
