"""ONNX frontend (reference python/flexflow/onnx/model.py:56:
`ONNXModel(onnx.load(path)).apply(ffmodel, inputs)`).

The onnx package is optional — the class raises a clear ImportError when
it's missing. Supported ops mirror the reference's set: Gemm/MatMul, Conv,
Relu/Sigmoid/Tanh/Softmax, MaxPool/AveragePool, Add/Sub/Mul, Concat,
Flatten, Reshape, Dropout, BatchNormalization.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from flexflow_tpu.ffconst import PoolType
from flexflow_tpu.model import FFModel, Tensor


class ONNXModel:
    def __init__(self, model_or_path):
        try:
            import onnx
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "the onnx package is required for the ONNX frontend"
            ) from e
        if isinstance(model_or_path, str):
            model_or_path = onnx.load(model_or_path)
        self.model = model_or_path

    def apply(self, ff: FFModel, input_tensors: Dict[str, Tensor]) -> List[Tensor]:
        graph = self.model.graph
        env: Dict[str, Tensor] = dict(input_tensors)
        inits = {i.name: i for i in graph.initializer}

        def attr(node, name, default=None):
            for a in node.attribute:
                if a.name == name:
                    if a.type == 7:  # INTS
                        return list(a.ints)
                    if a.type == 2:  # INT
                        return a.i
                    if a.type == 1:  # FLOAT
                        return a.f
            return default

        for node in graph.node:
            op = node.op_type
            name = node.name or node.output[0]
            if op == "Gemm":
                x = env[node.input[0]]
                w = inits[node.input[1]]
                out_dim = list(w.dims)[0 if attr(node, "transB", 0) else 1]
                env[node.output[0]] = ff.dense(
                    x, out_dim, use_bias=len(node.input) > 2, name=name
                )
            elif op == "MatMul":
                if node.input[1] in inits:
                    w = inits[node.input[1]]
                    env[node.output[0]] = ff.dense(
                        env[node.input[0]], list(w.dims)[-1], use_bias=False,
                        name=name,
                    )
                else:
                    env[node.output[0]] = ff.batch_matmul(
                        env[node.input[0]], env[node.input[1]], name=name
                    )
            elif op == "Conv":
                k = attr(node, "kernel_shape")
                s = attr(node, "strides", [1, 1])
                p = attr(node, "pads", [0, 0, 0, 0])
                g = attr(node, "group", 1)
                w = inits[node.input[1]]
                env[node.output[0]] = ff.conv2d(
                    env[node.input[0]], list(w.dims)[0], k[0], k[1], s[0], s[1],
                    p[0], p[1], groups=g, use_bias=len(node.input) > 2, name=name,
                )
            elif op in ("MaxPool", "AveragePool"):
                k = attr(node, "kernel_shape")
                s = attr(node, "strides", [1, 1])  # ONNX default is 1 per axis
                p = attr(node, "pads", [0, 0, 0, 0])
                env[node.output[0]] = ff.pool2d(
                    env[node.input[0]], k[0], k[1], s[0], s[1], p[0], p[1],
                    PoolType.MAX if op == "MaxPool" else PoolType.AVG, name=name,
                )
            elif op == "GlobalAveragePool":
                env[node.output[0]] = ff.mean(env[node.input[0]], (2, 3),
                                              keepdims=True, name=name)
            elif op == "Relu":
                env[node.output[0]] = ff.relu(env[node.input[0]], name=name)
            elif op == "Sigmoid":
                env[node.output[0]] = ff.sigmoid(env[node.input[0]], name=name)
            elif op == "Tanh":
                env[node.output[0]] = ff.tanh(env[node.input[0]], name=name)
            elif op == "Softmax":
                env[node.output[0]] = ff.softmax(env[node.input[0]],
                                                 attr(node, "axis", -1), name=name)
            elif op in ("Add", "Sub", "Mul"):
                a = env[node.input[0]]
                if node.input[1] in env:
                    b = env[node.input[1]]
                else:
                    # constant operand: materialize the initializer as a
                    # weight node holding its values
                    from onnx import numpy_helper

                    from flexflow_tpu.runtime.initializer import ArrayInitializer

                    arr = numpy_helper.to_array(inits[node.input[1]])
                    b = ff.create_weight(
                        arr.shape, initializer=ArrayInitializer(arr),
                        name=f"{name}_const",
                    )
                    env[node.input[1]] = b
                fn = {"Add": ff.add, "Sub": ff.subtract, "Mul": ff.multiply}[op]
                env[node.output[0]] = fn(a, b, name=name)
            elif op == "Concat":
                env[node.output[0]] = ff.concat(
                    [env[i] for i in node.input], attr(node, "axis", 0), name=name
                )
            elif op == "Flatten":
                env[node.output[0]] = ff.flat(env[node.input[0]], name=name)
            elif op == "Reshape":
                shape_init = inits[node.input[1]]
                shape = [int(s) for s in
                         np.frombuffer(shape_init.raw_data, dtype=np.int64)]
                x = env[node.input[0]]
                # ONNX: 0 copies the corresponding input dim, -1 is inferred
                shape = [x.shape[i] if s == 0 else s
                         for i, s in enumerate(shape)]
                total = int(np.prod(x.shape))
                known = int(np.prod([s for s in shape if s != -1]))
                shape = [total // known if s == -1 else s for s in shape]
                env[node.output[0]] = ff.reshape(x, shape, name=name)
            elif op == "Dropout":
                env[node.output[0]] = ff.dropout(
                    env[node.input[0]], attr(node, "ratio", 0.5), name=name
                )
            elif op == "BatchNormalization":
                env[node.output[0]] = ff.batch_norm(env[node.input[0]],
                                                    relu=False, name=name)
            elif op == "Identity":
                env[node.output[0]] = env[node.input[0]]
            else:
                raise NotImplementedError(f"ONNX op {op} not supported")
        return [env[o.name] for o in graph.output]
