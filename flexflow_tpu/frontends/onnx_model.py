"""ONNX frontend (reference python/flexflow/onnx/model.py:56:
`ONNXModel(onnx.load(path)).apply(ffmodel, inputs)`).

The onnx package is optional — the class raises a clear ImportError when
it's missing. Supported ops extend the reference's set: Gemm/MatMul, Conv,
Relu/Sigmoid/Tanh/Softmax/Gelu, MaxPool/AveragePool, Add/Sub/Mul/Div/
Pow/Sqrt/Exp, Concat/Split/Gather/Transpose/Squeeze/Unsqueeze, Flatten,
Reshape, Cast, Dropout, BatchNormalization, LayerNormalization,
ReduceMean/ReduceSum, TopK.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from flexflow_tpu.ffconst import PoolType
from flexflow_tpu.model import FFModel, Tensor


def _init_ints(init):
    """Integer list from a TensorProto, via numpy_helper (raw_data may be
    empty when values live in int64_data)."""
    from onnx import numpy_helper

    return [int(v) for v in numpy_helper.to_array(init).reshape(-1)]


def _operand(ff: FFModel, env, inits, input_name: str, node_name: str):
    """Resolve an op input: an env tensor, or a constant initializer
    materialized as a weight node (handles every ONNX tensor encoding via
    numpy_helper)."""
    if input_name in env:
        return env[input_name]
    from onnx import numpy_helper

    from flexflow_tpu.runtime.initializer import ArrayInitializer

    arr = numpy_helper.to_array(inits[input_name])
    t = ff.create_weight(arr.shape, initializer=ArrayInitializer(arr),
                         name=f"{node_name}_const")
    env[input_name] = t
    return t


class ONNXModel:
    def __init__(self, model_or_path):
        try:
            import onnx
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "the onnx package is required for the ONNX frontend"
            ) from e
        if isinstance(model_or_path, str):
            model_or_path = onnx.load(model_or_path)
        self.model = model_or_path

    def apply(self, ff: FFModel, input_tensors: Dict[str, Tensor]) -> List[Tensor]:
        graph = self.model.graph
        env: Dict[str, Tensor] = dict(input_tensors)
        inits = {i.name: i for i in graph.initializer}

        def attr(node, name, default=None):
            for a in node.attribute:
                if a.name == name:
                    if a.type == 7:  # INTS
                        return list(a.ints)
                    if a.type == 2:  # INT
                        return a.i
                    if a.type == 1:  # FLOAT
                        return a.f
            return default

        for node in graph.node:
            op = node.op_type
            name = node.name or node.output[0]
            if op == "Gemm":
                x = env[node.input[0]]
                w = inits[node.input[1]]
                out_dim = list(w.dims)[0 if attr(node, "transB", 0) else 1]
                env[node.output[0]] = ff.dense(
                    x, out_dim, use_bias=len(node.input) > 2, name=name
                )
            elif op == "MatMul":
                if node.input[1] in inits:
                    w = inits[node.input[1]]
                    env[node.output[0]] = ff.dense(
                        env[node.input[0]], list(w.dims)[-1], use_bias=False,
                        name=name,
                    )
                else:
                    env[node.output[0]] = ff.batch_matmul(
                        env[node.input[0]], env[node.input[1]], name=name
                    )
            elif op == "Conv":
                k = attr(node, "kernel_shape")
                s = attr(node, "strides", [1, 1])
                p = attr(node, "pads", [0, 0, 0, 0])
                g = attr(node, "group", 1)
                w = inits[node.input[1]]
                env[node.output[0]] = ff.conv2d(
                    env[node.input[0]], list(w.dims)[0], k[0], k[1], s[0], s[1],
                    p[0], p[1], groups=g, use_bias=len(node.input) > 2, name=name,
                )
            elif op in ("MaxPool", "AveragePool"):
                k = attr(node, "kernel_shape")
                s = attr(node, "strides", [1, 1])  # ONNX default is 1 per axis
                p = attr(node, "pads", [0, 0, 0, 0])
                env[node.output[0]] = ff.pool2d(
                    env[node.input[0]], k[0], k[1], s[0], s[1], p[0], p[1],
                    PoolType.MAX if op == "MaxPool" else PoolType.AVG, name=name,
                )
            elif op == "GlobalAveragePool":
                env[node.output[0]] = ff.mean(env[node.input[0]], (2, 3),
                                              keepdims=True, name=name)
            elif op == "Relu":
                env[node.output[0]] = ff.relu(env[node.input[0]], name=name)
            elif op == "Sigmoid":
                env[node.output[0]] = ff.sigmoid(env[node.input[0]], name=name)
            elif op == "Tanh":
                env[node.output[0]] = ff.tanh(env[node.input[0]], name=name)
            elif op == "Softmax":
                env[node.output[0]] = ff.softmax(env[node.input[0]],
                                                 attr(node, "axis", -1), name=name)
            elif op in ("Add", "Sub", "Mul", "Div"):
                a = env[node.input[0]]
                b = _operand(ff, env, inits, node.input[1], name)
                fn = {"Add": ff.add, "Sub": ff.subtract, "Mul": ff.multiply,
                      "Div": ff.divide}[op]
                env[node.output[0]] = fn(a, b, name=name)
            elif op == "Concat":
                env[node.output[0]] = ff.concat(
                    [env[i] for i in node.input], attr(node, "axis", 0), name=name
                )
            elif op == "Flatten":
                env[node.output[0]] = ff.flat(env[node.input[0]], name=name)
            elif op == "Reshape":
                shape = _init_ints(inits[node.input[1]])
                x = env[node.input[0]]
                # ONNX: 0 copies the corresponding input dim, -1 is inferred
                shape = [x.shape[i] if s == 0 else s
                         for i, s in enumerate(shape)]
                total = int(np.prod(x.shape))
                known = int(np.prod([s for s in shape if s != -1]))
                shape = [total // known if s == -1 else s for s in shape]
                env[node.output[0]] = ff.reshape(x, shape, name=name)
            elif op == "Dropout":
                env[node.output[0]] = ff.dropout(
                    env[node.input[0]], attr(node, "ratio", 0.5), name=name
                )
            elif op == "BatchNormalization":
                env[node.output[0]] = ff.batch_norm(env[node.input[0]],
                                                    relu=False, name=name)
            elif op == "Identity":
                env[node.output[0]] = env[node.input[0]]
            elif op == "Pow":
                exp_init = inits.get(node.input[1])
                if exp_init is None:
                    raise NotImplementedError(
                        f"ONNX Pow {name!r}: dynamic exponent not supported"
                    )
                from onnx import numpy_helper

                exponent = float(numpy_helper.to_array(exp_init).reshape(-1)[0])
                env[node.output[0]] = ff.pow(env[node.input[0]], exponent,
                                             name=name)
            elif op == "Sqrt":
                env[node.output[0]] = ff.pow(env[node.input[0]], 0.5, name=name)
            elif op == "Exp":
                env[node.output[0]] = ff.exp(env[node.input[0]], name=name)
            elif op == "Gelu":
                env[node.output[0]] = ff.gelu(env[node.input[0]], name=name)
            elif op == "Transpose":
                perm = attr(node, "perm")
                env[node.output[0]] = ff.transpose(env[node.input[0]],
                                                   perm, name=name)
            elif op == "Split":
                axis = attr(node, "axis", 0)
                sizes = attr(node, "split")
                x = env[node.input[0]]
                if sizes is None and len(node.input) > 1 and node.input[1] in inits:
                    sizes = _init_ints(inits[node.input[1]])
                if sizes is None:
                    n_out = len(node.output)
                    sizes = [x.shape[axis] // n_out] * n_out
                outs = ff.split(x, sizes, axis, name=name)
                for o_name, o in zip(node.output, outs):
                    env[o_name] = o
            elif op == "Gather":
                # embedding-style gather: a 2-D initializer table becomes
                # an embedding carrying the table's PRETRAINED values
                table = inits.get(node.input[0])
                if table is not None and node.input[0] not in env \
                        and len(table.dims) == 2:
                    from onnx import numpy_helper

                    from flexflow_tpu.runtime.initializer import (
                        ArrayInitializer,
                    )

                    arr = numpy_helper.to_array(table)
                    env[node.output[0]] = ff.embedding(
                        env[node.input[1]], arr.shape[0], arr.shape[1],
                        kernel_initializer=ArrayInitializer(arr), name=name,
                    )
                else:
                    env[node.output[0]] = ff.gather(
                        _operand(ff, env, inits, node.input[0], name),
                        env[node.input[1]],
                        attr(node, "axis", 0), name=name,
                    )
            elif op in ("Squeeze", "Unsqueeze"):
                x = env[node.input[0]]
                axes = attr(node, "axes")
                if axes is None and len(node.input) > 1 and node.input[1] in inits:
                    axes = _init_ints(inits[node.input[1]])
                if op == "Unsqueeze" and axes is None:
                    raise NotImplementedError(
                        f"ONNX Unsqueeze {name!r}: axes from a dynamic "
                        "tensor are not supported"
                    )
                shape = list(x.shape)
                if op == "Squeeze":
                    axes = sorted([a % len(shape) for a in (axes or
                                  [i for i, s in enumerate(shape) if s == 1])],
                                  reverse=True)
                    for a in axes:
                        shape.pop(a)
                else:
                    for a in sorted(a % (len(shape) + 1) for a in axes):
                        shape.insert(a, 1)
                env[node.output[0]] = ff.reshape(x, shape, name=name)
            elif op == "Cast":
                from flexflow_tpu.ffconst import DataType

                onnx_to_dt = {1: DataType.FLOAT, 6: DataType.INT32,
                              7: DataType.INT64, 10: DataType.HALF,
                              16: DataType.BFLOAT16}
                to = onnx_to_dt.get(attr(node, "to", 1), DataType.FLOAT)
                env[node.output[0]] = ff.cast(env[node.input[0]], to, name=name)
            elif op == "LayerNormalization":
                env[node.output[0]] = ff.layer_norm(
                    env[node.input[0]], axes=(attr(node, "axis", -1),),
                    eps=attr(node, "epsilon", 1e-5), name=name,
                )
            elif op in ("ReduceMean", "ReduceSum"):
                axes = attr(node, "axes")
                if axes is None and len(node.input) > 1 and node.input[1] in inits:
                    axes = _init_ints(inits[node.input[1]])
                if axes is None:
                    if len(node.input) > 1:
                        raise NotImplementedError(
                            f"ONNX {op} {name!r}: axes from a dynamic "
                            "tensor are not supported"
                        )
                    # per spec: no axes attr = reduce over ALL dims
                    axes = list(range(len(env[node.input[0]].shape)))
                keep = bool(attr(node, "keepdims", 1))
                fn = ff.mean if op == "ReduceMean" else ff.reduce_sum
                env[node.output[0]] = fn(env[node.input[0]],
                                         tuple(axes), keepdims=keep,
                                         name=name)
            elif op == "TopK":
                k = attr(node, "k")
                if k is None and len(node.input) > 1 and node.input[1] in inits:
                    k = _init_ints(inits[node.input[1]])[0]
                vals, idx = ff.top_k(env[node.input[0]], int(k), name=name)
                env[node.output[0]] = vals
                if len(node.output) > 1:
                    env[node.output[1]] = idx
            else:
                raise NotImplementedError(f"ONNX op {op} not supported")
        return [env[o.name] for o in graph.output]
