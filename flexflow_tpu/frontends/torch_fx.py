"""PyTorch frontend: torch.fx symbolic trace -> FFModel graph.

Reference analog: python/flexflow/torch/model.py — `PyTorchModel` wraps
`torch.fx.symbolic_trace` (:2408-2495), ~55 Node classes map fx ops to
FFModel layer calls (:43-2345), and a text IR supports decoupled
export/import (`torch_to_file`/`file_to_ff`, :2597/:2540: trace on a CPU
box with torch installed, train on the TPU pod without it).

Weight transfer: `copy_weights` pushes traced module parameters into the
compiled FFModel (torch Linear stores (out,in) — transposed into our
(in,out) layout; Conv2d OIHW matches).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from flexflow_tpu.ffconst import ActiMode, DataType, PoolType
from flexflow_tpu.model import FFModel, Tensor


def _flatten_dims(ff: FFModel, x: Tensor, start: int, end: int,
                  name: Optional[str] = None) -> Tensor:
    """torch.flatten(x, start_dim, end_dim) semantics via reshape."""
    nd = len(x.shape)
    start, end = start % nd, end % nd
    if start == 1 and end == nd - 1:
        return ff.flat(x, name=name)
    shape = (list(x.shape[:start])
             + [int(np.prod(x.shape[start:end + 1]))]
             + list(x.shape[end + 1:]))
    return ff.reshape(x, shape, name=name)


def _rms_norm_class_name(mod) -> bool:
    cls = type(mod).__name__
    return cls.endswith("RMSNorm") or cls == "T5LayerNorm"


def _is_rms_norm_module(mod) -> bool:
    """RMSNorm-family detection by class name + shape of the module: a
    single 1-D `weight` parameter and a variance epsilon. Covers
    transformers' T5LayerNorm / LlamaRMSNorm / MistralRMSNorm / GemmaRMSNorm
    and torch.nn.RMSNorm without importing any of them. Reads _parameters
    directly — during fx tracing, attribute access on a module is patched
    to return Proxies, and Proxy.__bool__ raises."""
    if not _rms_norm_class_name(mod):
        return False
    params = getattr(mod, "_parameters", {})
    w = params.get("weight")
    return w is not None and getattr(w, "ndim", 0) == 1


def _rms_eps(mod) -> float:
    for attr in ("variance_epsilon", "eps"):
        v = getattr(mod, attr, None)
        if v is not None:  # 0.0 is a legitimate explicit eps
            return float(v)
    return 1e-6


def _act(ff: FFModel, t: Tensor, mod) -> Tensor:
    import torch.nn as nn

    table = {
        nn.ReLU: ff.relu,
        nn.GELU: ff.gelu,
        nn.Sigmoid: ff.sigmoid,
        nn.Tanh: ff.tanh,
        nn.SiLU: ff.silu,
        nn.ELU: ff.elu,
    }
    return table[type(mod)](t)


class PyTorchModel:
    """Wraps a torch.nn.Module; `torch_to_ff` replays its fx graph as
    FFModel layer calls and returns the output tensors."""

    def __init__(self, model, seq_length: Optional[int] = None):
        import torch.fx

        class _HFAwareTracer(torch.fx.Tracer):
            """HF-aware coalescing (reference torch/model.py:2408-2495
            special-cases T5LayerNorm / mt5): RMSNorm-family modules are
            kept as LEAF nodes so they lower to one RMS_NORM op instead of
            an exploded mean/rsqrt/mul subgraph whose weights can't be
            mapped back."""

            def is_leaf_module(self, m, qualname):
                if _is_rms_norm_module(m):
                    return True
                return super().is_leaf_module(m, qualname)

        self.model = model
        graph = _HFAwareTracer().trace(model)
        self.traced = torch.fx.GraphModule(model, graph)
        # module path -> ALL ff node names it lowered to (a module called at
        # several sites becomes several FF layers; copy_weights fills each).
        # Note: the copies are not tied for training — updates diverge.
        self._name_map: Dict[str, List[str]] = {}
        # nn.LSTM modules expand into one FF lstm per (layer, direction),
        # each needing its OWN weight slice: target -> [(ff_name, layer,
        # is_reverse)]
        self._rnn_map: Dict[str, List[tuple]] = {}

    # ------------------------------------------------------------------

    def torch_to_ff(self, ff: FFModel, input_tensors: Sequence[Tensor]) -> List[Tensor]:
        import operator

        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        env: Dict[str, Union[Tensor, float, int, tuple]] = {}
        inputs = list(input_tensors)
        outputs: List[Tensor] = []

        def val(a):
            if isinstance(a, torch.fx.Node):
                return env[a.name]
            if isinstance(a, (list, tuple)):
                return type(a)(val(x) for x in a)
            return a

        for node in self.traced.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = inputs.pop(0)
            elif node.op == "get_attr":
                # constants/buffers/parameters all become weights holding
                # their traced values
                import operator as _op

                from flexflow_tpu.runtime.initializer import ArrayInitializer

                try:
                    t = self.traced.get_parameter(node.target)
                except AttributeError:
                    try:
                        t = self.traced.get_buffer(node.target)
                    except AttributeError:
                        t = _op.attrgetter(node.target)(self.traced)
                arr = t.detach().numpy()
                env[node.name] = ff.create_weight(
                    arr.shape, initializer=ArrayInitializer(arr), name=node.name
                )
            elif node.op == "call_module":
                mod = self.traced.get_submodule(node.target)
                x = val(node.args[0])
                env[node.name] = self._lower_module(ff, node, mod, x)
            elif node.op == "call_function":
                env[node.name] = self._lower_function(ff, node, val)
            elif node.op == "call_method":
                env[node.name] = self._lower_method(ff, node, val)
            elif node.op == "output":
                out = val(node.args[0])
                outputs = list(out) if isinstance(out, (list, tuple)) else [out]
        return outputs

    # ------------------------------------------------------------------

    def _record(self, target: str, t: Tensor) -> Tensor:
        # the layer call may have deduped the requested name; record the
        # final node name so copy_weights hits the right layer(s)
        self._name_map.setdefault(target, []).append(t.node.name)
        return t

    def _lower_module(self, ff: FFModel, node, mod, x: Tensor) -> Tensor:
        import torch.nn as nn

        name = node.target.replace(".", "_")
        if isinstance(mod, nn.Linear):
            return self._record(node.target, ff.dense(
                x, mod.out_features, use_bias=mod.bias is not None, name=name))
        if isinstance(mod, nn.Conv2d):
            return self._record(node.target, ff.conv2d(
                x, mod.out_channels, *mod.kernel_size,
                stride_h=mod.stride[0], stride_w=mod.stride[1],
                padding_h=mod.padding[0], padding_w=mod.padding[1],
                groups=mod.groups, use_bias=mod.bias is not None, name=name,
            ))
        if isinstance(mod, nn.Embedding):
            return self._record(node.target, ff.embedding(
                x, mod.num_embeddings, mod.embedding_dim, name=name))
        if isinstance(mod, nn.BatchNorm2d):
            return self._record(node.target, ff.batch_norm(x, relu=False, name=name))
        if isinstance(mod, nn.LayerNorm):
            return self._record(node.target, ff.layer_norm(
                x, axes=tuple(range(-len(mod.normalized_shape), 0)),
                elementwise_affine=mod.elementwise_affine,
                eps=mod.eps, name=name))
        if isinstance(mod, nn.MaxPool2d):
            k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
            s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride,) * 2
            p = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
            return ff.pool2d(x, k[0], k[1], s[0], s[1], p[0], p[1], PoolType.MAX,
                             name=name)
        if isinstance(mod, nn.AvgPool2d):
            k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
            s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride,) * 2
            p = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
            return ff.pool2d(x, k[0], k[1], s[0], s[1], p[0], p[1], PoolType.AVG,
                             name=name)
        if isinstance(mod, nn.AdaptiveAvgPool2d):
            out = mod.output_size if isinstance(mod.output_size, tuple) else (mod.output_size,) * 2
            h, w = x.shape[2], x.shape[3]
            if out == (1, 1):
                return ff.mean(x, axes=(2, 3), keepdims=True, name=name)
            kh, kw = h // out[0], w // out[1]
            return ff.pool2d(x, kh, kw, kh, kw, 0, 0, PoolType.AVG, name=name)
        if isinstance(mod, nn.Dropout):
            return ff.dropout(x, mod.p, name=name)
        if isinstance(mod, nn.Flatten):
            return _flatten_dims(ff, x, mod.start_dim, mod.end_dim, name=name)
        if isinstance(mod, nn.Softmax):
            return ff.softmax(x, axis=mod.dim if mod.dim is not None else -1, name=name)
        if isinstance(mod, nn.Identity):
            return ff.identity(x, name=name)
        if isinstance(mod, (nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh, nn.SiLU, nn.ELU)):
            return _act(ff, x, mod)
        if _is_rms_norm_module(mod):
            return self._record(node.target,
                                ff.rms_norm(x, eps=_rms_eps(mod), name=name))
        if isinstance(mod, nn.LSTM):
            # expands into one FF lstm op per (layer, direction); returns
            # the torch-shaped (output, states) tuple so downstream getitem
            # nodes unpack it. The packed (layers*dirs, batch, hidden)
            # states have no faithful analog here, so consuming them raises
            # (see _TorchLSTMStates).
            if not mod.batch_first:
                raise NotImplementedError(
                    "nn.LSTM import requires batch_first=True (framework "
                    "layout is (batch, seq, dim))"
                )
            if getattr(mod, "proj_size", 0):
                raise NotImplementedError(
                    "nn.LSTM proj_size != 0 is not supported"
                )
            if len(node.args) > 1 or node.kwargs:
                raise NotImplementedError(
                    "nn.LSTM import with explicit initial states is not "
                    "supported (torch packs them (layers*dirs, batch, "
                    "hidden); build with FFModel.lstm(initial_state=...) "
                    "directly)"
                )
            t, entries = _build_lstm_stack(
                ff, x, mod.hidden_size, mod.num_layers, mod.bidirectional,
                float(mod.dropout), mod.bias, name,
            )
            self._rnn_map.setdefault(node.target, []).extend(entries)
            return (t, _TorchLSTMStates())
        if isinstance(mod, nn.Sequential):
            t = x
            for child_name, sub in mod.named_children():
                # qualify by the child's own module path so names stay unique
                # and copy_weights resolves the actual leaf module
                fake = type(
                    "N", (),
                    {"target": f"{node.target}.{child_name}",
                     "name": f"{node.name}_{child_name}"},
                )
                t = self._lower_module(ff, fake, sub, t)
            return t
        raise NotImplementedError(f"torch module {type(mod).__name__} not supported")

    def _lower_function(self, ff: FFModel, node, val):
        import operator

        import torch
        import torch.nn.functional as F

        fn = node.target
        a = [val(x) for x in node.args]
        if fn is operator.getitem:
            if not isinstance(a[0], Tensor):
                # unpacking a module's tuple return (e.g. nn.LSTM's
                # (output, states)) — or hitting a placeholder like
                # _TorchLSTMStates, whose __getitem__ raises its own
                # targeted message
                return a[0][a[1]]
            return self._lower_getitem(ff, a[0], a[1])
        if fn in (operator.add, torch.add):
            if isinstance(a[1], Tensor):
                return ff.add(a[0], a[1])
            return ff.scalar_add(a[0], float(a[1]))
        if fn in (operator.sub, torch.sub):
            if isinstance(a[1], Tensor):
                return ff.subtract(a[0], a[1])
            return ff.scalar_sub(a[0], float(a[1]))
        if fn in (operator.mul, torch.mul):
            if isinstance(a[1], Tensor):
                return ff.multiply(a[0], a[1])
            return ff.scalar_multiply(a[0], float(a[1]))
        if fn in (operator.truediv, torch.div):
            if isinstance(a[1], Tensor):
                return ff.divide(a[0], a[1])
            return ff.scalar_true_divide(a[0], float(a[1]))
        if fn in (torch.relu, F.relu):
            return ff.relu(a[0])
        if fn is F.gelu:
            return ff.gelu(a[0])
        if fn in (torch.sigmoid, F.sigmoid):
            return ff.sigmoid(a[0])
        if fn in (torch.tanh, F.tanh):
            return ff.tanh(a[0])
        if fn in (torch.flatten,):
            start = a[1] if len(a) > 1 else node.kwargs.get("start_dim", 0)
            end = a[2] if len(a) > 2 else node.kwargs.get("end_dim", -1)
            return _flatten_dims(ff, a[0], int(start), int(end))
        if fn in (torch.cat,):
            axis = node.kwargs.get("dim", 0)
            if len(node.args) > 1:
                axis = node.args[1]
            return ff.concat(a[0], axis=axis)
        if fn in (torch.matmul, torch.bmm):
            return ff.batch_matmul(a[0], a[1])
        if fn is F.softmax:
            return ff.softmax(a[0], axis=node.kwargs.get("dim", -1))
        if fn is torch.exp:
            return ff.exp(a[0])
        if fn is torch.pow:
            return ff.pow(a[0], float(a[1]))
        if fn is torch.rsqrt:
            return ff.rsqrt(a[0])
        if fn is torch.mean:
            dims = a[1] if len(a) > 1 else node.kwargs.get("dim")
            keep = node.kwargs.get("keepdim", False)
            return ff.mean(a[0], axes=tuple(dims) if isinstance(dims, (list, tuple)) else (dims,), keepdims=keep)
        if fn in (F.avg_pool2d, F.max_pool2d):
            from flexflow_tpu.ffconst import PoolType

            ks = a[1] if len(a) > 1 else node.kwargs["kernel_size"]
            kh, kw = (ks, ks) if isinstance(ks, int) else tuple(ks)
            st = (a[2] if len(a) > 2 else None) or node.kwargs.get("stride") or ks
            sh, sw = (st, st) if isinstance(st, int) else tuple(st)
            pad = a[3] if len(a) > 3 else node.kwargs.get("padding", 0)
            ph, pw = (pad, pad) if isinstance(pad, int) else tuple(pad)
            pt = PoolType.AVG if fn is F.avg_pool2d else PoolType.MAX
            return ff.pool2d(a[0], kh, kw, sh, sw, ph, pw, pool_type=pt)
        if fn in (F.silu,):
            return ff.silu(a[0])
        if fn is F.dropout:
            rate = node.kwargs.get("p", a[1] if len(a) > 1 else 0.5)
            return ff.dropout(a[0], rate=float(rate))
        raise NotImplementedError(f"torch function {fn} not supported")

    def _lower_getitem(self, ff: FFModel, x: Tensor, idx):
        return _tensor_getitem(ff, x, idx)

    def _lower_method(self, ff: FFModel, node, val):
        a = [val(x) for x in node.args]
        m = node.target
        x = a[0]
        if m in ("view", "reshape"):
            shape = a[1:] if not isinstance(a[1], (list, tuple)) else list(a[1])
            shape = [int(s) for s in shape]
            total = int(np.prod(x.shape))
            known = int(np.prod([s for s in shape if s != -1]))
            shape = [total // known if s == -1 else s for s in shape]
            return ff.reshape(x, shape)
        if m == "flatten":
            start = a[1] if len(a) > 1 else node.kwargs.get("start_dim", 0)
            end = a[2] if len(a) > 2 else node.kwargs.get("end_dim", -1)
            return _flatten_dims(ff, x, int(start), int(end))
        if m == "transpose":
            d0, d1 = a[1], a[2]
            perm = list(range(len(x.shape)))
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return ff.transpose(x, perm)
        if m == "permute":
            perm = a[1:] if not isinstance(a[1], (list, tuple)) else list(a[1])
            return ff.transpose(x, [int(p) for p in perm])
        if m == "contiguous":
            return x
        if m == "size":
            return x.shape[a[1]] if len(a) > 1 else x.shape
        raise NotImplementedError(f"torch method {m} not supported")

    # ------------------------------------------------------------------

    def copy_weights(self, ff: FFModel):
        """Push the torch module's trained weights into the compiled model."""
        import torch.nn as nn

        for target, ff_names in self._name_map.items():
            mod = self.traced.get_submodule(target)
            for ff_name in ff_names:
                if isinstance(mod, nn.Linear):
                    ff.set_weight(ff_name, mod.weight.detach().numpy().T, "kernel")
                    if mod.bias is not None:
                        ff.set_weight(ff_name, mod.bias.detach().numpy(), "bias")
                elif isinstance(mod, nn.Conv2d):
                    ff.set_weight(ff_name, mod.weight.detach().numpy(), "kernel")
                    if mod.bias is not None:
                        ff.set_weight(ff_name, mod.bias.detach().numpy(), "bias")
                elif isinstance(mod, nn.Embedding):
                    ff.set_weight(ff_name, mod.weight.detach().numpy(), "kernel")
                elif isinstance(mod, nn.LayerNorm):
                    ff.set_weight(ff_name, mod.weight.detach().numpy(), "scale")
                    ff.set_weight(ff_name, mod.bias.detach().numpy(), "bias")
                elif isinstance(mod, nn.BatchNorm2d):
                    ff.set_weight(ff_name, mod.weight.detach().numpy(), "scale")
                    ff.set_weight(ff_name, mod.bias.detach().numpy(), "bias")
                    ff.set_weight(ff_name, mod.running_mean.detach().numpy(),
                                  "running_mean")
                    ff.set_weight(ff_name, mod.running_var.detach().numpy(),
                                  "running_var")
                elif _is_rms_norm_module(mod):
                    w = mod.weight.detach().numpy()
                    # Gemma's RMSNorm scales by (1 + weight); our RMS_NORM
                    # scales by the stored weight, so fold the +1 in
                    if type(mod).__name__.startswith("Gemma"):
                        w = w + 1.0
                    ff.set_weight(ff_name, w, "scale")
        for target, entries in self._rnn_map.items():
            mod = self.traced.get_submodule(target)
            for ff_name, layer, rev in entries:
                sfx = f"l{layer}" + ("_reverse" if rev else "")
                ff.set_weight(
                    ff_name,
                    getattr(mod, f"weight_ih_{sfx}").detach().numpy().T, "wx")
                ff.set_weight(
                    ff_name,
                    getattr(mod, f"weight_hh_{sfx}").detach().numpy().T, "wh")
                if mod.bias:
                    b = (getattr(mod, f"bias_ih_{sfx}")
                         + getattr(mod, f"bias_hh_{sfx}")).detach().numpy()
                    ff.set_weight(ff_name, b, "bias")

    # ------------------------------------------------------------------
    # text IR (reference torch_to_file/file_to_ff, torch/model.py:2597,2540)

    def torch_to_file(self, path: str):
        """Serialize the fx graph to a text IR so the TPU side can rebuild
        the model without torch installed."""
        import torch

        lines = []
        for node in self.traced.graph.nodes:
            if node.op == "placeholder":
                lines.append(f"input\t{node.name}")
            elif node.op == "output":
                srcs = node.args[0]
                if not isinstance(srcs, (list, tuple)):
                    srcs = [srcs]
                lines.append("output\t" + ",".join(s.name for s in srcs))
            elif node.op == "call_module":
                mod = self.traced.get_submodule(node.target)
                spec = _module_spec(mod)
                if len(node.args) > 1 or node.kwargs:
                    # the IR records one input per module line; extra call
                    # args (e.g. nn.LSTM initial states) would be silently
                    # dropped and the rebuilt model would diverge
                    raise NotImplementedError(
                        f"text-IR: module {node.target} called with extra "
                        "args/kwargs; only single-input module calls export"
                    )
                args = ",".join(a.name for a in node.args
                                if isinstance(a, torch.fx.Node))
                lines.append(f"module\t{node.name}\t{args}\t{spec}")
            elif node.op in ("call_function", "call_method"):
                import operator

                fname = getattr(node.target, "__name__", str(node.target))
                args = []
                for a in node.args:
                    args.append(a.name if isinstance(a, torch.fx.Node) else repr(a))
                lines.append(f"{node.op}\t{node.name}\t{fname}\t{';'.join(args)}")
        with open(path, "w") as f:
            f.write("\n".join(lines))


class _TorchLSTMStates:
    """Placeholder for nn.LSTM's (h_n, c_n) return slot: torch packs states
    as (num_layers*num_directions, batch, hidden), which the
    per-(layer, direction) expansion cannot reproduce faithfully — so a
    model that actually CONSUMES them fails loudly here instead of
    computing silently wrong results. (`y, _ = self.lstm(x)` binds but
    never touches this and imports fine.)"""

    def _unsupported(self):
        raise NotImplementedError(
            "nn.LSTM import: consuming h_n/c_n is not supported (torch "
            "packs them (layers*dirs, batch, hidden)); read the sequence "
            "output instead, or build with FFModel.lstm directly"
        )

    def __getitem__(self, i):
        self._unsupported()

    def __iter__(self):
        self._unsupported()


def _build_lstm_stack(ff: FFModel, x: Tensor, hidden: int, layers: int,
                      bidir: bool, dropout: float, use_bias: bool,
                      name: str):
    """Shared stacked/bidirectional nn.LSTM expansion (fx import + text-IR
    replay): one FF lstm per (layer, direction), directions concatenated on
    the feature dim, inter-layer dropout. Returns (output, entries) where
    entries = [(ff_node_name, layer, is_reverse)] for weight copy."""
    t, entries = x, []
    for layer in range(layers):
        y, _, _ = ff.lstm(t, hidden, use_bias=use_bias,
                          name=f"{name}_l{layer}")
        entries.append((y.node.name, layer, False))
        if bidir:
            yr, _, _ = ff.lstm(t, hidden, use_bias=use_bias, reverse=True,
                               name=f"{name}_l{layer}_rev")
            entries.append((yr.node.name, layer, True))
            y = ff.concat([y, yr], axis=-1, name=f"{name}_l{layer}_cat")
        t = y
        if dropout and layer < layers - 1:
            t = ff.dropout(t, dropout, name=f"{name}_l{layer}_do")
    return t, entries


def _tensor_getitem(ff: FFModel, x: Tensor, idx):
    """Basic tensor indexing (`y[:, -1]`, `y[..., :h]`): each indexed dim
    becomes a split that keeps the addressed piece; int indices squeeze
    their dim afterwards. Step slices / advanced indexing unsupported."""
    idx = idx if isinstance(idx, tuple) else (idx,)
    if any(it is Ellipsis for it in idx):
        # expand `...` to full slices over the unindexed middle dims
        pos = idx.index(Ellipsis)
        fill = len(x.shape) - (len(idx) - 1)
        idx = idx[:pos] + (slice(None),) * fill + idx[pos + 1:]
    t, squeeze = x, []
    for dim, it in enumerate(idx):
        size = t.shape[dim]
        if isinstance(it, slice):
            if it == slice(None):
                continue
            if it.step not in (None, 1):
                raise NotImplementedError(f"step slice {it} not supported")
            start, stop, _ = it.indices(size)
            if stop <= start:
                raise NotImplementedError(f"empty slice {it}")
            keep_start, keep_len = start, stop - start
        elif isinstance(it, int):
            if not -size <= it < size:
                raise IndexError(
                    f"index {it} out of range for dim {dim} of size {size}"
                )
            keep_start, keep_len = it % size, 1
            squeeze.append(dim)
        else:
            raise NotImplementedError(f"index {it!r} not supported")
        sizes = [keep_start, keep_len, size - keep_start - keep_len]
        keep_pos = int(keep_start > 0)  # a leading piece shifts the kept one
        pieces = ff.split(t, [s for s in sizes if s > 0], axis=dim)
        t = pieces[keep_pos] if isinstance(pieces, list) else pieces
    if squeeze:
        shape = [s for d, s in enumerate(t.shape) if d not in squeeze]
        t = ff.reshape(t, shape)
    return t


def _parse_index(s: str):
    """Parse a getitem index serialized by repr() back into ints/slices/
    tuples/Ellipsis — WITHOUT eval (IR files are untrusted input)."""
    import ast

    def conv(n):
        if isinstance(n, ast.Tuple):
            return tuple(conv(e) for e in n.elts)
        if isinstance(n, ast.Call) and getattr(n.func, "id", "") == "slice":
            return slice(*(conv(a) for a in n.args))
        if isinstance(n, ast.Constant):
            return n.value
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
            return -conv(n.operand)
        if isinstance(n, ast.Name) and n.id == "Ellipsis":
            return Ellipsis
        raise NotImplementedError(f"text-IR index {s!r}")

    return conv(ast.parse(s, mode="eval").body)


def _module_spec(mod) -> str:
    import torch.nn as nn

    if isinstance(mod, nn.Linear):
        return f"Linear:{mod.in_features}:{mod.out_features}:{int(mod.bias is not None)}"
    if isinstance(mod, nn.Conv2d):
        return (f"Conv2d:{mod.out_channels}:{mod.kernel_size[0]}:{mod.kernel_size[1]}"
                f":{mod.stride[0]}:{mod.stride[1]}:{mod.padding[0]}:{mod.padding[1]}"
                f":{mod.groups}:{int(mod.bias is not None)}")
    if isinstance(mod, nn.ReLU):
        return "ReLU"
    if isinstance(mod, nn.GELU):
        return "GELU"
    if isinstance(mod, nn.MaxPool2d):
        k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
        s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride,) * 2
        p = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
        return f"MaxPool2d:{k[0]}:{k[1]}:{s[0]}:{s[1]}:{p[0]}:{p[1]}"
    if isinstance(mod, nn.Flatten):
        return "Flatten"
    if isinstance(mod, nn.Dropout):
        return f"Dropout:{mod.p}"
    if isinstance(mod, nn.Softmax):
        return f"Softmax:{mod.dim if mod.dim is not None else -1}"
    if isinstance(mod, nn.LayerNorm):
        return f"LayerNorm:{len(mod.normalized_shape)}:{mod.eps}"
    if isinstance(mod, nn.Embedding):
        return f"Embedding:{mod.num_embeddings}:{mod.embedding_dim}"
    if isinstance(mod, nn.BatchNorm2d):
        return "BatchNorm2d"
    if _is_rms_norm_module(mod):
        return f"RMSNorm:{_rms_eps(mod)}"
    if isinstance(mod, nn.LSTM):
        if not mod.batch_first:
            raise NotImplementedError("text-IR LSTM requires batch_first=True")
        if getattr(mod, "proj_size", 0):
            raise NotImplementedError("text-IR LSTM proj_size != 0 unsupported")
        return (f"LSTM:{mod.hidden_size}:{mod.num_layers}"
                f":{int(mod.bidirectional)}:{mod.dropout}:{int(mod.bias)}")
    raise NotImplementedError(f"no text-IR spec for {type(mod).__name__}")


def file_to_ff(path: str, ff: FFModel, input_tensors: Sequence[Tensor]) -> List[Tensor]:
    """Rebuild an FFModel graph from the text IR (no torch needed)."""
    env: Dict[str, Tensor] = {}
    inputs = list(input_tensors)
    outputs: List[Tensor] = []
    with open(path) as f:
        for line in f.read().splitlines():
            if not line.strip():
                continue
            parts = line.split("\t")
            kind = parts[0]
            if kind == "input":
                env[parts[1]] = inputs.pop(0)
            elif kind == "output":
                outputs = [env[n] for n in parts[1].split(",")]
            elif kind == "module":
                name, args, spec = parts[1], parts[2], parts[3]
                x = env[args.split(",")[0]]
                env[name] = _apply_spec(ff, spec, x, name)
            elif kind in ("call_function", "call_method"):
                import ast

                name, fname, rawargs = parts[1], parts[2], parts[3]
                args = rawargs.split(";")
                ts = [env[a] for a in args if a in env]

                def scalars():
                    # scalar operand may come before or after the tensor;
                    # parse with literal_eval (never eval untrusted IR
                    # files). Lazy: getitem's slice reprs aren't literals.
                    return [ast.literal_eval(a) for a in args if a not in env]
                if fname == "add":
                    env[name] = (ff.add(ts[0], ts[1]) if len(ts) > 1
                                 else ff.scalar_add(ts[0], float(scalars()[0])))
                elif fname == "mul":
                    env[name] = (ff.multiply(ts[0], ts[1]) if len(ts) > 1
                                 else ff.scalar_multiply(ts[0], float(scalars()[0])))
                elif fname == "flatten":
                    env[name] = ff.flat(ts[0])
                elif fname == "relu":
                    env[name] = ff.relu(ts[0])
                elif fname == "getitem":
                    v = ts[0]
                    # the index is the SECOND arg (repr-serialized)
                    sub = _parse_index(args[1])
                    if isinstance(v, Tensor):
                        env[name] = _tensor_getitem(ff, v, sub)
                    else:
                        # tuple returns / placeholders index themselves
                        env[name] = v[sub]
                else:
                    raise NotImplementedError(f"text-IR function {fname}")
    return outputs


def _apply_spec(ff: FFModel, spec: str, x: Tensor, name: str) -> Tensor:
    parts = spec.split(":")
    kind = parts[0]
    if kind == "Linear":
        return ff.dense(x, int(parts[2]), use_bias=bool(int(parts[3])), name=name)
    if kind == "Conv2d":
        o, kh, kw, sh, sw, ph, pw, g, b = (int(p) for p in parts[1:])
        return ff.conv2d(x, o, kh, kw, sh, sw, ph, pw, groups=g,
                         use_bias=bool(b), name=name)
    if kind == "ReLU":
        return ff.relu(x, name=name)
    if kind == "GELU":
        return ff.gelu(x, name=name)
    if kind == "MaxPool2d":
        vals = [int(p) for p in parts[1:]]
        kh, kw, sh, sw = vals[:4]
        ph, pw = vals[4:6] if len(vals) >= 6 else (0, 0)
        return ff.pool2d(x, kh, kw, sh, sw, ph, pw, name=name)
    if kind == "Flatten":
        return ff.flat(x, name=name)
    if kind == "Dropout":
        return ff.dropout(x, float(parts[1]), name=name)
    if kind == "Softmax":
        return ff.softmax(x, axis=int(parts[1]), name=name)
    if kind == "LayerNorm":
        return ff.layer_norm(x, axes=tuple(range(-int(parts[1]), 0)),
                             eps=float(parts[2]), name=name)
    if kind == "Embedding":
        return ff.embedding(x, int(parts[1]), int(parts[2]), name=name)
    if kind == "BatchNorm2d":
        return ff.batch_norm(x, relu=False, name=name)
    if kind == "RMSNorm":
        return ff.rms_norm(x, eps=float(parts[1]), name=name)
    if kind == "LSTM":
        hidden, layers, bidir, drop, bias = (
            int(parts[1]), int(parts[2]), bool(int(parts[3])),
            float(parts[4]), bool(int(parts[5])),
        )
        t, _ = _build_lstm_stack(ff, x, hidden, layers, bidir, drop, bias,
                                 name)
        return (t, _TorchLSTMStates())
    raise NotImplementedError(f"text-IR spec {kind}")
