"""FFModel — the central user-facing model object.

Reference analog: `FFModel` (include/flexflow/model.h:326, cffi surface
python/flexflow/core/flexflow_cffi.py:883): layer-building methods record a
lazy graph; `compile()` turns it into a PCG, picks a parallelization
strategy, and lowers to jitted SPMD step functions; `fit()/eval()` drive the
training loop (flexflow_cffi.py:2044-2088).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OpType,
    ParamSyncType,
    PoolType,
)
from flexflow_tpu.ops import attrs as A
from flexflow_tpu.parallel.mesh import make_mesh
from flexflow_tpu.parallel.sharding import ShardingView, data_batch_spec
from flexflow_tpu.pcg.graph import Graph, Node
from flexflow_tpu.pcg.tensor import TensorShape
from flexflow_tpu.runtime.executor import Executor, node_key
from flexflow_tpu.runtime.metrics import PerfMetrics
from flexflow_tpu.runtime.optimizer import Optimizer, SGDOptimizer


@dataclasses.dataclass
class Tensor:
    """Frontend tensor handle (reference tensor.h:85): points at a graph
    node output."""

    node: Node
    idx: int = 0

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(d.size for d in self.node.outputs[self.idx].dims)

    @property
    def dims(self) -> Tuple[int, ...]:
        return self.shape

    @property
    def dtype(self) -> DataType:
        return self.node.outputs[self.idx].dtype

    def __repr__(self):
        return f"Tensor({self.node.name}:{self.idx} {self.shape})"


class FFModel:
    """Build a layer graph, compile it to a sharded training program, train."""

    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.graph = Graph()
        self._executor: Optional[Executor] = None
        self._mesh = None
        self._params = None  # (trainable, nontrainable)
        self._opt_state = None
        self._optimizer: Optional[Optimizer] = None
        self._loss_type: Optional[LossType] = None
        self._metrics: List[MetricsType] = []
        self._init_overrides: Dict[str, Dict] = {}
        self._cache_scores: Dict[str, object] = {}
        self._cache_snapshots: Dict[str, object] = {}
        self._used_names: set = set()
        self._rng_seed = self.config.seed
        # set by compile() when validate_top_k >= 2 ran the empirical
        # strategy validation: {"timed_ms", "modeled_ms",
        # "picked_modeled_rank"}
        self.strategy_validation: Optional[Dict] = None
        # set by compile() when the strategy search ran: the modeled
        # candidate pool [(cost, graph, strategy)] and search-cost stats
        # {"wall_s", "expansions", "baseline_cost", ...}
        self.searched_candidates: List = []
        self.search_stats: Dict = {}
        self._step_count = 0
        self._fit_calls = 0
        self.current_metrics: Optional[PerfMetrics] = None

    # ------------------------------------------------------------------
    # graph building helpers

    def _add(self, op_type: OpType, op_attrs, inputs: Sequence[Tensor], name: Optional[str]) -> Node:
        name = name or op_type.value
        # node names must be unique: strategies, weight access, and strategy
        # export/import files are keyed by name
        if name in self._used_names:
            base = name
            while name in self._used_names:
                name = f"{base}_{self.graph.new_guid()}"
        self._used_names.add(name)
        node = self.graph.create_node(op_type, op_attrs, name)
        for i, t in enumerate(inputs):
            self.graph.add_edge(t.node, node, t.idx, i)
        node.outputs = tuple(
            op_attrs.infer(*[t.node.outputs[t.idx] for t in inputs])
        )
        return node

    def _one(self, op_type, op_attrs, inputs, name) -> Tensor:
        return Tensor(self._add(op_type, op_attrs, inputs, name))

    def _record_init(self, node: Node, **inits):
        d = {k: v for k, v in inits.items() if v is not None}
        if d:
            self._init_overrides[node_key(node)] = d

    # ------------------------------------------------------------------
    # inputs / weights

    def create_tensor(self, dims: Sequence[int], dtype: DataType = DataType.FLOAT,
                      name: Optional[str] = None) -> Tensor:
        shape = TensorShape(tuple(dims), dtype)
        return self._one(OpType.INPUT, A.InputAttrs(shape), [], name or "input")

    def create_weight(self, dims: Sequence[int], dtype: DataType = DataType.FLOAT,
                      initializer=None, name: Optional[str] = None) -> Tensor:
        shape = TensorShape(tuple(dims), dtype)
        node = self._add(OpType.WEIGHT, A.WeightAttrs(shape), [], name or "weight")
        self._record_init(node, weight=initializer)
        return Tensor(node)

    # ------------------------------------------------------------------
    # layers (reference model.h:336-552 surface)

    def dense(self, input: Tensor, out_dim: int, activation: ActiMode = ActiMode.NONE,
              use_bias: bool = True, kernel_initializer=None, bias_initializer=None,
              name: Optional[str] = None) -> Tensor:
        node = self._add(
            OpType.LINEAR,
            A.LinearAttrs(out_dim, use_bias, ActiMode.coerce(activation)),
            [input],
            name or "dense",
        )
        self._record_init(node, kernel=kernel_initializer, bias=bias_initializer)
        return Tensor(node)

    def conv2d(self, input: Tensor, out_channels: int, kernel_h: int, kernel_w: int,
               stride_h: int = 1, stride_w: int = 1, padding_h: int = 0,
               padding_w: int = 0, activation: ActiMode = ActiMode.NONE,
               groups: int = 1, use_bias: bool = True, kernel_initializer=None,
               bias_initializer=None, name: Optional[str] = None) -> Tensor:
        node = self._add(
            OpType.CONV2D,
            A.Conv2DAttrs(
                out_channels, (kernel_h, kernel_w), (stride_h, stride_w),
                (padding_h, padding_w), groups, use_bias,
                ActiMode.coerce(activation),
            ),
            [input],
            name or "conv2d",
        )
        self._record_init(node, kernel=kernel_initializer, bias=bias_initializer)
        return Tensor(node)

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int, stride_h: int,
               stride_w: int, padding_h: int = 0, padding_w: int = 0,
               pool_type: PoolType = PoolType.MAX,
               activation: ActiMode = ActiMode.NONE,
               name: Optional[str] = None) -> Tensor:
        return self._one(
            OpType.POOL2D,
            A.Pool2DAttrs((kernel_h, kernel_w), (stride_h, stride_w),
                          (padding_h, padding_w), PoolType.coerce(pool_type),
                          ActiMode.coerce(activation)),
            [input], name or "pool2d",
        )

    def embedding(self, input: Tensor, num_entries: int, out_dim: int,
                  aggr: AggrMode = AggrMode.NONE, dtype: DataType = DataType.FLOAT,
                  kernel_initializer=None, name: Optional[str] = None) -> Tensor:
        node = self._add(
            OpType.EMBEDDING,
            A.EmbeddingAttrs(num_entries, out_dim, AggrMode.coerce(aggr), dtype),
            [input], name or "embedding",
        )
        self._record_init(node, kernel=kernel_initializer)
        return Tensor(node)

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int, kdim: int = 0,
                            vdim: int = 0, dropout: float = 0.0, bias: bool = True,
                            causal: bool = False, kv_heads: Optional[int] = None,
                            rope: bool = False, rope_theta: float = 10000.0,
                            kernel_initializer=None,
                            name: Optional[str] = None) -> Tensor:
        node = self._add(
            OpType.MULTIHEAD_ATTENTION,
            A.MultiHeadAttentionAttrs(
                embed_dim, num_heads, kv_heads, kdim // num_heads if kdim else None,
                causal, bias, dropout, rope, rope_theta,
            ),
            [query, key, value], name or "attention",
        )
        self._record_init(node, wq=kernel_initializer, wk=kernel_initializer,
                          wv=kernel_initializer, wo=kernel_initializer)
        return Tensor(node)

    def ring_attention(self, query: Tensor, key: Tensor, value: Tensor,
                       embed_dim: int, num_heads: int, causal: bool = True,
                       kv_heads: Optional[int] = None, rope: bool = False,
                       rope_theta: float = 10000.0, seq_mode: str = "ring",
                       name: Optional[str] = None) -> Tensor:
        return self._one(
            OpType.RING_ATTENTION,
            A.RingAttentionAttrs(embed_dim, num_heads, kv_heads, None, causal,
                                 False, 0.0, rope, rope_theta, seq_mode),
            [query, key, value], name or "ring_attention",
        )

    def ulysses_attention(self, query: Tensor, key: Tensor, value: Tensor,
                          embed_dim: int, num_heads: int, causal: bool = True,
                          kv_heads: Optional[int] = None, rope: bool = False,
                          rope_theta: float = 10000.0,
                          name: Optional[str] = None) -> Tensor:
        """Sequence parallelism via seq<->head all-to-all exchange
        (DeepSpeed-Ulysses; lowers through OpType.ALL_TO_ALL semantics)."""
        return self.ring_attention(
            query, key, value, embed_dim, num_heads, causal=causal,
            kv_heads=kv_heads, rope=rope, rope_theta=rope_theta,
            seq_mode="ulysses", name=name or "ulysses_attention",
        )

    def silu(self, x, name=None):
        return self._unary("silu", x, name)

    def batch_matmul(self, a: Tensor, b: Tensor, a_seq_length_dim: int = -1,
                     b_seq_length_dim: int = -1, name: Optional[str] = None) -> Tensor:
        return self._one(
            OpType.BATCH_MATMUL,
            A.BatchMatmulAttrs(a_seq_length_dim, b_seq_length_dim),
            [a, b], name or "batch_matmul",
        )

    # ---- elementwise binary ----

    def _binary(self, kind: str, x: Tensor, y: Tensor, name) -> Tensor:
        return self._one(OpType.ELEMENT_BINARY, A.ElementBinaryAttrs(kind), [x, y],
                         name or kind)

    def add(self, x, y, name=None):
        return self._binary("add", x, y, name)

    def add_position_embedding(self, x, table, name=None):
        """Add a learned absolute-position row table (seq_len, dim) onto
        (batch, seq, dim) activations. Unlike a plain add, the op is
        MARKED as a position table: KV-cache decode slices the rows at
        the cache position, and generate() refuses lengths beyond the
        table (GPT-2/BERT-style positions)."""
        return self._one(
            OpType.ELEMENT_BINARY,
            A.ElementBinaryAttrs("add", position_table=True),
            [x, table], name or "add_pos",
        )

    def subtract(self, x, y, name=None):
        return self._binary("subtract", x, y, name)

    def multiply(self, x, y, name=None):
        return self._binary("multiply", x, y, name)

    def divide(self, x, y, name=None):
        return self._binary("divide", x, y, name)

    def max(self, x, y, name=None):
        return self._binary("max", x, y, name)

    def min(self, x, y, name=None):
        return self._binary("min", x, y, name)

    # ---- elementwise unary ----

    def _unary(self, kind: str, x: Tensor, name, scalar: float = 0.0,
               inplace: bool = False) -> Tensor:
        return self._one(OpType.ELEMENT_UNARY,
                         A.ElementUnaryAttrs(kind, scalar, inplace), [x], name or kind)

    def exp(self, x, name=None):
        return self._unary("exp", x, name)

    def sin(self, x, name=None):
        return self._unary("sin", x, name)

    def cos(self, x, name=None):
        return self._unary("cos", x, name)

    def relu(self, x, inplace: bool = True, name=None):
        return self._unary("relu", x, name, inplace=inplace)

    def gelu(self, x, name=None):
        return self._unary("gelu", x, name)

    def sigmoid(self, x, name=None):
        return self._unary("sigmoid", x, name)

    def tanh(self, x, name=None):
        return self._unary("tanh", x, name)

    def elu(self, x, name=None):
        return self._unary("elu", x, name)

    def rsqrt(self, x, name=None):
        return self._unary("rsqrt", x, name)

    def pow(self, x, exponent: float, name=None):
        return self._unary("pow", x, name, scalar=exponent)

    def identity(self, x, name=None):
        return self._unary("identity", x, name)

    def scalar_add(self, x, scalar: float, name=None):
        return self._unary("scalar_add", x, name, scalar=scalar)

    def scalar_sub(self, x, scalar: float, name=None):
        return self._unary("scalar_sub", x, name, scalar=scalar)

    def scalar_multiply(self, x, scalar: float, name=None):
        return self._unary("scalar_multiply", x, name, scalar=scalar)

    def scalar_true_divide(self, x, scalar: float, name=None):
        return self._unary("scalar_truediv", x, name, scalar=scalar)

    # ---- shape ----

    def reshape(self, input: Tensor, shape: Sequence[int], name=None) -> Tensor:
        return self._one(OpType.RESHAPE, A.ReshapeAttrs(tuple(shape)), [input],
                         name or "reshape")

    def flat(self, input: Tensor, name=None) -> Tensor:
        return self._one(OpType.FLAT, A.FlatAttrs(), [input], name or "flat")

    def transpose(self, input: Tensor, perm: Sequence[int], name=None) -> Tensor:
        return self._one(OpType.TRANSPOSE, A.TransposeAttrs(tuple(perm)), [input],
                         name or "transpose")

    def reverse(self, input: Tensor, axis: int, name=None) -> Tensor:
        return self._one(OpType.REVERSE, A.ReverseAttrs(axis), [input],
                         name or "reverse")

    def concat(self, tensors: Sequence[Tensor], axis: int, name=None) -> Tensor:
        # normalize here so attrs-equality (CSE, substitution-rule matching)
        # never sees axis=-1 and axis=ndim-1 as distinct ops
        axis = axis % len(tensors[0].shape)
        return self._one(OpType.CONCAT, A.ConcatAttrs(axis), list(tensors),
                         name or "concat")

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]], axis: int,
              name=None) -> List[Tensor]:
        axis = axis % len(input.shape)
        if isinstance(sizes, int):
            total = input.shape[axis]
            sizes = [total // sizes] * sizes
        node = self._add(OpType.SPLIT, A.SplitAttrs(tuple(sizes), axis), [input],
                         name or "split")
        return [Tensor(node, i) for i in range(len(sizes))]

    def cast(self, input: Tensor, dtype: DataType, name=None) -> Tensor:
        return self._one(OpType.CAST, A.CastAttrs(dtype), [input], name or "cast")

    # ---- norm / softmax / dropout ----

    def batch_norm(self, input: Tensor, relu: bool = True, name=None) -> Tensor:
        return self._one(OpType.BATCH_NORM, A.BatchNormAttrs(relu), [input],
                         name or "batch_norm")

    def layer_norm(self, input: Tensor, axes: Sequence[int] = (-1,),
                   elementwise_affine: bool = True, eps: float = 1e-5,
                   name=None) -> Tensor:
        return self._one(
            OpType.LAYER_NORM,
            A.LayerNormAttrs(tuple(axes), elementwise_affine, eps),
            [input], name or "layer_norm",
        )

    def rms_norm(self, input: Tensor, eps: float = 1e-6, name=None) -> Tensor:
        return self._one(OpType.RMS_NORM, A.RMSNormAttrs(eps), [input],
                         name or "rms_norm")

    def softmax(self, input: Tensor, axis: int = -1, name=None) -> Tensor:
        return self._one(OpType.SOFTMAX, A.SoftmaxAttrs(axis), [input],
                         name or "softmax")

    def dropout(self, input: Tensor, rate: float, seed: int = 0, name=None) -> Tensor:
        return self._one(OpType.DROPOUT, A.DropoutAttrs(rate, seed), [input],
                         name or "dropout")

    # ---- gather / reduce / topk ----

    def gather(self, input: Tensor, index: Tensor, axis: int, name=None) -> Tensor:
        return self._one(OpType.GATHER, A.GatherAttrs(axis), [input, index],
                         name or "gather")

    def reduce_sum(self, input: Tensor, axes: Sequence[int], keepdims: bool = False,
                   name=None) -> Tensor:
        return self._one(OpType.REDUCE_SUM, A.ReduceAttrs("sum", tuple(axes), keepdims),
                         [input], name or "reduce_sum")

    def mean(self, input: Tensor, axes: Sequence[int], keepdims: bool = False,
             name=None) -> Tensor:
        return self._one(OpType.MEAN, A.ReduceAttrs("mean", tuple(axes), keepdims),
                         [input], name or "mean")

    def top_k(self, input: Tensor, k: int, sorted: bool = True,
              name=None) -> Tuple[Tensor, Tensor]:
        node = self._add(OpType.TOPK, A.TopKAttrs(k, sorted), [input], name or "topk")
        return Tensor(node, 0), Tensor(node, 1)

    # ---- recurrent ----

    def lstm(self, input: Tensor, hidden: int,
             initial_state: Optional[Tuple[Tensor, Tensor]] = None,
             use_bias: bool = True, reverse: bool = False,
             name=None) -> Tuple[Tensor, Tensor, Tensor]:
        """LSTM over a (batch, seq, dim) sequence -> (outputs, h_n, c_n)
        (reference legacy NMT LSTM node, nmt/rnn.h:161). `initial_state`
        wires a decoder to an encoder's final (h, c)."""
        ins = [input] + (list(initial_state) if initial_state else [])
        node = self._add(OpType.LSTM, A.LSTMAttrs(hidden, use_bias, reverse),
                         ins, name or "lstm")
        return Tensor(node, 0), Tensor(node, 1), Tensor(node, 2)

    # ---- MoE ----

    def group_by(self, input: Tensor, assign: Tensor, n: int, alpha: float,
                 name=None) -> List[Tensor]:
        node = self._add(OpType.GROUP_BY, A.GroupByAttrs(n, alpha), [input, assign],
                         name or "group_by")
        return [Tensor(node, i) for i in range(n)]

    def aggregate(self, inputs: Sequence[Tensor], n: int, lambda_bal: float = 0.0,
                  name=None) -> Tensor:
        return self._one(OpType.AGGREGATE, A.AggregateAttrs(n, lambda_bal),
                         list(inputs), name or "aggregate")

    def aggregate_spec(self, inputs: Sequence[Tensor], n: int,
                       lambda_bal: float = 0.0, name=None) -> Tensor:
        return self._one(OpType.AGGREGATE_SPEC, A.AggregateSpecAttrs(n, lambda_bal),
                         list(inputs), name or "aggregate_spec")

    def experts(self, input: Tensor, gate: Tensor, n_experts: int, k: int,
                hidden_dim: int, out_dim: int, alpha: float = 1.0,
                activation: ActiMode = ActiMode.GELU, lambda_bal: float = 1e-2,
                dispatch: str = "sort", name=None) -> Tensor:
        return self._one(
            OpType.EXPERTS,
            A.ExpertsAttrs(n_experts, k, hidden_dim, out_dim, alpha,
                           ActiMode.coerce(activation), lambda_bal,
                           dispatch=dispatch),
            [input, gate], name or "experts",
        )

    def moe(self, input: Tensor, num_exp: int, num_select: int, expert_hidden_size: int,
            alpha: float = 2.0, lambda_bal: float = 0.04, name=None) -> Tensor:
        """Composite MoE layer (reference src/ops/moe.cc:20-44): gate dense →
        top-k → group_by → per-expert dense → aggregate."""
        gate_preds = self.dense(input, num_exp, name=f"{name or 'moe'}_gate")
        gate_sm = self.softmax(gate_preds, name=f"{name or 'moe'}_gate_sm")
        topk_values, topk_assign = self.top_k(gate_sm, num_select)
        grouped = self.group_by(input, topk_assign, num_exp, alpha)
        expert_outs = []
        for i, g in enumerate(grouped):
            h = self.dense(g, expert_hidden_size, ActiMode.RELU,
                           name=f"{name or 'moe'}_expert{i}")
            expert_outs.append(h)
        agg_inputs = [topk_values, topk_assign, topk_assign, gate_sm] + expert_outs
        return self.aggregate(agg_inputs, num_exp, lambda_bal, name=name)

    def pipeline(self, input: Tensor, layers: int, heads: int, kv_heads: int,
                 hidden: int, n_microbatches: int = 4, causal: bool = True,
                 rope_theta: float = 500000.0, norm_eps: float = 1e-5,
                 name=None) -> Tensor:
        """Stacked decoder blocks as a GPipe pipeline composite (fills the
        reference's OP_PIPELINE stub — runs as stages over the `pipe` mesh
        axis when present, else as a layer-stacked scan)."""
        return self._one(
            OpType.PIPELINE,
            A.PipelineAttrs(layers, heads, kv_heads, hidden, n_microbatches,
                            causal, rope_theta, norm_eps),
            [input], name or "pipeline",
        )

    def cache(self, input: Tensor, score_func=None, name=None) -> Tensor:
        """Activation cache (reference src/ops/cache.cc). During training
        the op stores its input into a non-trainable buffer each step;
        `score_func(old, new) -> float` (the reference's user score, e.g.
        moe.cc similarity) is evaluated host-side via `cache_score(name)`
        — typically inside a RecompileState trigger that swaps the model
        between recompute and cached modes when the score degrades."""
        name = name or "cache"
        t = self._one(OpType.CACHE, A.CacheAttrs(), [input], name)
        if score_func is not None:
            self._cache_scores[t.node.name] = score_func
        return t

    def cache_score(self, name: str) -> float:
        """Run the cache's score function on (previous snapshot, current
        buffer); snapshots the current buffer for the next call. Returns
        1.0 on the first call (nothing to compare)."""
        import numpy as np_

        node = next(n for n in self.graph.nodes if n.name == name)
        key = node_key(node)
        _, ntr = self._params
        cur = np_.asarray(ntr[key]["cached"])
        prev = self._cache_snapshots.get(name)
        self._cache_snapshots[name] = cur
        if prev is None:
            return 1.0
        fn = self._cache_scores.get(name)
        if fn is None:
            # default score: cosine-like similarity (reference default is a
            # user-provided function; this mirrors the moe.cc example)
            denom = float((prev * prev).sum() ** 0.5 * (cur * cur).sum() ** 0.5)
            return float((prev * cur).sum()) / max(denom, 1e-30)
        return float(fn(prev, cur))

    # ------------------------------------------------------------------
    # compile / fit / eval  (reference flexflow_cffi.py:2004-2088)

    def compile(self, optimizer: Optional[Optimizer] = None,
                loss_type: LossType = LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                metrics: Sequence[MetricsType] = (),
                comp_mode: CompMode = CompMode.TRAINING,
                strategy: Optional[Dict[str, ShardingView]] = None):
        """Convert the layer graph to a PCG, pick a parallelization strategy,
        and lower to jitted SPMD step functions.

        `strategy` maps node name -> ShardingView for manual strategies; when
        omitted, DP over all devices is used unless config.search_budget > 0
        (then the strategy search runs — see flexflow_tpu.search).
        """
        import jax

        cfg = self.config
        self._optimizer = optimizer or SGDOptimizer()
        self._loss_type = loss_type
        self._metrics = list(metrics)

        self.graph.infer_shapes()

        if cfg.perform_fusion:
            # reference --fusion / apply_fusion (model.cc:2965): fold
            # fusable op pairs into one PCG node before search/lowering.
            # XLA fuses kernels regardless; this shrinks the searched graph.
            from flexflow_tpu.search.substitution import (
                make_fuse_linear_activation,
            )

            xf = make_fuse_linear_activation()
            while True:
                cands = xf.apply_all(self.graph)
                if not cands:
                    break
                self.graph = cands[0]

        devices = cfg.devices
        if cfg.mesh_shape:
            mesh_axes = dict(cfg.mesh_shape)
        else:
            mesh_axes = {"data": len(devices)}
        if (cfg.enable_submesh and "data_sub" not in mesh_axes
                and mesh_axes.get("data", 1) >= 4
                and mesh_axes["data"] % 2 == 0):
            # submesh placement: split data into data x data_sub so views
            # can target a device subset (MachineView start/stride analog;
            # see FFConfig.enable_submesh)
            mesh_axes["data_sub"] = 2
            mesh_axes["data"] //= 2
        self._mesh = make_mesh(mesh_axes, devices)

        if strategy is None and cfg.import_strategy_file:
            # reference --import-strategy (model.cc:3599)
            import json as _json

            from flexflow_tpu.parallel.sharding import view_from_json

            with open(cfg.import_strategy_file) as f:
                strategy = {
                    k: view_from_json(v) for k, v in _json.load(f).items()
                }
            # fail fast on corrupt/stale files with a named-node
            # diagnostic instead of a cryptic lowering error: the fflint
            # consistency pass checks the sharding algebra (degrees
            # divide dims, GQA grouping, no duplicate axes) against THIS
            # graph and mesh
            import os as _os

            from flexflow_tpu.analysis.consistency import check_strategy

            findings = check_strategy(
                self.graph, strategy, mesh_axes,
                subject=_os.path.basename(cfg.import_strategy_file),
            )
            errors = [f for f in findings if f.severity == "error"]
            if errors:
                detail = "\n".join(
                    f"  [{f.code}] {f.where}: {f.message}" for f in errors
                )
                raise ValueError(
                    f"imported strategy file {cfg.import_strategy_file} "
                    f"is inconsistent with this graph/mesh "
                    f"({len(errors)} error(s)):\n{detail}"
                )
            warnings_ = [f for f in findings if f.severity == "warning"]
            if warnings_:
                import logging

                logging.getLogger(__name__).warning(
                    "imported strategy %s: %s",
                    cfg.import_strategy_file,
                    "; ".join(f.message for f in warnings_),
                )
        search_candidates: List = []
        self.search_stats = {}
        if strategy is None and not cfg.only_data_parallel and cfg.search_budget > 0:
            from flexflow_tpu.runtime import distributed as dist

            collect = search_candidates if cfg.validate_top_k > 1 else None
            if cfg.search_budget > 5:
                from flexflow_tpu.search.api import graph_optimize

                # multi-host: only process 0 searches; the rewritten PCG +
                # strategy ship to every host (GraphOptimalViewSerialized,
                # graph.cc:2162) so all processes lower the identical
                # program. The playoff CANDIDATE POOL ships the same way:
                # every host then compiles and times the identical
                # candidate sequence in lockstep, and process 0's ranking
                # picks the winner (VERDICT r2 weakness 7).
                if not dist.is_multi_host():
                    self.graph, strategy = graph_optimize(
                        self.graph, self._mesh, cfg, candidates_out=collect,
                        stats_out=self.search_stats,
                    )
                else:
                    if dist.process_index() == 0:
                        self.graph, strategy = graph_optimize(
                            self.graph, self._mesh, cfg,
                            candidates_out=collect,
                            stats_out=self.search_stats,
                        )
                    self.graph, strategy = dist.broadcast_graph(
                        self.graph, strategy
                    )
                    self.search_stats = dist.broadcast_stats(
                        self.search_stats
                    )
                    if collect is not None:
                        search_candidates[:] = dist.broadcast_candidates(
                            search_candidates
                        )
            else:
                from flexflow_tpu.search.api import search_strategy

                strategy = search_strategy(
                    self.graph, self._mesh, cfg, candidates_out=collect,
                )
                # every process must lower the identical strategy: ship
                # process 0's search result to all (candidate pool too —
                # the playoff must run the same sequence everywhere)
                if dist.is_multi_host():
                    strategy = dist.broadcast_strategy(strategy, self._mesh)
                    if collect is not None:
                        search_candidates[:] = dist.broadcast_candidates(
                            search_candidates
                        )

        # the full modeled pool (top-k + best-per-structural-class + the
        # unrewritten baseline) stays inspectable after compile
        self.searched_candidates = list(search_candidates)
        validated_executor = None
        if len(search_candidates) > 1:
            from flexflow_tpu.search.substitution import structural_class

            # timed playoff pool: top validate_top_k by modeled cost PLUS
            # every retained structural candidate past the cutoff — a
            # structural rewrite's small modeled margin must not exclude it
            # from the empirical playoff (r03 MULTICHIP failure mode)
            picked = list(search_candidates[: cfg.validate_top_k])
            have = {id(g) for _, g, _ in picked}
            for cand in search_candidates[cfg.validate_top_k:]:
                if structural_class(cand[1]) and id(cand[1]) not in have:
                    picked.append(cand)
                    have.add(id(cand[1]))
            self.graph, strategy, validated_executor = self._validate_candidates(
                picked
            )

        # default DP: shard every INPUT's batch dim over "data"; explicit
        # strategy views override per node name
        self._apply_strategy(self.graph, strategy)

        # the winner's executor already compiled its train step during the
        # timed playoff — reuse it (params re-init below, same seed)
        self._executor = validated_executor or self._build_executor(self.graph)
        rng = jax.random.key(cfg.seed)
        self._params = self._executor.init_params(rng, self._init_overrides)
        self._opt_state = self._executor.init_opt_state(
            self._optimizer, self._params[0]
        )

        if cfg.export_strategy_file:
            self.export_strategy_file(cfg.export_strategy_file)
        if cfg.export_strategy_computation_graph_file:
            # reference --compgraph dot export (model.cc:3664); with
            # --include-costs-dot-graph each node is annotated with its
            # modeled per-shard time (model.cc:3660)
            costs = None
            if cfg.include_costs_dot_graph:
                from flexflow_tpu.search.api import _cost_model

                cm = _cost_model(self._mesh, cfg)
                costs = {
                    n.guid: (
                        cm.node_compute_time(self.graph, n, n.sharding)
                        + cm.node_comm_time(self.graph, n, n.sharding)
                    )
                    * 1e3
                    for n in self.graph.nodes
                }
            with open(cfg.export_strategy_computation_graph_file, "w") as f:
                f.write(self.graph.to_dot(costs=costs))
        return self

    def _apply_strategy(self, graph, strategy) -> None:
        """Attach strategy views to nodes; unnamed INPUTs default to
        batch-over-data sharding (over the full data x data_sub group
        when the submesh split is active and the batch divides it)."""
        axis_sizes = dict(
            zip(self._mesh.axis_names, self._mesh.devices.shape)
        )
        data_degree = axis_sizes.get("data", 1)
        for n in graph.nodes:
            if strategy and n.name in strategy:
                n.sharding = strategy[n.name]
            elif n.op_type == OpType.INPUT and (
                    data_degree > 1 or axis_sizes.get("data_sub", 1) > 1):
                from flexflow_tpu.parallel.sharding import group_degree

                shape = n.outputs[0]
                spec = data_batch_spec(shape.ndim, shape.dims[0].size,
                                       axis_sizes)
                deg = group_degree(spec[0], axis_sizes)
                # shard over the widest divisible group (possibly the
                # data_sub-only subset); indivisible stays replicated
                if deg > 1 and shape.dims[0].size % deg == 0:
                    n.sharding = ShardingView((spec,))

    def _build_executor(self, graph) -> Executor:
        cfg = self.config
        return Executor(
            graph,
            self._mesh,
            loss_type=self._loss_type,
            metrics=self._metrics,
            optimizer=self._optimizer,
            seq_length=cfg.seq_length,
            donate=cfg.donate_buffers,
            remat=cfg.remat,
            zero_sharded_opt=cfg.param_sync == ParamSyncType.SHARDED,
        )

    def _playoff_input(self, node):
        """A zeros input for the timed playoff. Single-host: device_put.
        Multi-host: every process must contribute its shard of one GLOBAL
        array (the candidate's step is one SPMD program across hosts) —
        batch-shardable inputs assemble from per-process slices, the rest
        are replicated (zeros are identical everywhere by construction)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from flexflow_tpu.runtime import distributed as dist

        dims = tuple(d.size for d in node.outputs[0].dims)
        dt = node.outputs[0].dtype.jnp_dtype
        if not dist.is_multi_host():
            return jax.device_put(np.zeros(dims, dt))
        nproc = dist.process_count()
        from flexflow_tpu.parallel.sharding import (
            batch_spec,
            spec_to_partition_spec,
        )

        data_deg = dict(zip(self._mesh.axis_names,
                            self._mesh.devices.shape)).get("data", 1)
        if data_deg > 1 and dims[0] % data_deg == 0 and dims[0] % nproc == 0:
            sh = NamedSharding(
                self._mesh, spec_to_partition_spec(batch_spec(len(dims)))
            )
            local = np.zeros((dims[0] // nproc,) + dims[1:], dt)
            return jax.make_array_from_process_local_data(sh, local)
        repl = NamedSharding(self._mesh, PartitionSpec())
        return jax.make_array_from_process_local_data(repl, np.zeros(dims, dt))

    def _validate_candidates(self, candidates):
        """Empirical top-k strategy validation (SURVEY §7 mitigation: 'cost
        the whole step for top-k candidate strategies' — XLA fusion makes
        the op-sum model an imperfect ranking). Compiles each candidate's
        REAL train step on the target mesh, times a few steps on synthetic
        data, and keeps the fastest. Multi-host: every process runs the
        identical candidate sequence in lockstep (the pool was broadcast
        from process 0) and process 0's ranking picks the winner. Records
        the outcome in self.strategy_validation."""
        import time as _time

        import jax

        from flexflow_tpu.runtime import distributed as dist

        results = []  # (timed, modeled_rank, graph, strategy, executor)
        for rank, (modeled, graph, strategy) in enumerate(candidates):
            try:
                # candidates may alias the same Graph object (winner-vs-
                # baseline pairs pass one graph twice); a private copy keeps
                # each candidate's node shardings from leaking into the
                # executors built for the others
                graph = graph.copy()
                self._apply_strategy(graph, strategy)
                ex = self._build_executor(graph)
                rng = jax.random.key(self.config.seed)
                params = ex.init_params(rng, self._init_overrides)
                opt_state = ex.init_opt_state(self._optimizer, params[0])
                step = ex.train_step()
                inputs = [
                    self._playoff_input(n)
                    for n in graph.nodes if n.op_type == OpType.INPUT
                ]
                if dist.is_multi_host():
                    from jax.sharding import NamedSharding, PartitionSpec

                    labels = jax.make_array_from_process_local_data(
                        NamedSharding(self._mesh, PartitionSpec()),
                        self._synth_labels(graph),
                    )
                else:
                    labels = jax.device_put(self._synth_labels(graph))
                tr, ntr = params
                # the step donates (tr, ntr, opt): rebind every call
                tr, ntr, opt_state, m = step(tr, ntr, opt_state, rng,
                                             labels, *inputs)
                float(np.asarray(m["loss"]))  # sync (tunnel-safe)
                t0 = _time.perf_counter()
                for _ in range(3):
                    tr, ntr, opt_state, m = step(tr, ntr, opt_state, rng,
                                                 labels, *inputs)
                float(np.asarray(m["loss"]))
                dt = (_time.perf_counter() - t0) / 3
                results.append((dt, rank, graph, strategy, ex))
            except Exception as e:  # an uncompilable candidate loses, only
                import warnings

                warnings.warn(f"strategy candidate failed validation: {e}")
        if not results:
            _, g, s = candidates[0]
            return g, s, None
        results.sort(key=lambda r: r[0])
        win = results[0]
        if dist.is_multi_host():
            # per-host wall clocks may rank differently by timer noise;
            # every host must adopt THE SAME winner — process 0 decides
            # (the same discipline as broadcast_graph). Failed candidates
            # are deterministic across hosts (identical programs), so the
            # surviving modeled ranks align and broadcasting one suffices.
            # `results` stays in THIS host's time order (the recorded
            # timings must not misrepresent local measurements); only the
            # adopted winner changes.
            win_rank = dist.broadcast_winner_index(win[1])
            win = next((r for r in results if r[1] == win_rank), win)
        self.strategy_validation = {
            "timed_ms": [r[0] * 1e3 for r in results],
            # modeled rank (0 = the model's own pick) per timed entry —
            # honest even when some candidates failed to compile
            "modeled_ranks": [r[1] for r in results],
            "modeled_ms": [candidates[r[1]][0] * 1e3 for r in results],
            "picked_modeled_rank": win[1],
            "picked_timed_index": results.index(win),
            # search-cost observability (wall time, expansions, baseline)
            # so gate records carry regression signals as the corpus grows
            "search": dict(self.search_stats),
        }
        if self.config.profiling:
            timed = ", ".join(f"{r[0]*1e3:.2f}" for r in results)
            print(f"[search] top-{len(results)} validated (ms/step): {timed}")
        return win[2], win[3], win[4]

    def _synth_labels(self, graph):
        """Zero labels for the timed playoff (values never matter). Shaped
        like what fit() passes: the INPUT batch size + the sink's middle
        dims — NOT the sink batch, which AggregateSpec graphs inflate by
        label_repeats (the executor re-repeats labels itself)."""
        sink = [n for n in graph.nodes if not graph.succs(n)][0]
        out = sink.outputs[0]
        first_input = next(n for n in graph.nodes if n.op_type == OpType.INPUT)
        b = first_input.outputs[0].dims[0].size
        dims = (b,) + tuple(d.size for d in out.dims[1:])
        if self._loss_type == LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
            return np.zeros(dims[:-1], np.int32)
        return np.zeros(dims, np.float32)

    @property
    def mesh(self):
        return self._mesh

    @property
    def executor(self) -> Executor:
        if self._executor is None:
            raise RuntimeError("call compile() first")
        return self._executor

    def _batches(self, arrays: List[np.ndarray], batch_size: int):
        """Full batches only; the trailing partial batch is dropped (same as
        the reference dataloader, which sizes steps as n // batch_size)."""
        n = arrays[0].shape[0]
        steps = n // batch_size
        for i in range(steps):
            yield [a[i * batch_size : (i + 1) * batch_size] for a in arrays]

    def _device_put_batch(self, arrs):
        import jax

        from flexflow_tpu.runtime import distributed as dist

        out = []
        multi = dist.is_multi_host()
        for a in arrs:
            sh = self._executor.batch_sharding(a.ndim, a.shape[0])
            if multi:
                # every process passes the same GLOBAL batch; each host
                # device_puts only its slice and the logical global array is
                # assembled across hosts (SingleDataLoader-for-pods analog).
                # device_put with a global sharding would raise on the
                # non-addressable devices, so every multi-host path goes
                # through make_array_from_process_local_data — replicated
                # when the batch doesn't split evenly across processes.
                from jax.sharding import NamedSharding, PartitionSpec

                pc, pi = dist.process_count(), dist.process_index()
                if sh is not None and a.shape[0] % pc == 0:
                    n = a.shape[0] // pc
                    out.append(jax.make_array_from_process_local_data(
                        sh, np.ascontiguousarray(a[pi * n:(pi + 1) * n])
                    ))
                else:
                    repl = NamedSharding(self._mesh, PartitionSpec())
                    out.append(jax.make_array_from_process_local_data(repl, a))
                continue
            out.append(jax.device_put(a, sh) if sh is not None else jax.device_put(a))
        return out

    def export_strategy_file(self, path: str) -> None:
        """Write the compiled strategy as JSON (reference --export-strategy,
        model.cc:3604); also exposed through the C API."""
        import json as _json

        from flexflow_tpu.parallel.sharding import view_to_json

        with open(path, "w") as f:
            _json.dump(
                {
                    n.name: view_to_json(n.sharding)
                    for n in self.graph.nodes
                    if n.sharding is not None
                },
                f,
                indent=1,
            )

    def create_data_loader(self, tensor: Tensor, full_array,
                           batch_size: Optional[int] = None,
                           shuffle: bool = False, seed: int = 0):
        """Reference SingleDataLoader analog (flexflow_cffi.py:2433).
        Pass a numpy array for the in-memory loader, or a .npy file PATH
        for the native mmap + background-gather loader (the reference's
        C++ dataloader analog, native/ffloader.cc)."""
        import os

        if isinstance(full_array, (str, os.PathLike)):
            from flexflow_tpu.runtime.dataloader import FileDataLoader

            return FileDataLoader(self, tensor, os.fspath(full_array),
                                  batch_size=batch_size, shuffle=shuffle,
                                  seed=seed)
        from flexflow_tpu.runtime.dataloader import SingleDataLoader

        return SingleDataLoader(self, tensor, full_array, batch_size=batch_size,
                                shuffle=shuffle, seed=seed)

    def fit(self, x=None, y=None, epochs: Optional[int] = None,
            batch_size: Optional[int] = None, verbose: bool = True,
            dataloaders=None, recompile_state=None):
        """Training loop (reference flexflow_cffi.py:2044: per iteration
        next_batch -> forward -> zero_grads -> backward -> update, wrapped in
        a Legion trace — here one jitted step call). Either pass numpy
        arrays (x, y) or `dataloaders` = [input loaders..., label loader]
        built via create_data_loader (prefetched host->device)."""
        import contextlib

        import jax

        with contextlib.ExitStack() as stack:
            if self.config.profiler_trace_dir:
                # jax profiler capture of the whole fit (xprof/tensorboard
                # viewable — the reference relies on Legion's -lg:prof)
                stack.enter_context(
                    jax.profiler.trace(self.config.profiler_trace_dir)
                )
            if self.config.transfer_guard:
                # surface accidental host<->device transfers in the loop
                stack.enter_context(
                    jax.transfer_guard(self.config.transfer_guard)
                )
            return self._fit_impl(x, y, epochs, batch_size, verbose,
                                  dataloaders, recompile_state)

    def _fit_impl(self, x, y, epochs, batch_size, verbose, dataloaders,
                  recompile_state):
        import jax

        from flexflow_tpu.runtime.dataloader import PrefetchLoader

        epochs = epochs or self.config.epochs
        explicit_bs = batch_size
        batch_size = batch_size or self.config.batch_size
        step = self.executor.train_step()
        tr, ntr = self._params
        opt_state = self._opt_state
        # fold the fit-call counter in so repeated fit() calls (e.g. the
        # keras per-epoch loop) draw FRESH dropout/rng streams instead of
        # replaying the first call's masks
        with jax.transfer_guard("allow"):  # seed upload is deliberate
            rng = jax.random.key(self._rng_seed + 1 + self._fit_calls)
        self._fit_calls += 1
        for epoch in range(epochs):
            self.current_metrics = PerfMetrics()
            if dataloaders is not None:
                if explicit_bs is not None:
                    for dl in dataloaders:
                        dl.batch_size = explicit_bs
                batches = iter(PrefetchLoader(self, dataloaders))
            else:
                xs = [x] if isinstance(x, np.ndarray) else list(x)
                batches = (
                    self._device_put_batch(b)
                    for b in self._batches(xs + [y], batch_size)
                )
            # metrics accumulate ON DEVICE across the epoch (reference
            # PerfMetrics future-reduction discipline); one host sync at
            # epoch end — per-step float() would block async dispatch and
            # serialize the step stream
            dev_sums = None
            n_samples = 0
            for batch in batches:
                *bx, by = batch
                rng, sub = jax.random.split(rng)
                tr, ntr, opt_state, m = step(tr, ntr, opt_state, sub, by, *bx)
                self._step_count += 1
                bsz = by.shape[0]
                n_samples += bsz
                # scaling by the python batch-size constant implicitly
                # uploads a scalar — deliberate, so exempt from a
                # configured transfer guard (which hunts DATA transfers)
                with jax.transfer_guard("allow"):
                    scaled = {
                        k: (v if k == "accuracy_correct" else v * bsz)
                        for k, v in m.items()
                        if k != "loss"
                    }
                    dev_sums = (
                        scaled
                        if dev_sums is None
                        else jax.tree.map(lambda a, b: a + b, dev_sums, scaled)
                    )
                if recompile_state is not None:
                    # reference recompile_on_condition (model.cc:2422);
                    # trigger functions read device metrics — a deliberate
                    # sync, exempt from a configured transfer guard
                    from flexflow_tpu.runtime.recompile import (
                        recompile_on_condition,
                    )

                    recompile_state.last_metrics = m
                    self._params = (tr, ntr)
                    self._opt_state = opt_state
                    with jax.transfer_guard("allow"):
                        recompiled = recompile_on_condition(
                            self, recompile_state
                        )
                    if recompiled:
                        step = self.executor.train_step()
                        tr, ntr = self._params
                        opt_state = self._opt_state
                if (
                    self.config.checkpoint_every
                    and self.config.checkpoint_dir
                    and self._step_count % self.config.checkpoint_every == 0
                ):
                    from flexflow_tpu.runtime.checkpoint import periodic_save

                    self._params = (tr, ntr)
                    self._opt_state = opt_state
                    # checkpoint writes gather state to host by design
                    with jax.transfer_guard("allow"):
                        periodic_save(self.config.checkpoint_dir, self)
            self.current_metrics.train_all = n_samples
            if dev_sums is not None:
                # the ONE deliberate device->host sync per epoch — exempt
                # from a configured transfer guard (which exists to catch
                # transfers inside the step loop, not this one)
                with jax.transfer_guard("allow"):
                    host = {k: float(v) for k, v in dev_sums.items()}
                self.current_metrics.train_correct = int(
                    round(host.get("accuracy_correct", 0.0))
                )
                for k in (
                    "cce_loss", "sparse_cce_loss", "mse_loss", "rmse_loss",
                    "mae_loss",
                ):
                    if k in host:
                        setattr(self.current_metrics, k, host[k])
            if verbose:
                print(f"epoch {epoch}: {self.current_metrics.report(self._metrics)}")
        self._params = (tr, ntr)
        self._opt_state = opt_state
        return self.current_metrics

    def eval(self, x: Union[np.ndarray, Sequence[np.ndarray]], y: np.ndarray,
             batch_size: Optional[int] = None, verbose: bool = True):
        xs = [x] if isinstance(x, np.ndarray) else list(x)
        batch_size = batch_size or self.config.batch_size
        step = self.executor.eval_step()
        tr, ntr = self._params
        pm = PerfMetrics()
        for batch in self._batches(xs + [y], batch_size):
            *bx, by = self._device_put_batch(batch)
            m = step(tr, ntr, by, *bx)
            pm.update({k: float(v) for k, v in m.items() if k != "loss"}, batch_size)
        if verbose:
            print(f"eval: {pm.report(self._metrics)}")
        return pm

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0) -> np.ndarray:
        """Autoregressive generation with a KV cache (net-new vs the
        reference, which has no decode path): one prefill pass writes the
        prompt's K/V into per-layer caches, then single-token steps extend
        them. temperature=0 is greedy; >0 samples. Returns
        [batch, max_new_tokens] int32 tokens."""
        import jax
        import jax.numpy as jnp

        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        ex = self.executor
        prompt_ids = np.asarray(prompt_ids, np.int32)
        b, s = prompt_ids.shape
        # learned-position models: decode must not run past the position
        # table (the in-jit slice would silently clamp to the last row)
        rows = self.position_table_rows()
        if rows is not None and s + max_new_tokens > rows:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the learned position table ({rows} rows); "
                "rebuild the model with a longer seq_len")
        if s < 1:
            raise ValueError("prompt must contain at least one token")
        caches = ex.init_kv_cache(b, s + max_new_tokens)
        step = ex.decode_fn()
        tr, ntr = self._params
        rng = jax.random.key(seed)

        def pick(probs, rng):
            # sink softmax already normalized; sample or argmax the LAST
            # position
            p = probs[:, -1, :]
            if temperature <= 0.0:
                return jnp.argmax(p, axis=-1).astype(jnp.int32)
            logits = jnp.log(jnp.maximum(p, 1e-30)) / temperature
            return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)

        probs, caches = step(tr, ntr, caches, 0, jnp.asarray(prompt_ids))
        rng, sub = jax.random.split(rng)
        tok = pick(probs, sub)
        out = [tok]
        pos = s
        for _ in range(max_new_tokens - 1):
            probs, caches = step(tr, ntr, caches, pos, tok[:, None])
            rng, sub = jax.random.split(rng)
            tok = pick(probs, sub)
            out.append(tok)
            pos += 1
        return np.stack([np.asarray(t) for t in out], axis=1)

    def serve(self, batch_sizes=(1, 8), max_delay_ms: float = 2.0,
              warmup: bool = True):
        """Start a serving endpoint over this compiled model (the
        reference triton/ backend analog — flexflow_tpu.serving)."""
        from flexflow_tpu.serving import serve as _serve

        return _serve(self, batch_sizes=batch_sizes, max_delay_ms=max_delay_ms,
                      warmup=warmup)

    def serve_generation(self, slots: int = 4, max_len: int = 512,
                         eos_id=None, seed: int = 0, paged: bool = False,
                         page_size: int = 64, num_pages=None,
                         preemption: bool = True, prefix_cache: bool = True,
                         prefill_chunk: int = 64, speculate=None,
                         ragged_pack: bool = True, megastep_ticks: int = 1,
                         megastep_mixed: bool = False,
                         overlap_dispatch: bool = False,
                         kv_dtype: str = "auto",
                         request_record_limit=None, serve_strategy=None,
                         search_budget=None, traffic="smoke",
                         reqlog_capacity=None, slo=None, slo_dump_dir=None,
                         kv_quant_canary=None, defer_start: bool = False,
                         host_tier=None):
        """Continuous-batching autoregressive generation endpoint (KV-cache
        decode with per-slot positions — flexflow_tpu.serving). With
        `paged=True` the KV cache is a block-paged pool shared by all
        requests (flexflow_tpu.paged): HBM scales with tokens in flight,
        admission is by free-page budget, and page pressure preempts and
        requeues the youngest request; `prefix_cache` shares
        content-addressed prompt-prefix pages across requests and
        `prefill_chunk` bounds the prompt tokens prefilled per decode
        tick (chunked prefill — long prompts never stall in-flight
        decodes). `speculate=SpecConfig(...)` (with paged=True) adds
        speculative tree decoding (flexflow_tpu.spec): drafted token
        trees verified in one step, greedy output token-identical, up to
        depth+1 tokens emitted per step. `megastep_ticks=N` (paged, no
        speculate) fuses up to N decode ticks into one jitted dispatch
        with zero host syncs in the inner loop — token output stays
        identical (docs/paged.md "Decode megasteps");
        `megastep_mixed=True` makes the megastep UNIVERSAL — mid-prefill
        chunks and on-device drafted spec chains fuse into the same
        dispatch — and `overlap_dispatch=True` runs the next tick's
        admission work in the shadow of the in-flight dispatch
        (docs/paged.md "Universal megasteps").
        `search_budget=N` auto-tunes the paged/spec/megastep knobs with
        the serving-strategy search against the `traffic` profile before
        serving; `serve_strategy` applies a previously searched
        ServeStrategy (or its JSON dict) directly (docs/search.md,
        "Serving strategy search"). `kv_dtype="int8"` (paged only)
        stores KV pages quantized with per-page per-head scales —
        ~4x more tokens per byte of pool HBM at a bounded logit
        tolerance (docs/paged.md "Quantized KV pages").
        `reqlog_capacity` sizes the always-on request-log flight
        recorder (0 disables), `slo=SLOTarget(...)` arms the live SLO
        monitor with breach dumps under `slo_dump_dir`, and
        `kv_quant_canary=N` samples the fp32 quantization-error shadow
        onto every Nth request (docs/observability.md).
        `defer_start=True` builds the server without starting its loop —
        the drain-and-swap handoff warms shapes, adopts the predecessor's
        pool and absorbs its carried requests before calling .start()
        (docs/serving.md, "Autopilot & drain-and-swap").
        `host_tier=HostTier(...)` (or a page count, paged only) backs
        the pool with a host-RAM KV spill tier: LRU evictions spill
        instead of dropping and later lookups fetch pages back
        (docs/disaggregation.md)."""
        from flexflow_tpu.serving import serve_generation as _sg

        return _sg(self, slots=slots, max_len=max_len, eos_id=eos_id,
                   seed=seed, paged=paged, page_size=page_size,
                   num_pages=num_pages, preemption=preemption,
                   prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
                   speculate=speculate, ragged_pack=ragged_pack,
                   megastep_ticks=megastep_ticks,
                   megastep_mixed=megastep_mixed,
                   overlap_dispatch=overlap_dispatch, kv_dtype=kv_dtype,
                   request_record_limit=request_record_limit,
                   serve_strategy=serve_strategy,
                   search_budget=search_budget, traffic=traffic,
                   reqlog_capacity=reqlog_capacity, slo=slo,
                   slo_dump_dir=slo_dump_dir,
                   kv_quant_canary=kv_quant_canary,
                   defer_start=defer_start, host_tier=host_tier)

    def predict(self, x: Union[np.ndarray, Sequence[np.ndarray]],
                batch_size: Optional[int] = None) -> np.ndarray:
        xs = [x] if isinstance(x, np.ndarray) else list(x)
        batch_size = batch_size or self.config.batch_size
        fwd = self.executor.forward_fn()
        tr, ntr = self._params
        n = xs[0].shape[0]
        # pad to a whole number of batches so every row gets a prediction
        # (unlike fit/eval, predict must not drop the remainder)
        pad = (-n) % batch_size
        if pad:
            xs = [np.concatenate([a, np.repeat(a[-1:], pad, axis=0)]) for a in xs]
        outs = []
        for batch in self._batches(xs, batch_size):
            bx = self._device_put_batch(batch)
            outs.append(np.asarray(fwd(tr, ntr, *bx)))
        return np.concatenate(outs, axis=0)[:n]

    # ---- weight access (reference ParallelTensor::set_tensor/get_tensor) ----

    def get_weight(self, tensor_or_name: Union[Tensor, str], weight_name: str = "kernel") -> np.ndarray:
        key = self._resolve_param_key(tensor_or_name)
        tr, ntr = self._params
        src = tr if key in tr and weight_name in tr.get(key, {}) else ntr
        return np.asarray(src[key][weight_name])

    def position_table_rows(self) -> Optional[int]:
        """Smallest learned-position table in the graph (rows), or None.
        Every decode entry point (generate, GenerationServer) must keep
        prompt+new tokens within it — the in-jit row slice clamps rather
        than faults."""
        rows = None
        for n in self.graph.nodes:
            if getattr(n.attrs, "position_table", False):
                ins = self.graph.input_shapes(n)
                if len(ins) > 1:
                    r = ins[1].dims[0].size
                    rows = r if rows is None else min(rows, r)
        return rows

    def set_weight(self, tensor_or_name: Union[Tensor, str], value: np.ndarray,
                   weight_name: str = "kernel"):
        import jax

        key = self._resolve_param_key(tensor_or_name)
        tr, ntr = self._params
        target = tr if key in tr and weight_name in tr.get(key, {}) else ntr
        old = target[key][weight_name]
        target[key][weight_name] = jax.device_put(
            value.astype(old.dtype), old.sharding
        )

    def _resolve_param_key(self, tensor_or_name) -> str:
        if isinstance(tensor_or_name, Tensor):
            return node_key(tensor_or_name.node)
        for n in self.graph.nodes:
            if n.name == tensor_or_name:
                return node_key(n)
        raise KeyError(tensor_or_name)

    def to_dot(self) -> str:
        return self.graph.to_dot()
