"""Model zoo: builders for the BASELINE target configs.

Reference analog: examples/cpp + examples/python (SURVEY.md §2.8) — each
builder constructs the model through the FFModel layer API exactly like the
reference examples do, and (TPU-native addition) can also return a manual
tensor/expert-parallel strategy as node-name -> ShardingView, playing the
role of the reference's strategy files.
"""

from flexflow_tpu.models.mlp import build_mlp
from flexflow_tpu.models.alexnet import build_alexnet
from flexflow_tpu.models.resnet import build_resnet50
from flexflow_tpu.models.bert import BertConfig, build_bert
from flexflow_tpu.models.llama import LlamaConfig, build_llama, llama_tp_strategy
from flexflow_tpu.models.mixtral import MixtralConfig, build_mixtral
from flexflow_tpu.models.dlrm import build_dlrm
from flexflow_tpu.models.inception import build_inception_v3
from flexflow_tpu.models.resnext import build_resnext50
from flexflow_tpu.models.candle_uno import build_candle_uno
from flexflow_tpu.models.nmt import NMTConfig, build_nmt, nmt_dp_strategy
from flexflow_tpu.models.transformer import (
    TransformerConfig,
    build_transformer_encoder,
    build_transformer_encoder_decoder,
)
from flexflow_tpu.models.xdl import build_xdl

__all__ = [
    "build_mlp",
    "build_alexnet",
    "build_resnet50",
    "BertConfig",
    "build_bert",
    "LlamaConfig",
    "build_llama",
    "llama_tp_strategy",
    "MixtralConfig",
    "build_mixtral",
    "build_dlrm",
    "build_inception_v3",
    "build_resnext50",
    "build_candle_uno",
    "NMTConfig",
    "build_nmt",
    "nmt_dp_strategy",
    "build_xdl",
    "TransformerConfig",
    "build_transformer_encoder",
    "build_transformer_encoder_decoder",
]
