"""AlexNet for CIFAR-10 (reference bootcamp_demo/ff_alexnet_cifar10.py,
examples/cpp/AlexNet/alexnet.cc): 32x32x3 NCHW input, 10 classes."""

from __future__ import annotations

from flexflow_tpu.ffconst import ActiMode, DataType, PoolType
from flexflow_tpu.model import FFModel, Tensor


def build_alexnet(ff: FFModel, batch_size: int = None, classes: int = 10) -> Tensor:
    b = batch_size or ff.config.batch_size
    t = ff.create_tensor((b, 3, 229, 229), DataType.FLOAT, name="input")
    t = ff.conv2d(t, 64, 11, 11, 4, 4, 2, 2, ActiMode.RELU, name="conv1")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.RELU, name="conv2")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool2")
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.RELU, name="conv3")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.RELU, name="conv4")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.RELU, name="conv5")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool3")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 4096, ActiMode.RELU, name="fc6")
    t = ff.dense(t, 4096, ActiMode.RELU, name="fc7")
    t = ff.dense(t, classes, name="fc8")
    return ff.softmax(t, name="softmax")


def build_alexnet_cifar10(ff: FFModel, batch_size: int = None) -> Tensor:
    """CIFAR-10-sized variant (32x32 inputs, the bootcamp demo's data)."""
    b = batch_size or ff.config.batch_size
    t = ff.create_tensor((b, 3, 32, 32), DataType.FLOAT, name="input")
    t = ff.conv2d(t, 64, 5, 5, 1, 1, 2, 2, ActiMode.RELU, name="conv1")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool1")
    t = ff.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.RELU, name="conv2")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool2")
    t = ff.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.RELU, name="conv3")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.RELU, name="conv4")
    t = ff.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.RELU, name="conv5")
    t = ff.pool2d(t, 3, 3, 2, 2, name="pool3")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 1024, ActiMode.RELU, name="fc6")
    t = ff.dense(t, 1024, ActiMode.RELU, name="fc7")
    t = ff.dense(t, 10, name="fc8")
    return ff.softmax(t, name="softmax")
