"""BERT-base encoder builder (reference examples/python/native/
bert_proxy_native.py, examples/cpp/Transformer/transformer.cc:23-60).

The attribute-parallel strategy (attention heads over the `model` axis —
BASELINE config 3) is returned by `bert_attribute_parallel_strategy`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel, Tensor
from flexflow_tpu.parallel.sharding import ShardingView


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    intermediate: int = 3072
    max_seq: int = 512
    num_classes: int = 2  # sequence-classification head
    dropout: float = 0.1


def build_bert(ff: FFModel, cfg: BertConfig, batch_size: int = None,
               seq_len: int = 128, dtype: DataType = DataType.FLOAT) -> Tensor:
    b = batch_size or ff.config.batch_size
    ids = ff.create_tensor((b, seq_len), DataType.INT32, name="input_ids")
    h = ff.embedding(ids, cfg.vocab_size, cfg.hidden, dtype=dtype, name="tok_emb")
    # learned positional embedding via a standalone weight broadcast-added
    pos = ff.create_weight((seq_len, cfg.hidden), dtype, name="pos_emb")
    h = ff.add_position_embedding(h, pos, name="add_pos")
    h = ff.layer_norm(h, name="emb_ln")
    for i in range(cfg.layers):
        a = ff.multihead_attention(
            h, h, h, cfg.hidden, cfg.heads, dropout=cfg.dropout, bias=True,
            name=f"l{i}_attn",
        )
        h = ff.layer_norm(ff.add(h, a, name=f"l{i}_res1"), name=f"l{i}_ln1")
        m = ff.dense(h, cfg.intermediate, ActiMode.GELU, name=f"l{i}_ff1")
        m = ff.dense(m, cfg.hidden, name=f"l{i}_ff2")
        h = ff.layer_norm(ff.add(h, m, name=f"l{i}_res2"), name=f"l{i}_ln2")
    # CLS-token classification head (proxy task like the reference's example)
    cls = ff.split(h, [1, seq_len - 1], axis=1, name="cls_split")[0]
    cls = ff.reshape(cls, (b, cfg.hidden), name="cls_flat")
    logits = ff.dense(cls, cfg.num_classes, name="cls_head")
    return ff.softmax(logits, name="softmax")


def bert_attribute_parallel_strategy(cfg: BertConfig) -> Dict[str, ShardingView]:
    """Attention heads sharded over the `model` mesh axis (the reference's
    attribute parallelism, attention.cc head-parallel machine views) +
    Megatron column/row split of the FFN."""
    views: Dict[str, ShardingView] = {}
    for i in range(cfg.layers):
        views[f"l{i}_attn"] = ShardingView(
            output_specs=(None,),
            weight_specs={
                "wq": ((), ("model",), ()),
                "wk": ((), ("model",), ()),
                "wv": ((), ("model",), ()),
                "wo": (("model",), (), ()),
                "bq": (("model",), ()),
                "bk": (("model",), ()),
                "bv": (("model",), ()),
                "bo": ((),),
            },
        )
        views[f"l{i}_ff1"] = ShardingView(
            output_specs=(None,),
            weight_specs={"kernel": ((), ("model",)), "bias": (("model",),)},
        )
        views[f"l{i}_ff2"] = ShardingView(
            output_specs=(None,),
            weight_specs={"kernel": (("model",), ()), "bias": ((),)},
        )
    return views
