"""CANDLE-UNO builder (reference examples/cpp/candle_uno/candle_uno.cc):
the cancer drug-response model — per-feature-set encoder towers whose
outputs concat into a deep regression head. Pure dense: the search's
sample/parameter-parallel playground in the reference's AE scripts."""

from __future__ import annotations

from typing import Dict, Sequence

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel, Tensor


def _tower(ff: FFModel, t: Tensor, dims: Sequence[int], name: str) -> Tensor:
    for i, d in enumerate(dims):
        t = ff.dense(t, d, ActiMode.RELU, name=f"{name}{i}")
    return t


def build_candle_uno(
    ff: FFModel,
    batch_size: int = None,
    feature_dims: Dict[str, int] = None,
    tower_dims: Sequence[int] = (1000, 1000, 1000),
    head_dims: Sequence[int] = (1000, 1000, 1000, 1000, 1000),
) -> Tensor:
    """Three encoder towers (gene expression + two drug descriptor sets by
    default, matching the reference's feature sets), concatenated with the
    raw dose input into the dense head; scalar growth prediction (MSE)."""
    b = batch_size or ff.config.batch_size
    feature_dims = feature_dims or {"gene": 942, "drug1": 3820, "drug2": 3820}
    parts = []
    dose = ff.create_tensor((b, 1), DataType.FLOAT, name="dose_input")
    parts.append(dose)
    for fname, fdim in feature_dims.items():
        x = ff.create_tensor((b, fdim), DataType.FLOAT, name=f"{fname}_input")
        parts.append(_tower(ff, x, tower_dims, f"{fname}_t"))
    t = ff.concat(parts, axis=1, name="feature_cat")
    for i, d in enumerate(head_dims):
        t = ff.dense(t, d, ActiMode.RELU, name=f"head{i}")
    return ff.dense(t, 1, name="growth")
