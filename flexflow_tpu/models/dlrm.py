"""DLRM builder (reference examples/cpp/DLRM/dlrm.cc): sparse embedding
bags + bottom/top MLPs with pairwise-interaction-style concat."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.ffconst import ActiMode, AggrMode, DataType
from flexflow_tpu.model import FFModel, Tensor


def _mlp(ff: FFModel, t: Tensor, dims: Sequence[int], name: str,
         final_act: ActiMode = ActiMode.RELU) -> Tensor:
    for i, d in enumerate(dims):
        act = final_act if i == len(dims) - 1 else ActiMode.RELU
        t = ff.dense(t, d, act, name=f"{name}{i}")
    return t


def build_dlrm(ff: FFModel, num_sparse: int = 8, vocab: int = 1000000,
               embed_dim: int = 64, dense_dim: int = 13,
               bag_size: int = 1,
               bot_mlp: Sequence[int] = (512, 256, 64),
               top_mlp: Sequence[int] = (512, 256, 1),
               batch_size: int = None) -> Tensor:
    """Embedding-heavy recommender (sigmoid CTR output; trained with MSE
    like the reference example)."""
    b = batch_size or ff.config.batch_size
    dense_in = ff.create_tensor((b, dense_dim), DataType.FLOAT, name="dense_input")
    x = _mlp(ff, dense_in, list(bot_mlp)[:-1] + [embed_dim], "bot")
    feats = [x]
    for i in range(num_sparse):
        ids = ff.create_tensor((b, bag_size), DataType.INT32, name=f"sparse{i}")
        e = ff.embedding(ids, vocab, embed_dim, AggrMode.SUM, name=f"emb{i}")
        feats.append(e)
    t = ff.concat(feats, axis=1, name="interact")
    t = _mlp(ff, t, list(top_mlp), "top", final_act=ActiMode.SIGMOID)
    return t
