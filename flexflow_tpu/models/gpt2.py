"""GPT-2 family builder: pre-LayerNorm decoder with learned positions.

Reference analog: the transformer/BERT example builders
(examples/cpp/Transformer/transformer.cc:34-45) — this variant matches
the HuggingFace GPT-2 architecture exactly so frontends/hf.py can map a
pretrained checkpoint onto it weight for weight (Conv1D [in,out] layouts,
fused c_attn split into per-head q/k/v, tanh-approximate GELU, tied
lm_head handled by the importer).
"""

from __future__ import annotations

import dataclasses

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel, Tensor


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    dim: int = 768
    layers: int = 12
    heads: int = 12
    inner: int = 0  # 0 -> 4*dim
    ln_eps: float = 1e-5

    @property
    def intermediate(self) -> int:
        return self.inner or 4 * self.dim

    @staticmethod
    def tiny(vocab: int = 256) -> "GPT2Config":
        return GPT2Config(vocab_size=vocab, dim=64, layers=2, heads=4)


def build_gpt2(ff: FFModel, cfg: GPT2Config, batch_size: int = None,
               seq_len: int = 128,
               dtype: DataType = DataType.FLOAT) -> Tensor:
    b = batch_size or ff.config.batch_size
    ids = ff.create_tensor((b, seq_len), DataType.INT32, name="input_ids")
    h = ff.embedding(ids, cfg.vocab_size, cfg.dim, dtype=dtype, name="wte")
    pos = ff.create_weight((seq_len, cfg.dim), dtype, name="wpe")
    h = ff.add_position_embedding(h, pos, name="add_pos")
    for i in range(cfg.layers):
        a = ff.layer_norm(h, eps=cfg.ln_eps, name=f"h{i}_ln1")
        a = ff.multihead_attention(a, a, a, cfg.dim, cfg.heads, bias=True,
                                   causal=True, name=f"h{i}_attn")
        h = ff.add(h, a, name=f"h{i}_res1")
        m = ff.layer_norm(h, eps=cfg.ln_eps, name=f"h{i}_ln2")
        m = ff.dense(m, cfg.intermediate, ActiMode.GELU, name=f"h{i}_fc")
        m = ff.dense(m, cfg.dim, name=f"h{i}_proj")
        h = ff.add(h, m, name=f"h{i}_res2")
    h = ff.layer_norm(h, eps=cfg.ln_eps, name="ln_f")
    logits = ff.dense(h, cfg.vocab_size, use_bias=False, name="lm_head")
    return ff.softmax(logits, name="softmax")
