"""InceptionV3 builder (reference examples/cpp/InceptionV3/inception.cc):
the multi-branch inception blocks whose Concat fan-ins are exactly the
substitution targets the Unity search rewrites (the reference ships
inception-specific concat xfers, substitution.cc:1726-1868). NCHW."""

from __future__ import annotations

from flexflow_tpu.ffconst import DataType, PoolType
from flexflow_tpu.model import FFModel, Tensor


def _conv_bn(ff: FFModel, t: Tensor, ch: int, kh: int, kw: int,
             sh: int = 1, sw: int = 1, ph: int = 0, pw: int = 0,
             name: str = "") -> Tensor:
    t = ff.conv2d(t, ch, kh, kw, sh, sw, ph, pw, use_bias=False,
                  name=f"{name}_conv")
    return ff.batch_norm(t, relu=True, name=f"{name}_bn")


def _inception_a(ff, t, pool_ch, name):
    b1 = _conv_bn(ff, t, 64, 1, 1, name=f"{name}_b1")
    b2 = _conv_bn(ff, t, 48, 1, 1, name=f"{name}_b2a")
    b2 = _conv_bn(ff, b2, 64, 5, 5, 1, 1, 2, 2, name=f"{name}_b2b")
    b3 = _conv_bn(ff, t, 64, 1, 1, name=f"{name}_b3a")
    b3 = _conv_bn(ff, b3, 96, 3, 3, 1, 1, 1, 1, name=f"{name}_b3b")
    b3 = _conv_bn(ff, b3, 96, 3, 3, 1, 1, 1, 1, name=f"{name}_b3c")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.AVG, name=f"{name}_pool")
    b4 = _conv_bn(ff, b4, pool_ch, 1, 1, name=f"{name}_b4")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def _inception_b(ff, t, name):
    b1 = _conv_bn(ff, t, 384, 3, 3, 2, 2, name=f"{name}_b1")
    b2 = _conv_bn(ff, t, 64, 1, 1, name=f"{name}_b2a")
    b2 = _conv_bn(ff, b2, 96, 3, 3, 1, 1, 1, 1, name=f"{name}_b2b")
    b2 = _conv_bn(ff, b2, 96, 3, 3, 2, 2, name=f"{name}_b2c")
    b3 = ff.pool2d(t, 3, 3, 2, 2, name=f"{name}_pool")
    return ff.concat([b1, b2, b3], axis=1, name=f"{name}_cat")


def _inception_c(ff, t, ch7, name):
    b1 = _conv_bn(ff, t, 192, 1, 1, name=f"{name}_b1")
    b2 = _conv_bn(ff, t, ch7, 1, 1, name=f"{name}_b2a")
    b2 = _conv_bn(ff, b2, ch7, 1, 7, 1, 1, 0, 3, name=f"{name}_b2b")
    b2 = _conv_bn(ff, b2, 192, 7, 1, 1, 1, 3, 0, name=f"{name}_b2c")
    b3 = _conv_bn(ff, t, ch7, 1, 1, name=f"{name}_b3a")
    b3 = _conv_bn(ff, b3, ch7, 7, 1, 1, 1, 3, 0, name=f"{name}_b3b")
    b3 = _conv_bn(ff, b3, ch7, 1, 7, 1, 1, 0, 3, name=f"{name}_b3c")
    b3 = _conv_bn(ff, b3, ch7, 7, 1, 1, 1, 3, 0, name=f"{name}_b3d")
    b3 = _conv_bn(ff, b3, 192, 1, 7, 1, 1, 0, 3, name=f"{name}_b3e")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.AVG, name=f"{name}_pool")
    b4 = _conv_bn(ff, b4, 192, 1, 1, name=f"{name}_b4")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def _inception_d(ff, t, name):
    b1 = _conv_bn(ff, t, 192, 1, 1, name=f"{name}_b1a")
    b1 = _conv_bn(ff, b1, 320, 3, 3, 2, 2, name=f"{name}_b1b")
    b2 = _conv_bn(ff, t, 192, 1, 1, name=f"{name}_b2a")
    b2 = _conv_bn(ff, b2, 192, 1, 7, 1, 1, 0, 3, name=f"{name}_b2b")
    b2 = _conv_bn(ff, b2, 192, 7, 1, 1, 1, 3, 0, name=f"{name}_b2c")
    b2 = _conv_bn(ff, b2, 192, 3, 3, 2, 2, name=f"{name}_b2d")
    b3 = ff.pool2d(t, 3, 3, 2, 2, name=f"{name}_pool")
    return ff.concat([b1, b2, b3], axis=1, name=f"{name}_cat")


def _inception_e(ff, t, name):
    b1 = _conv_bn(ff, t, 320, 1, 1, name=f"{name}_b1")
    b2 = _conv_bn(ff, t, 384, 1, 1, name=f"{name}_b2a")
    b2x = _conv_bn(ff, b2, 384, 1, 3, 1, 1, 0, 1, name=f"{name}_b2b")
    b2y = _conv_bn(ff, b2, 384, 3, 1, 1, 1, 1, 0, name=f"{name}_b2c")
    b2 = ff.concat([b2x, b2y], axis=1, name=f"{name}_cat2")
    b3 = _conv_bn(ff, t, 448, 1, 1, name=f"{name}_b3a")
    b3 = _conv_bn(ff, b3, 384, 3, 3, 1, 1, 1, 1, name=f"{name}_b3b")
    b3x = _conv_bn(ff, b3, 384, 1, 3, 1, 1, 0, 1, name=f"{name}_b3c")
    b3y = _conv_bn(ff, b3, 384, 3, 1, 1, 1, 1, 0, name=f"{name}_b3d")
    b3 = ff.concat([b3x, b3y], axis=1, name=f"{name}_cat3")
    b4 = ff.pool2d(t, 3, 3, 1, 1, 1, 1, PoolType.AVG, name=f"{name}_pool")
    b4 = _conv_bn(ff, b4, 192, 1, 1, name=f"{name}_b4")
    return ff.concat([b1, b2, b3, b4], axis=1, name=f"{name}_cat")


def build_inception_v3(ff: FFModel, batch_size: int = None,
                       classes: int = 1000, image_size: int = 299) -> Tensor:
    b = batch_size or ff.config.batch_size
    t = ff.create_tensor((b, 3, image_size, image_size), DataType.FLOAT,
                         name="input")
    t = _conv_bn(ff, t, 32, 3, 3, 2, 2, name="stem1")
    t = _conv_bn(ff, t, 32, 3, 3, name="stem2")
    t = _conv_bn(ff, t, 64, 3, 3, 1, 1, 1, 1, name="stem3")
    t = ff.pool2d(t, 3, 3, 2, 2, name="stem_pool1")
    t = _conv_bn(ff, t, 80, 1, 1, name="stem4")
    t = _conv_bn(ff, t, 192, 3, 3, name="stem5")
    t = ff.pool2d(t, 3, 3, 2, 2, name="stem_pool2")
    t = _inception_a(ff, t, 32, "a1")
    t = _inception_a(ff, t, 64, "a2")
    t = _inception_a(ff, t, 64, "a3")
    t = _inception_b(ff, t, "b1")
    t = _inception_c(ff, t, 128, "c1")
    t = _inception_c(ff, t, 160, "c2")
    t = _inception_c(ff, t, 160, "c3")
    t = _inception_c(ff, t, 192, "c4")
    t = _inception_d(ff, t, "d1")
    t = _inception_e(ff, t, "e1")
    t = _inception_e(ff, t, "e2")
    t = ff.mean(t, axes=(2, 3), name="gap")
    t = ff.dense(t, classes, name="fc")
    return ff.softmax(t, name="softmax")
