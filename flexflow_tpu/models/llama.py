"""Llama-family decoder builder — the flagship model (BASELINE config 4:
Llama-3-8B hybrid TP+DP).

Built through the FFModel layer API: RMSNorm, GQA attention with RoPE,
SwiGLU MLP. `llama_tp_strategy` returns the Megatron-style hybrid TP+DP
sharding (the strategy the Unity-style search should discover); with
`use_ring_attention=True` the attention ops become sequence-parallel ring
attention (net-new vs the reference, SURVEY.md §5.7).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel, Tensor
from flexflow_tpu.parallel.sharding import ShardingView


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    layers: int = 32
    heads: int = 32
    kv_heads: int = 8
    hidden: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab: int = 512) -> "LlamaConfig":
        """Test-sized config (multi-chip dryruns, CPU tests)."""
        return LlamaConfig(vocab_size=vocab, dim=64, layers=2, heads=4,
                           kv_heads=2, hidden=128, rope_theta=10000.0)

    @staticmethod
    def bench_1b() -> "LlamaConfig":
        """~1.2B-param config that fits one v5e chip with Adam state."""
        return LlamaConfig(vocab_size=32000, dim=2048, layers=16, heads=16,
                           kv_heads=8, hidden=5632)


def build_llama(ff: FFModel, cfg: LlamaConfig, batch_size: int = None,
                seq_len: int = 2048, dtype: DataType = DataType.BFLOAT16,
                use_ring_attention: bool = False,
                seq_mode: str = "ring",
                use_pipeline: bool = False,
                n_microbatches: int = 4) -> Tensor:
    b = batch_size or ff.config.batch_size
    ids = ff.create_tensor((b, seq_len), DataType.INT32, name="input_ids")
    h = ff.embedding(ids, cfg.vocab_size, cfg.dim, dtype=dtype, name="tok_emb")
    if use_pipeline:
        # all decoder blocks as ONE stacked-weight composite: GPipe stages
        # over the `pipe` mesh axis, or a layer-stacked scan without one
        h = ff.pipeline(h, cfg.layers, cfg.heads, cfg.kv_heads, cfg.hidden,
                        n_microbatches=n_microbatches,
                        rope_theta=cfg.rope_theta, norm_eps=cfg.norm_eps,
                        name="decoder_pipeline")
        h = ff.rms_norm(h, eps=cfg.norm_eps, name="final_norm")
        logits = ff.dense(h, cfg.vocab_size, use_bias=False, name="lm_head")
        return ff.softmax(logits, name="softmax")
    for i in range(cfg.layers):
        a = ff.rms_norm(h, eps=cfg.norm_eps, name=f"l{i}_attn_norm")
        if use_ring_attention:
            attn_fn = lambda q, k, v, e, nh, **kw: ff.ring_attention(
                q, k, v, e, nh, seq_mode=seq_mode, **kw
            )
        else:
            attn_fn = lambda q, k, v, e, nh, **kw: ff.multihead_attention(
                q, k, v, e, nh, bias=False, **kw
            )
        a = attn_fn(a, a, a, cfg.dim, cfg.heads, causal=True,
                    kv_heads=cfg.kv_heads, rope=True, rope_theta=cfg.rope_theta,
                    name=f"l{i}_attn")
        h = ff.add(h, a, name=f"l{i}_res1")
        m = ff.rms_norm(h, eps=cfg.norm_eps, name=f"l{i}_mlp_norm")
        g = ff.dense(m, cfg.hidden, use_bias=False, name=f"l{i}_gate")
        u = ff.dense(m, cfg.hidden, use_bias=False, name=f"l{i}_up")
        x = ff.multiply(ff.silu(g, name=f"l{i}_silu"), u, name=f"l{i}_gxu")
        d = ff.dense(x, cfg.dim, use_bias=False, name=f"l{i}_down")
        h = ff.add(h, d, name=f"l{i}_res2")
    h = ff.rms_norm(h, eps=cfg.norm_eps, name="final_norm")
    logits = ff.dense(h, cfg.vocab_size, use_bias=False, name="lm_head")
    return ff.softmax(logits, name="softmax")


def llama_tp_strategy(cfg: LlamaConfig, seq_parallel: bool = False) -> Dict[str, ShardingView]:
    """Hybrid TP(+SP)+DP views — the Megatron layout: attention heads and
    MLP column/row split over `model`, the gate→silu→×→down chain keeping
    its hidden dim model-sharded between the column and row matmuls;
    activations batch-sharded over `data` (and sequence over `seq` when
    seq_parallel); lm_head + softmax vocab-sharded. Every view declares its
    output/input specs explicitly so the cost model prices the strategy the
    same way it prices search-enumerated views (no optimistic gaps)."""
    sq = ("seq",) if seq_parallel else ()
    act3 = (("data",), sq, ())           # (batch, seq, features) replicated
    hid3 = (("data",), sq, ("model",))   # feature dim model-sharded
    views: Dict[str, ShardingView] = {}
    for i in range(cfg.layers):
        views[f"l{i}_attn"] = ShardingView(
            output_specs=(act3,),
            weight_specs={
                "wq": ((), ("model",), ()),
                "wk": ((), ("model",), ()),
                "wv": ((), ("model",), ()),
                "wo": (("model",), (), ()),
            },
            input_specs=(act3,) * 3,
        )
        views[f"l{i}_gate"] = ShardingView(
            (hid3,), {"kernel": ((), ("model",))}, input_specs=(act3,)
        )
        views[f"l{i}_up"] = ShardingView(
            (hid3,), {"kernel": ((), ("model",))}, input_specs=(act3,)
        )
        views[f"l{i}_silu"] = ShardingView((hid3,))
        views[f"l{i}_gxu"] = ShardingView((hid3,))
        views[f"l{i}_down"] = ShardingView(
            (act3,), {"kernel": (("model",), ())}, input_specs=(hid3,)
        )
    views["lm_head"] = ShardingView(
        (hid3,), {"kernel": ((), ("model",))}, input_specs=(act3,)
    )
    views["softmax"] = ShardingView((hid3,))
    views["tok_emb"] = ShardingView(
        output_specs=(act3,), weight_specs={"kernel": ((), ("model",))}
    )
    return views


def llama_pp_strategy(cfg: LlamaConfig) -> Dict[str, ShardingView]:
    """Pipeline strategy for the use_pipeline=True builder: the stacked
    decoder weights shard their leading layer dim over `pipe` (stage s
    holds its layer slice), activations stay batch-sharded over `data`.
    (`cfg` kept for signature symmetry with llama_tp_strategy; the
    microbatch count lives in the built PipelineAttrs, not the view.)"""
    from flexflow_tpu.parallel.sharding import pipeline_pipe_view

    return {"decoder_pipeline": pipeline_pipe_view(3)}
