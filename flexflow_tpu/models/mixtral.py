"""Mixtral-style MoE decoder builder (BASELINE config 5: Mixtral-8x7B
expert-parallel).

Reference anchors: examples/cpp/mixture_of_experts/moe.cc and the
group_by/aggregate/topk op family. The hot path uses the fused EXPERTS op
(capacity-based one-hot dispatch — MXU-friendly) whose stacked expert
weights shard over the `expert` mesh axis; `mixtral_ep_strategy` returns
that expert-parallel view set. The composite `FFModel.moe` (explicit
top_k -> group_by -> dense -> aggregate, matching the reference graph
structure) is exercised by `build_moe_classifier` for parity testing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel, Tensor
from flexflow_tpu.parallel.sharding import ShardingView


@dataclasses.dataclass
class MixtralConfig:
    vocab_size: int = 32000
    dim: int = 4096
    layers: int = 32
    heads: int = 32
    kv_heads: int = 8
    hidden: int = 14336
    n_experts: int = 8
    top_k: int = 2
    rope_theta: float = 1000000.0
    norm_eps: float = 1e-5
    capacity_factor: float = 1.25
    lambda_bal: float = 1e-2

    @staticmethod
    def mixtral_8x7b() -> "MixtralConfig":
        return MixtralConfig()

    @staticmethod
    def tiny(vocab: int = 512) -> "MixtralConfig":
        return MixtralConfig(vocab_size=vocab, dim=64, layers=2, heads=4,
                             kv_heads=2, hidden=128, n_experts=4, top_k=2,
                             rope_theta=10000.0)


def build_mixtral(ff: FFModel, cfg: MixtralConfig, batch_size: int = None,
                  seq_len: int = 2048, dtype: DataType = DataType.BFLOAT16) -> Tensor:
    b = batch_size or ff.config.batch_size
    ids = ff.create_tensor((b, seq_len), DataType.INT32, name="input_ids")
    h = ff.embedding(ids, cfg.vocab_size, cfg.dim, dtype=dtype, name="tok_emb")
    for i in range(cfg.layers):
        a = ff.rms_norm(h, eps=cfg.norm_eps, name=f"l{i}_attn_norm")
        a = ff.multihead_attention(
            a, a, a, cfg.dim, cfg.heads, bias=False, causal=True,
            kv_heads=cfg.kv_heads, rope=True, rope_theta=cfg.rope_theta,
            name=f"l{i}_attn",
        )
        h = ff.add(h, a, name=f"l{i}_res1")
        m = ff.rms_norm(h, eps=cfg.norm_eps, name=f"l{i}_moe_norm")
        gate = ff.dense(m, cfg.n_experts, use_bias=False, name=f"l{i}_router")
        e = ff.experts(
            m, gate, cfg.n_experts, cfg.top_k, cfg.hidden, cfg.dim,
            alpha=cfg.capacity_factor, activation=ActiMode.SILU,
            lambda_bal=cfg.lambda_bal, name=f"l{i}_experts",
        )
        h = ff.add(h, e, name=f"l{i}_res2")
    h = ff.rms_norm(h, eps=cfg.norm_eps, name="final_norm")
    logits = ff.dense(h, cfg.vocab_size, use_bias=False, name="lm_head")
    return ff.softmax(logits, name="softmax")


def mixtral_ep_strategy(cfg: MixtralConfig) -> Dict[str, ShardingView]:
    """Expert-parallel: stacked expert weights sharded over `expert`;
    attention stays TP over `model` like llama."""
    views: Dict[str, ShardingView] = {}
    for i in range(cfg.layers):
        views[f"l{i}_attn"] = ShardingView(
            weight_specs={
                "wq": ((), ("model",), ()),
                "wk": ((), ("model",), ()),
                "wv": ((), ("model",), ()),
                "wo": (("model",), (), ()),
            },
        )
        views[f"l{i}_experts"] = ShardingView(
            weight_specs={
                "w1": (("expert",), (), ()),
                "w2": (("expert",), (), ()),
            },
        )
    return views


def build_moe_classifier(ff: FFModel, input_dim: int, num_classes: int,
                         num_exp: int = 4, num_select: int = 2,
                         hidden: int = 64, batch_size: int = None) -> Tensor:
    """The reference's MoE example shape (examples/cpp/mixture_of_experts/
    moe.cc): composite gate -> top_k -> group_by -> experts -> aggregate."""
    b = batch_size or ff.config.batch_size
    x = ff.create_tensor((b, input_dim), DataType.FLOAT, name="input")
    t = ff.moe(x, num_exp, num_select, hidden, alpha=2.0, lambda_bal=0.04,
               name="moe")
    t = ff.dense(t, num_classes, name="head")
    return ff.softmax(t, name="softmax")


def build_moe_spec_classifier(ff: FFModel, input_dim: int, num_classes: int,
                              num_exp: int = 4, num_select: int = 2,
                              hidden: int = 64,
                              batch_size: int = None) -> Tensor:
    """Speculative MoE head (reference AggregateSpec, aggregate_spec.cc):
    every selected expert's output becomes its OWN row — (b·k, classes)
    logits — and the loss sees each label k times (the reference's
    repl_labels path, model.cc:2875, wired in the executor)."""
    b = batch_size or ff.config.batch_size
    x = ff.create_tensor((b, input_dim), DataType.FLOAT, name="input")
    gate_preds = ff.dense(x, num_exp, name="spec_gate")
    gate_sm = ff.softmax(gate_preds, name="spec_gate_sm")
    topk_values, topk_assign = ff.top_k(gate_sm, num_select)
    grouped = ff.group_by(x, topk_assign, num_exp, 2.0)
    expert_outs = []
    for i, g in enumerate(grouped):
        h = ff.dense(g, hidden, ActiMode.RELU, name=f"spec_expert{i}")
        expert_outs.append(h)
    agg_inputs = [topk_values, topk_assign, topk_assign, gate_sm] + expert_outs
    t = ff.aggregate_spec(agg_inputs, num_exp, name="agg_spec")
    t = ff.dense(t, num_classes, name="spec_head")
    return ff.softmax(t, name="softmax")
