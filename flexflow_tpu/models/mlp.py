"""MLP builder (reference examples/python/native/mnist_mlp.py and
examples/cpp/MLP_Unify)."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel, Tensor


def build_mlp(ff: FFModel, input_dim: int, hidden: Sequence[int], classes: int,
              batch_size: int = None) -> Tensor:
    b = batch_size or ff.config.batch_size
    t = ff.create_tensor((b, input_dim), DataType.FLOAT, name="input")
    for i, h in enumerate(hidden):
        t = ff.dense(t, h, ActiMode.RELU, name=f"dense{i}")
    t = ff.dense(t, classes, name="head")
    return ff.softmax(t, name="softmax")
