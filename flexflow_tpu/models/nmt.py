"""Seq2seq NMT builder — analog of the reference's legacy standalone NMT app
(nmt/nmt.cc, nmt/rnn.h): stacked-LSTM encoder over the source sequence,
stacked-LSTM decoder over the target sequence whose per-layer initial (h, c)
come from the encoder's finals (the reference wires lstm[layer][seq] nodes
layer-to-layer the same way, rnn.h:184), then a vocab projection + softmax
on every decoder step (reference add_linear_node/add_softmaxDP_node,
rnn.h:164-175).

TPU-native differences: one LSTM op per (layer, direction) scanning the whole
sequence — not one node per LSTM_PER_NODE_LENGTH timesteps — and
data-parallel batch sharding instead of the reference's per-node
ParallelConfig grid.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from flexflow_tpu.ffconst import DataType
from flexflow_tpu.model import FFModel, Tensor
from flexflow_tpu.parallel.sharding import ShardingView


@dataclasses.dataclass(frozen=True)
class NMTConfig:
    src_vocab: int = 32000
    tgt_vocab: int = 32000
    embed_dim: int = 1024
    hidden: int = 1024
    layers: int = 2

    @staticmethod
    def tiny() -> "NMTConfig":
        return NMTConfig(src_vocab=96, tgt_vocab=88, embed_dim=16, hidden=24,
                         layers=2)


def build_nmt(ff: FFModel, cfg: NMTConfig, batch_size: int = None,
              src_len: int = 32, tgt_len: int = 32) -> Tensor:
    """Returns per-step target-vocab probabilities (batch, tgt_len, tgt_vocab);
    train against next-token labels with sparse CCE."""
    b = batch_size or ff.config.batch_size
    src = ff.create_tensor((b, src_len), DataType.INT32, name="src_ids")
    tgt = ff.create_tensor((b, tgt_len), DataType.INT32, name="tgt_ids")

    h = ff.embedding(src, cfg.src_vocab, cfg.embed_dim, name="src_emb")
    finals = []
    for i in range(cfg.layers):
        h, hn, cn = ff.lstm(h, cfg.hidden, name=f"enc{i}")
        finals.append((hn, cn))

    d = ff.embedding(tgt, cfg.tgt_vocab, cfg.embed_dim, name="tgt_emb")
    for i in range(cfg.layers):
        d, _, _ = ff.lstm(d, cfg.hidden, initial_state=finals[i],
                          name=f"dec{i}")

    logits = ff.dense(d, cfg.tgt_vocab, name="proj")
    return ff.softmax(logits, name="softmax")


def nmt_dp_strategy(cfg: NMTConfig) -> Dict[str, ShardingView]:
    """Data-parallel views (the reference NMT's default ParallelConfig is
    also batch partitioning, nmt.cc:319-350) with the vocab projection
    column-sharded over `model` when that axis exists — the softmaxDP
    analog."""
    seq3 = (("data",), (), ())
    state2 = (("data",), ())
    views: Dict[str, ShardingView] = {}
    for pre in ("enc", "dec"):
        for i in range(cfg.layers):
            views[f"{pre}{i}"] = ShardingView((seq3, state2, state2))
    views["src_emb"] = ShardingView((seq3,))
    views["tgt_emb"] = ShardingView((seq3,))
    views["proj"] = ShardingView(
        ((("data",), (), ("model",)),), {"kernel": ((), ("model",))},
        input_specs=(seq3,),
    )
    views["softmax"] = ShardingView(((("data",), (), ("model",)),))
    return views
