"""ResNet-50 builder (reference examples/cpp/ResNet/resnet.cc and
examples/python/pytorch/resnet.py): bottleneck blocks, NCHW."""

from __future__ import annotations

from flexflow_tpu.ffconst import ActiMode, DataType, PoolType
from flexflow_tpu.model import FFModel, Tensor


def _bottleneck(ff: FFModel, t: Tensor, out_ch: int, stride: int, name: str) -> Tensor:
    """1x1 -> 3x3 -> 1x1 with 4x expansion + projection shortcut when shape
    changes (reference resnet.cc BottleneckBlock)."""
    shortcut = t
    in_ch = t.shape[1]
    u = ff.conv2d(t, out_ch, 1, 1, 1, 1, 0, 0, name=f"{name}_c1")
    u = ff.batch_norm(u, relu=True, name=f"{name}_bn1")
    u = ff.conv2d(u, out_ch, 3, 3, stride, stride, 1, 1, name=f"{name}_c2")
    u = ff.batch_norm(u, relu=True, name=f"{name}_bn2")
    u = ff.conv2d(u, 4 * out_ch, 1, 1, 1, 1, 0, 0, name=f"{name}_c3")
    u = ff.batch_norm(u, relu=False, name=f"{name}_bn3")
    if stride != 1 or in_ch != 4 * out_ch:
        shortcut = ff.conv2d(t, 4 * out_ch, 1, 1, stride, stride, 0, 0,
                             name=f"{name}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"{name}_bnp")
    u = ff.add(u, shortcut, name=f"{name}_add")
    return ff.relu(u, name=f"{name}_relu")


def build_resnet50(ff: FFModel, batch_size: int = None, classes: int = 1000,
                   image_size: int = 224) -> Tensor:
    b = batch_size or ff.config.batch_size
    t = ff.create_tensor((b, 3, image_size, image_size), DataType.FLOAT, name="input")
    t = ff.conv2d(t, 64, 7, 7, 2, 2, 3, 3, name="conv1")
    t = ff.batch_norm(t, relu=True, name="bn1")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="pool1")
    for stage, (blocks, ch, stride) in enumerate(
        [(3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2)]
    ):
        for i in range(blocks):
            t = _bottleneck(ff, t, ch, stride if i == 0 else 1,
                            f"s{stage}b{i}")
    # global average pool over spatial dims
    t = ff.mean(t, axes=(2, 3), name="gap")
    t = ff.dense(t, classes, name="fc")
    return ff.softmax(t, name="softmax")
