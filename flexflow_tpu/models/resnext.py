"""ResNeXt-50 (32x4d) builder (reference examples/cpp/resnext50/
resnext.cc): bottlenecks with 32-group 3x3 convs — exercises the grouped
`feature_group_count` conv lowering. NCHW."""

from __future__ import annotations

from flexflow_tpu.ffconst import DataType
from flexflow_tpu.model import FFModel, Tensor


def _resnext_block(ff: FFModel, t: Tensor, mid_ch: int, out_ch: int,
                   stride: int, groups: int, name: str) -> Tensor:
    shortcut = t
    in_ch = t.shape[1]
    u = ff.conv2d(t, mid_ch, 1, 1, 1, 1, 0, 0, use_bias=False,
                  name=f"{name}_c1")
    u = ff.batch_norm(u, relu=True, name=f"{name}_bn1")
    u = ff.conv2d(u, mid_ch, 3, 3, stride, stride, 1, 1, groups=groups,
                  use_bias=False, name=f"{name}_c2")
    u = ff.batch_norm(u, relu=True, name=f"{name}_bn2")
    u = ff.conv2d(u, out_ch, 1, 1, 1, 1, 0, 0, use_bias=False,
                  name=f"{name}_c3")
    u = ff.batch_norm(u, relu=False, name=f"{name}_bn3")
    if stride != 1 or in_ch != out_ch:
        shortcut = ff.conv2d(t, out_ch, 1, 1, stride, stride, 0, 0,
                             use_bias=False, name=f"{name}_proj")
        shortcut = ff.batch_norm(shortcut, relu=False, name=f"{name}_bnp")
    u = ff.add(u, shortcut, name=f"{name}_add")
    return ff.relu(u, name=f"{name}_relu")


def build_resnext50(ff: FFModel, batch_size: int = None, classes: int = 1000,
                    image_size: int = 224, groups: int = 32,
                    width_per_group: int = 4) -> Tensor:
    b = batch_size or ff.config.batch_size
    t = ff.create_tensor((b, 3, image_size, image_size), DataType.FLOAT,
                         name="input")
    t = ff.conv2d(t, 64, 7, 7, 2, 2, 3, 3, use_bias=False, name="conv1")
    t = ff.batch_norm(t, relu=True, name="bn1")
    t = ff.pool2d(t, 3, 3, 2, 2, 1, 1, name="pool1")
    for stage, (blocks, base, stride) in enumerate(
        [(3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2)]
    ):
        mid = base * groups * width_per_group // 64
        for i in range(blocks):
            t = _resnext_block(ff, t, mid, base * 4, stride if i == 0 else 1,
                               groups, f"s{stage}b{i}")
    t = ff.mean(t, axes=(2, 3), name="gap")
    t = ff.dense(t, classes, name="fc")
    return ff.softmax(t, name="softmax")
