"""Transformer builders — the reference Transformer example analog
(examples/cpp/Transformer/transformer.cc): an encoder stack
(create_attention_encoder, transformer.cc:33-45: MHA + two dense layers)
and the encoder-decoder variant with CROSS-attention
(create_attention_encoder_decoder, transformer.cc:47-72: decoder
self-attention, then attention over the encoder states) that the reference
carries but leaves commented out of its main.

Regression head (dense -> 1, MSE) matches the reference example's training
setup (transformer.cc:158)."""

from __future__ import annotations

import dataclasses

from flexflow_tpu.ffconst import ActiMode, DataType
from flexflow_tpu.model import FFModel, Tensor


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    dim: int = 512
    heads: int = 8
    hidden: int = 2048
    layers: int = 6

    @staticmethod
    def tiny() -> "TransformerConfig":
        return TransformerConfig(dim=32, heads=4, hidden=64, layers=2)


def _ffn(ff: FFModel, t: Tensor, cfg: TransformerConfig, name: str) -> Tensor:
    h = ff.dense(t, cfg.hidden, ActiMode.RELU, use_bias=False,
                 name=f"{name}_ff1")
    return ff.dense(h, cfg.dim, use_bias=False, name=f"{name}_ff2")


def _encoder_stack(ff: FFModel, t: Tensor, cfg: TransformerConfig) -> Tensor:
    for i in range(cfg.layers):
        a = ff.multihead_attention(t, t, t, cfg.dim, cfg.heads,
                                   causal=False, name=f"enc{i}_attn")
        t = ff.add(t, a, name=f"enc{i}_res")
        t = _ffn(ff, t, cfg, f"enc{i}")
    return t


def build_transformer_encoder(ff: FFModel, cfg: TransformerConfig,
                              batch_size: int = None,
                              seq_len: int = 64) -> Tensor:
    """Encoder stack + regression head (the reference example's main path,
    transformer.cc:144-158)."""
    b = batch_size or ff.config.batch_size
    t = ff.create_tensor((b, seq_len, cfg.dim), DataType.FLOAT, name="input")
    return ff.dense(_encoder_stack(ff, t, cfg), 1, use_bias=False,
                    name="head")


def build_transformer_encoder_decoder(ff: FFModel, cfg: TransformerConfig,
                                      batch_size: int = None,
                                      src_len: int = 64,
                                      tgt_len: int = 48) -> Tensor:
    """Encoder-decoder with cross-attention (transformer.cc:47-72): the
    decoder attends causally to itself, then (unmasked) to the encoder
    states — the layout every seq2seq transformer uses."""
    b = batch_size or ff.config.batch_size
    src = ff.create_tensor((b, src_len, cfg.dim), DataType.FLOAT, name="src")
    tgt = ff.create_tensor((b, tgt_len, cfg.dim), DataType.FLOAT, name="tgt")
    t1 = _encoder_stack(ff, src, cfg)
    t2 = tgt
    for i in range(cfg.layers):
        a = ff.multihead_attention(t2, t2, t2, cfg.dim, cfg.heads,
                                   causal=True, name=f"dec{i}_self")
        t2 = ff.add(t2, a, name=f"dec{i}_res1")
        x = ff.multihead_attention(t2, t1, t1, cfg.dim, cfg.heads,
                                   causal=False, name=f"dec{i}_cross")
        t2 = ff.add(t2, x, name=f"dec{i}_res2")
        t2 = _ffn(ff, t2, cfg, f"dec{i}")
    return ff.dense(t2, 1, use_bias=False, name="head")
