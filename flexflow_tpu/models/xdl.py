"""XDL builder (reference examples/cpp/XDL/xdl.cc): the ads CTR model —
many small sparse embeddings concatenated straight into a dense stack (no
DLRM-style bottom MLP / interaction). Embedding-table parallelism target."""

from __future__ import annotations

from typing import Sequence

from flexflow_tpu.ffconst import ActiMode, AggrMode, DataType
from flexflow_tpu.model import FFModel, Tensor


def build_xdl(ff: FFModel, num_sparse: int = 16, vocab: int = 100000,
              embed_dim: int = 16, dense_dim: int = 16,
              mlp_dims: Sequence[int] = (512, 256, 128, 1),
              batch_size: int = None) -> Tensor:
    b = batch_size or ff.config.batch_size
    parts = []
    for i in range(num_sparse):
        ids = ff.create_tensor((b, 1), DataType.INT32, name=f"sparse_{i}")
        # SUM aggregation collapses the bag dim to (b, embed_dim) directly
        # (same pattern as the DLRM builder — no reshape node needed)
        parts.append(ff.embedding(ids, vocab, embed_dim, AggrMode.SUM,
                                  name=f"emb_{i}"))
    dense_in = ff.create_tensor((b, dense_dim), DataType.FLOAT,
                                name="dense_input")
    parts.append(dense_in)
    t = ff.concat(parts, axis=1, name="cat")
    for i, d in enumerate(mlp_dims[:-1]):
        t = ff.dense(t, d, ActiMode.RELU, name=f"mlp{i}")
    t = ff.dense(t, mlp_dims[-1], ActiMode.SIGMOID, name="ctr")
    return t
