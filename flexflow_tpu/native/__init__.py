"""ctypes loader for the native search engine (native/ffsim.cc).

The reference's search/simulator layer is C++ (src/runtime/simulator.cc,
model.cc mcmc); ours is too — Python prices (node, view) pairs with the
analytic TPU cost model, and libffsim owns the hot loops. The library is
built on demand with g++ (no pybind11 in this image; plain C ABI +
ctypes). Everything degrades gracefully to the pure-Python path when no
compiler is available: callers must check `available()`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_PKG_DIR, "..", "..", "native", "ffsim.cc")
_LIB_PATH = os.path.join(_PKG_DIR, "libffsim.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_so(src: str, lib_path: str, extra_flags=()) -> bool:
    """Compile `src` to `lib_path` if stale; atomic tmp+replace so a
    concurrent process never dlopens a partially written .so."""
    src = os.path.abspath(src)
    if not os.path.exists(src):
        return False
    if os.path.exists(lib_path) and os.path.getmtime(lib_path) >= os.path.getmtime(src):
        return True
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", *extra_flags,
             "-o", tmp, src],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, lib_path)
        return True
    except (OSError, subprocess.SubprocessError):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _build() -> bool:
    return _build_so(_SRC, _LIB_PATH)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    dp = ctypes.POINTER(ctypes.c_double)
    ip = ctypes.POINTER(ctypes.c_int)
    lib.ffsim_create.restype = ctypes.c_void_p
    lib.ffsim_create.argtypes = [ctypes.c_int]
    lib.ffsim_destroy.argtypes = [ctypes.c_void_p]
    lib.ffsim_set_node.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                   dp, dp, dp, dp]
    lib.ffsim_add_edge.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int, dp]
    lib.ffsim_eval.restype = ctypes.c_double
    lib.ffsim_eval.argtypes = [ctypes.c_void_p, ip, ctypes.c_double, dp]
    lib.ffsim_simulate.restype = ctypes.c_double
    lib.ffsim_simulate.argtypes = [ctypes.c_void_p, ip]
    lib.ffsim_mcmc.restype = ctypes.c_int
    lib.ffsim_mcmc.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_double,
                               ctypes.c_uint64, ctypes.c_double, ctypes.c_double,
                               ctypes.c_int, ip, dp]
    lib.ffsim_tasksim_build.restype = ctypes.c_void_p
    lib.ffsim_tasksim_build.argtypes = [ctypes.c_int, ctypes.c_int, ip, dp,
                                        ctypes.c_int, ip, ip]
    lib.ffsim_tasksim_destroy.argtypes = [ctypes.c_void_p]
    lib.ffsim_tasksim_run.restype = ctypes.c_double
    lib.ffsim_tasksim_run.argtypes = [ctypes.c_void_p]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("FLEXFLOW_NATIVE", "1") == "0":
        return None
    if _build():
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


class NativeSimGraph:
    """Owns one ffsim graph handle; rows are (node, view) cost tables."""

    def __init__(self, n_nodes: int):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native ffsim library unavailable")
        self._h = self._lib.ffsim_create(n_nodes)
        self.n_nodes = n_nodes
        self._n_views = [0] * n_nodes

    def __del__(self):
        if getattr(self, "_h", None) and self._lib is not None:
            self._lib.ffsim_destroy(self._h)
            self._h = None

    @staticmethod
    def _darr(vals):
        return (ctypes.c_double * len(vals))(*vals)

    def set_node(self, node, compute, comm, sync, memory):
        n = len(compute)
        assert len(comm) == len(sync) == len(memory) == n
        self._n_views[node] = n
        self._lib.ffsim_set_node(
            self._h, node, n, self._darr(compute), self._darr(comm),
            self._darr(sync), self._darr(memory),
        )

    def add_edge(self, src, dst, xfer_matrix):
        flat = [x for row in xfer_matrix for x in row]
        assert len(flat) == self._n_views[src] * self._n_views[dst]
        self._lib.ffsim_add_edge(self._h, src, dst, self._darr(flat))

    def _iarr(self, assignment):
        assert len(assignment) == self.n_nodes
        return (ctypes.c_int * self.n_nodes)(*assignment)

    def eval(self, assignment, overlap: float = 0.0):
        mem = ctypes.c_double()
        t = self._lib.ffsim_eval(self._h, self._iarr(assignment), overlap,
                                 ctypes.byref(mem))
        return t, mem.value

    def simulate(self, assignment) -> float:
        return self._lib.ffsim_simulate(self._h, self._iarr(assignment))

    def mcmc(self, assignment, *, budget: int, alpha: float, seed: int = 0,
             overlap: float = 0.0, memory_limit: float = 0.0,
             use_simulate: bool = False):
        arr = self._iarr(assignment)
        best_cost = ctypes.c_double()
        accepted = self._lib.ffsim_mcmc(
            self._h, budget, alpha, seed, overlap, memory_limit,
            1 if use_simulate else 0, arr, ctypes.byref(best_cost),
        )
        return list(arr), best_cost.value, accepted


def run_task_dag(n_channels: int, channels, durations, dep_src, dep_dst):
    """List-schedule a task DAG on `n_channels` serial channels (per-chip
    compute + per-axis ICI — see native/ffsim.cc ffsim_tasksim_build) and
    return the makespan, or None when the native engine is unavailable.
    `channels`/`durations`/`dep_*` are flat sequences (numpy arrays fine);
    the whole DAG ships in one call to keep ctypes off the hot loop."""
    lib = get_lib()
    if lib is None:
        return None
    import numpy as np

    # one bulk conversion per array — per-element ctypes marshalling would
    # dominate the C scheduler on the search hot path
    ch = np.ascontiguousarray(channels, dtype=np.int32)
    du = np.ascontiguousarray(durations, dtype=np.float64)
    ds = np.ascontiguousarray(dep_src, dtype=np.int32)
    dd = np.ascontiguousarray(dep_dst, dtype=np.int32)
    ip = ctypes.POINTER(ctypes.c_int)
    dp = ctypes.POINTER(ctypes.c_double)
    h = lib.ffsim_tasksim_build(
        n_channels, len(ch), ch.ctypes.data_as(ip), du.ctypes.data_as(dp),
        len(ds), ds.ctypes.data_as(ip), dd.ctypes.data_as(ip))
    try:
        t = lib.ffsim_tasksim_run(h)
    finally:
        lib.ffsim_tasksim_destroy(h)
    return None if t < 0 else t


# ---------------------------------------------------------------------------
# native data loader (native/ffloader.cc — flexflow_dataloader.cc analog)

_LOADER_SRC = os.path.join(_PKG_DIR, "..", "..", "native", "ffloader.cc")
_LOADER_LIB_PATH = os.path.join(_PKG_DIR, "libffloader.so")
_loader_lib: Optional[ctypes.CDLL] = None
_loader_tried = False


def _build_loader() -> bool:
    return _build_so(_LOADER_SRC, _LOADER_LIB_PATH, extra_flags=("-pthread",))


def get_loader_lib() -> Optional[ctypes.CDLL]:
    global _loader_lib, _loader_tried
    if _loader_lib is not None or _loader_tried:
        return _loader_lib
    _loader_tried = True
    if os.environ.get("FLEXFLOW_NATIVE", "1") == "0":
        return None
    if _build_loader():
        try:
            lib = ctypes.CDLL(_LOADER_LIB_PATH)
            lib.ffl_open.restype = ctypes.c_void_p
            lib.ffl_open.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                     ctypes.c_long, ctypes.c_long]
            lib.ffl_config.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_long]
            lib.ffl_reset.argtypes = [ctypes.c_void_p]
            lib.ffl_next.restype = ctypes.c_int
            lib.ffl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_long]
            lib.ffl_close.argtypes = [ctypes.c_void_p]
            _loader_lib = lib
        except OSError:
            _loader_lib = None
    return _loader_lib


def loader_available() -> bool:
    return get_loader_lib() is not None
