"""fftrace — structured tracing + metrics for the serving tick loop.

Two layers with different overhead budgets:

  * `MetricsRegistry` (obs.metrics): counters/gauges/fixed-bucket
    histograms. Always on — every generation server owns one and feeds
    both the JSON metrics endpoint and the Prometheus text endpoint.
    An observe() is a bisect + two adds.
  * Span recorder + TickLedger (obs.trace / obs.ledger): opt-in via
    `obs.enable()`. When disabled, `obs.span(name)` returns a shared
    falsy singleton — zero allocations on the tick path (the
    disabled-overhead guard in tests/test_obs.py holds this to account).
  * Request log + SLO monitor (obs.reqlog / obs.slo): a bounded
    flight recorder of one record per COMPLETED request (cheap enough
    to leave on in production; `request_log(0)` is the same falsy
    no-op discipline as span), the replay substrate for `servesearch
    search --replay` and `fftrace replay`, and the sliding-window SLO
    judge whose breach events dump the recorder state to disk.

Usage on a hot path:

    from flexflow_tpu import obs
    ...
    with obs.span("decode_tick") as sp:
        if sp:  # only build the attrs dict when someone is recording
            sp.set(live=len(live), width=T)
        ...

Calibration (see obs.calibrate and tools/fftrace.py):

    obs.enable()
    ... serve traffic ...
    obs.recorder().export_chrome_trace("trace.json")   # Perfetto
    led = obs.ledger(); stamp_ledger_meta(led, ff); led.save("ledger.json")
    # fftrace calibrate ledger.json -> per-tick-shape scale factors
"""

from __future__ import annotations

from typing import Optional

from flexflow_tpu.obs.compile_tracker import CompileTracker
from flexflow_tpu.obs.ledger import TickLedger, shape_key
from flexflow_tpu.obs.metrics import (
    COUNT_BUCKETS,
    RATIO_BUCKETS,
    TIME_BUCKETS_S,
    Histogram,
    MetricsRegistry,
    flatten_scalars,
)
from flexflow_tpu.obs.reqlog import (
    NULL_REQLOG,
    BoundedRing,
    RequestLog,
    dump_jsonl,
    load_jsonl,
    request_log,
)
from flexflow_tpu.obs.slo import SLOMonitor, SLOTarget
from flexflow_tpu.obs.trace import NULL_SPAN, Span, TraceRecorder

_recorder: Optional[TraceRecorder] = None


def enable(max_events: int = 200_000,
           annotate_device: bool = True) -> TraceRecorder:
    """Install a fresh TraceRecorder (replacing any previous one) and
    return it. Spans and ledger recording start immediately."""
    global _recorder
    _recorder = TraceRecorder(max_events=max_events,
                              annotate_device=annotate_device)
    return _recorder


def disable() -> Optional[TraceRecorder]:
    """Stop recording; returns the recorder so its events/ledger can
    still be exported after the fact."""
    global _recorder
    rec, _recorder = _recorder, None
    return rec


def enabled() -> bool:
    return _recorder is not None


def recorder() -> Optional[TraceRecorder]:
    return _recorder


def ledger() -> Optional[TickLedger]:
    return _recorder.ledger if _recorder is not None else None


def span(name: str):
    """A live Span when enabled, else the falsy no-op singleton."""
    rec = _recorder
    if rec is None:
        return NULL_SPAN
    return Span(rec, name)


__all__ = [
    "COUNT_BUCKETS",
    "BoundedRing",
    "CompileTracker",
    "Histogram",
    "MetricsRegistry",
    "NULL_REQLOG",
    "NULL_SPAN",
    "RATIO_BUCKETS",
    "RequestLog",
    "SLOMonitor",
    "SLOTarget",
    "Span",
    "TIME_BUCKETS_S",
    "TickLedger",
    "TraceRecorder",
    "disable",
    "dump_jsonl",
    "enable",
    "enabled",
    "flatten_scalars",
    "ledger",
    "load_jsonl",
    "recorder",
    "request_log",
    "shape_key",
    "span",
]
