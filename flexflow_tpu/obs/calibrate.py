"""Predicted-vs-measured calibration: diff the TickLedger against the
decode-tick time the search side prices.

The search stack prices one *full training/inference step* of the
compiled graph (search/cost_model.graph_cost, or the per-device event
simulator when the native extension is present). A serving tick runs
the same program at a different token count — `batch` rows for a plain
decode tick, `batch * tree_width` scored rows for a speculative verify,
`chunk` prompt tokens for a chunked-prefill tick — so the prediction
for a tick shape is the priced step time scaled by
tick_tokens / graph_tokens. That linear-in-tokens model is crude on
purpose: its per-shape error IS the calibration signal. The report's
ratios (measured / predicted) are exactly the scale factors
`MeasuredCostModel.set_tick_calibration` consumes, closing the loop
ROADMAP's "auto-tuned decode strategies under SLO" item needs.

`stamp_ledger_meta(ledger, ff)` embeds the priced base step into the
ledger before it is saved, so `fftrace calibrate ledger.json` runs from
the artifact alone — no model, no recompile, no accelerator.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from flexflow_tpu.obs.ledger import TickLedger, parse_shape_key

# Report schema: v2 added the created-at stamp consumers use for
# staleness (search/servesearch.py refuses reports older than its
# max-age window, mirroring bench.py's last-green guard).
CALIBRATION_SCHEMA_VERSION = 2


def graph_tokens(graph) -> int:
    """Token count of one step of `graph`: product of the first INPUT's
    leading dims (batch × seq for an LM, batch for a flat model)."""
    from flexflow_tpu.ffconst import OpType

    first = next(n for n in graph.nodes if n.op_type == OpType.INPUT)
    dims = first.outputs[0].dims
    toks = dims[0].size
    if len(dims) > 1:
        toks *= dims[1].size
    return max(int(toks), 1)


def predict_step_seconds(ff) -> Dict:
    """Price one forward (inference) step of ff's compiled graph with
    the same model the strategy search uses: eventsim when the native
    extension is available, graph_cost otherwise. Returns the priced
    time plus everything calibration needs to scale it per tick shape."""
    from flexflow_tpu.search import eventsim
    from flexflow_tpu.search.api import _cost_model

    graph = ff.graph
    strategy = {n.name: n.sharding for n in graph.nodes
                if n.sharding is not None}
    cost = _cost_model(ff.mesh, ff.config)
    t, mode = eventsim.step_seconds(graph, strategy, cost, training=False)
    return {
        "predicted_step_s": float(t),
        "pricing_mode": mode,
        "graph_tokens": graph_tokens(graph),
    }


def tick_tokens(phase: str, batch: int, chunk: int, width: int) -> int:
    """Token rows one ledger entry of this shape pushes through the
    model. For decode, `width` is the MEGASTEP width — fused inner ticks
    per dispatch (w1 = the one-tick loop), each scoring `batch` rows —
    so `decode|b4|w8` prices 32 rows and the per-shape calibration
    ratios (and MeasuredCostModel.decode_tick_time) stay meaningful
    across megastep configurations."""
    if phase == "prefill":
        return max(int(chunk), 1)
    # decode: one row per live slot per fused tick; verify: one row per
    # tree node per slot
    return max(int(batch) * max(int(width), 1), 1)


def predict_tick_seconds(base_step_s: float, base_tokens: int, phase: str,
                         batch: int, chunk: int = 0, width: int = 1
                         ) -> float:
    toks = tick_tokens(phase, batch, chunk, width)
    return base_step_s * toks / max(int(base_tokens), 1)


def stamp_ledger_meta(ledger: TickLedger, ff, **extra) -> None:
    """Embed the priced base step (and any caller context, e.g. model
    name) into ledger.meta so the saved ledger is self-contained. When
    the executor's CompileTracker has recorded events, their median
    per-compile wall time rides along too — `servesearch explain`
    prices each candidate strategy's warmup as catalog size × this
    median."""
    ledger.meta.update(predict_step_seconds(ff))
    tracker = getattr(getattr(ff, "executor", None),
                      "compile_tracker", None)
    events = tracker.observed() if tracker is not None else []
    if events:
        secs = sorted(ev["seconds"] for ev in events)
        ledger.meta["compile_seconds_p50"] = secs[len(secs) // 2]
        ledger.meta["compile_events"] = len(secs)
    ledger.meta.update(extra)


def calibration_report(ledger: TickLedger,
                       predicted: Optional[Dict] = None) -> Dict:
    """Per-shape predicted-vs-measured diff. `predicted` overrides the
    base-step pricing; by default it comes from ledger.meta (stamped by
    stamp_ledger_meta). Raises if neither carries a priced step.

    Report structure:
      version / created_at(_unix): schema + staleness stamp — consumers
                   with a freshness window (servesearch) check these
      shapes:      {key: {measured p50/p95/mean, predicted_s, ratio}}
      tick_scales: {key: ratio}      — MeasuredCostModel.set_tick_calibration
      phases:      {phase: median ratio across that phase's shapes}
      compile:     {seconds_p50, events} when the ledger was stamped on
                   a model whose CompileTracker saw compiles — the
                   measured per-compile price servesearch explain's
                   compile_cost line multiplies the shape catalog by
    Ratio > 1 means reality is slower than the model prices (the usual
    direction on host-bound CPU ticks); ratio ≈ 1 means the cost model
    already prices this shape faithfully.
    """
    src = predicted if predicted is not None else ledger.meta
    if "predicted_step_s" not in src:
        raise ValueError(
            "ledger has no predicted_step_s meta — run stamp_ledger_meta "
            "(or pass predicted=) before calibrating")
    base_s = float(src["predicted_step_s"])
    base_tokens = int(src.get("graph_tokens", 1))

    shapes: Dict[str, Dict] = {}
    by_phase: Dict[str, list] = {}
    for key in ledger.shapes():
        st = ledger.stats(key)
        if st is None:
            continue
        sk = parse_shape_key(key)
        pred = predict_tick_seconds(base_s, base_tokens, sk["phase"],
                                    sk["batch"], sk["chunk"], sk["width"])
        ratio = st["p50_s"] / pred if pred > 0 else float("inf")
        shapes[key] = {
            **sk,
            "count": st["count"],
            "measured_p50_s": st["p50_s"],
            "measured_p95_s": st["p95_s"],
            "measured_mean_s": st["mean_s"],
            "predicted_s": pred,
            "ratio": ratio,
        }
        by_phase.setdefault(sk["phase"], []).append(ratio)

    phases = {}
    for phase, ratios in sorted(by_phase.items()):
        rs = sorted(ratios)
        phases[phase] = rs[len(rs) // 2]
    now = time.time()
    report = {
        "version": CALIBRATION_SCHEMA_VERSION,
        "created_at_unix": float(now),
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)),
        "base": {"predicted_step_s": base_s, "graph_tokens": base_tokens,
                 "pricing_mode": src.get("pricing_mode", "unknown")},
        "meta": {k: v for k, v in ledger.meta.items()
                 if k not in ("predicted_step_s", "graph_tokens",
                              "pricing_mode", "compile_seconds_p50",
                              "compile_events")},
        "shapes": shapes,
        "tick_scales": {k: v["ratio"] for k, v in shapes.items()},
        "phases": phases,
    }
    if "compile_seconds_p50" in ledger.meta:
        report["compile"] = {
            "seconds_p50": float(ledger.meta["compile_seconds_p50"]),
            "events": int(ledger.meta.get("compile_events", 0)),
        }
    return report
