"""Compile-event tracker — the runtime arm of the shapecheck pass
(docs/analysis.md "shapecheck", docs/observability.md "Compile events").

Every distinct input shape hitting a `jax.jit` entry point costs an XLA
compilation. The static arm (analysis/shapecheck.py) enumerates the
closed catalog of reachable launch shapes per served config; this
module OBSERVES the compilations that actually happen, so the two can
be diffed:

  * `CompileTracker.wrap(entry, fn, sig_fn)` wraps a jitted callable.
    Real XLA compiles are detected through jax's monitoring events
    (`/jax/core/compile/*` durations fire synchronously on the calling
    thread, so a thread-local frame attributes them to the wrapped call
    in flight); each compiling call records {entry, shape, seconds,
    steady_state} with `seconds` the summed trace+lower+backend-compile
    time. The jit dispatch cache also keys on argument COMMITTEDNESS
    (device-bound jit outputs vs fresh host uploads), so it grows new
    entries that reuse an existing lowering — those cost ~ms, compile
    nothing, and are deliberately NOT events. When the monitoring hook
    is unavailable the fallback is the jit wrapper's own cache-size
    delta (`fn._cache_size()`), or a seen-signature set below that;
    there `seconds` wall-times the missing call (an upper bound that
    includes the first execution — the conservative direction for TTFT
    accounting).
  * `mark_steady_state()` flips the phase bit after warmup: every event
    recorded afterwards increments the `steady_state_recompiles` gauge
    — the number the CI soundness gate pins at zero.
  * `set_registry(MetricsRegistry)` exports `ff_compile_seconds` (a
    histogram of per-event compile wall time) and the
    `ff_compile_events_total` counter; scoped scalar totals also ride
    the server's metrics() payload alongside the
    `ff_steady_state_recompiles` / `ff_jit_cache_entries` gauges the
    serving layer sets.

The tracker only touches jax lazily (the optional monitoring hook) and
degrades to plain callables: any function works, at one list append
plus one clock read per wrapped call on the hit path.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# thread-local stack of in-flight wrapped calls: jax's monitoring
# listeners fire synchronously on the compiling thread, so the top
# frame is the call any compile event belongs to
_tls = threading.local()
_listener_state = {"installed": None}  # None = not tried yet
_install_lock = threading.Lock()


def _on_duration_event(name: str, seconds: float, **_kw) -> None:
    stack = getattr(_tls, "stack", None)
    if not stack or not name.startswith("/jax/core/compile/"):
        return
    frame = stack[-1]
    frame["seconds"] += float(seconds)
    if name.endswith("backend_compile_duration"):
        frame["compiles"] += 1


def _install_listener() -> bool:
    """Register the compile-event listener once per process; False when
    this jax build doesn't expose the monitoring hook (the wrapper then
    falls back to cache-size deltas)."""
    if _listener_state["installed"] is None:
        with _install_lock:
            if _listener_state["installed"] is None:
                try:
                    from jax._src import monitoring

                    monitoring.register_event_duration_secs_listener(
                        _on_duration_event)
                    _listener_state["installed"] = True
                except Exception:
                    _listener_state["installed"] = False
    return _listener_state["installed"]


def _default_sig(args: Sequence[Any]) -> Tuple[int, ...]:
    """Fallback signature: the shape of the first array-like argument."""
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            return tuple(int(x) for x in shape)
    return ()


class _TrackedJit:
    """Transparent wrapper around one jitted entry point. Delegates
    everything (`.lower()`, `.clear_cache()`, ...) to the wrapped
    function — same contract as the executor's _TracedStep shim."""

    __slots__ = ("_fn", "_entry", "_sig_fn", "_tracker", "_seen")

    def __init__(self, tracker: "CompileTracker", entry: str,
                 fn: Callable, sig_fn: Optional[Callable] = None):
        self._tracker = tracker
        self._entry = entry
        self._fn = fn
        self._sig_fn = sig_fn
        self._seen: set = set()

    def _shape(self, args) -> Tuple[int, ...]:
        try:
            return tuple(int(x) for x in (self._sig_fn(args)
                                          if self._sig_fn
                                          else _default_sig(args)))
        except Exception:
            return ()

    def __call__(self, *args):
        if _install_listener():
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            frame = {"compiles": 0, "seconds": 0.0}
            stack.append(frame)
            try:
                out = self._fn(*args)
            finally:
                stack.pop()
            if frame["compiles"]:
                self._tracker.record(self._entry, self._shape(args),
                                     frame["seconds"])
            return out
        cache_size = getattr(self._fn, "_cache_size", None)
        if callable(cache_size):
            before = cache_size()
            t0 = time.monotonic()
            out = self._fn(*args)
            if cache_size() > before:
                self._tracker.record(self._entry, self._shape(args),
                                     time.monotonic() - t0)
            return out
        # no hook at all: first sighting of each canonical signature
        # counts as the compile (an approximation that still catches
        # every shape-space escape, the property the gate pins)
        shape = self._shape(args)
        if shape in self._seen:
            return self._fn(*args)
        t0 = time.monotonic()
        out = self._fn(*args)
        self._seen.add(shape)
        self._tracker.record(self._entry, shape, time.monotonic() - t0)
        return out

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


class CompileTracker:
    """Process-wide (per-Executor) ledger of jit compile events."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[Dict] = []
        self._steady = False
        self._registry = None
        self._h_seconds = None
        self._c_events = None

    # -- wiring -----------------------------------------------------------

    def wrap(self, entry: str, fn: Callable,
             sig_fn: Optional[Callable] = None) -> _TrackedJit:
        """Wrap a jitted callable; `sig_fn(args) -> tuple` extracts the
        canonical launch-shape signature (the catalog's coordinate
        system) from one call's arguments."""
        return _TrackedJit(self, entry, fn, sig_fn)

    def set_registry(self, registry) -> None:
        """Bind a MetricsRegistry: subsequent events observe the
        `compile_seconds` histogram and increment the
        `compile_events_total` counter (events recorded before binding
        ride metrics() snapshots only — counters cannot be back-dated)."""
        with self._lock:
            self._registry = registry
            self._h_seconds = registry.histogram("compile_seconds")
            self._c_events = registry.counter("compile_events_total")

    def mark_steady_state(self) -> None:
        """Warmup is over: every compile event from here on is a
        steady-state recompile — the count the soundness gate pins at
        zero."""
        with self._lock:
            self._steady = True

    def mark_warmup(self) -> None:
        """Re-enter the warmup phase. An executor-owned tracker outlives
        any one server; a new server starting its own warm cycle (the
        common sequential-servers pattern in tests) must not have its
        warm compiles counted as the previous server's steady-state
        recompiles."""
        with self._lock:
            self._steady = False

    # -- recording --------------------------------------------------------

    def record(self, entry: str, shape: Tuple[int, ...],
               seconds: float) -> None:
        with self._lock:
            self._events.append({
                "entry": entry,
                "shape": tuple(int(x) for x in shape),
                "seconds": float(seconds),
                "steady_state": self._steady,
            })
            if self._h_seconds is not None:
                self._h_seconds.observe(float(seconds))
            if self._c_events is not None:
                self._c_events.inc()

    # -- reading ----------------------------------------------------------

    @property
    def in_steady_state(self) -> bool:
        return self._steady

    def observed(self, since: int = 0) -> List[Dict]:
        """Copies of recorded events (from index `since` — a server
        passes its creation-time event count to scope the view to its
        own lifetime) — check_soundness input."""
        with self._lock:
            return [dict(ev) for ev in self._events[since:]]

    def observed_shapes(self) -> Dict[str, set]:
        """entry -> set of observed launch-shape signatures."""
        out: Dict[str, set] = {}
        with self._lock:
            for ev in self._events:
                out.setdefault(ev["entry"], set()).add(ev["shape"])
        return out

    @property
    def compile_events_total(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def compile_seconds_total(self) -> float:
        with self._lock:
            return sum(ev["seconds"] for ev in self._events)

    @property
    def steady_state_recompiles(self) -> int:
        with self._lock:
            return sum(1 for ev in self._events if ev["steady_state"])

    def snapshot(self, since: int = 0) -> Dict:
        """Scalar block for a server's metrics() payload (the /metrics
        endpoint renders *_total names as Prometheus counters). `since`
        scopes the totals to events recorded after that index — a
        server's own lifetime on a shared executor tracker."""
        with self._lock:
            evs = self._events[since:]
            return {
                "compile_events_total": len(evs),
                "compile_seconds_sum": round(
                    sum(ev["seconds"] for ev in evs), 6),
                "steady_state_recompiles": sum(
                    1 for ev in evs if ev["steady_state"]),
            }
