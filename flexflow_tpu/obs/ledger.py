"""TickLedger — measured per-tick wall times keyed by tick shape.

The calibration half of fftrace: every scheduler tick records its
measured wall time under a *shape key* ("what work did this tick do"),
so `fftrace calibrate` can diff each shape's measured distribution
against the time the search side prices for the same work
(search/cost_model.py + eventsim). Shape keys:

    decode|b4|c0|w1     — plain decode tick, 4 live slots
    verify|b4|c0|w8     — speculative verify, 8-node trees
    prefill|b2|c64|w1   — chunked prefill, 64 prompt tokens this tick

Per-shape samples are bounded (deque maxlen): a long-running server's
ledger holds the *recent* distribution per shape, not an unbounded
history — calibration wants current conditions anyway.

The ledger also carries a `meta` dict (model name, predicted base step
time, graph token count) stamped by whoever runs the workload, so a
saved ledger.json is self-contained: `fftrace calibrate ledger.json`
needs no model recompile.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Deque, Dict, List, Optional


def shape_key(phase: str, batch: int, chunk: int = 0, width: int = 1) -> str:
    return f"{phase}|b{int(batch)}|c{int(chunk)}|w{int(width)}"


def parse_shape_key(key: str) -> Dict:
    phase, b, c, w = key.split("|")
    return {"phase": phase, "batch": int(b[1:]), "chunk": int(c[1:]),
            "width": int(w[1:])}


def _quantile(sorted_vals: List[float], q: float) -> float:
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (idx - lo)


class TickLedger:
    """Bounded per-shape samples of measured tick wall times (seconds)."""

    def __init__(self, max_samples_per_shape: int = 512):
        self.max_samples = int(max_samples_per_shape)
        self._samples: Dict[str, Deque[float]] = {}
        self._counts: Dict[str, int] = {}
        self.meta: Dict = {}

    def record(self, phase: str, seconds: float, batch: int,
               chunk: int = 0, width: int = 1) -> None:
        key = shape_key(phase, batch, chunk, width)
        d = self._samples.get(key)
        if d is None:
            d = self._samples[key] = deque(maxlen=self.max_samples)
        d.append(float(seconds))
        self._counts[key] = self._counts.get(key, 0) + 1

    def shapes(self) -> List[str]:
        return sorted(self._samples)

    def stats(self, key: str) -> Optional[Dict]:
        d = self._samples.get(key)
        if not d:
            return None
        vals = sorted(d)
        return {
            "count": self._counts[key],
            "sampled": len(vals),
            "mean_s": sum(vals) / len(vals),
            "p50_s": _quantile(vals, 0.50),
            "p95_s": _quantile(vals, 0.95),
            "min_s": vals[0],
            "max_s": vals[-1],
        }

    # -- persistence -------------------------------------------------------

    def to_json(self) -> Dict:
        return {
            "version": 1,
            "max_samples_per_shape": self.max_samples,
            "meta": self.meta,
            "shapes": {
                key: {"count": self._counts[key],
                      "samples": list(self._samples[key])}
                for key in self.shapes()
            },
        }

    @classmethod
    def from_json(cls, doc: Dict) -> "TickLedger":
        led = cls(max_samples_per_shape=doc.get("max_samples_per_shape",
                                                512))
        led.meta = dict(doc.get("meta", {}))
        for key, rec in doc.get("shapes", {}).items():
            d = deque(rec["samples"], maxlen=led.max_samples)
            led._samples[key] = d
            led._counts[key] = int(rec.get("count", len(d)))
        return led

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, path: str) -> "TickLedger":
        with open(path) as f:
            return cls.from_json(json.load(f))
