"""Metrics registry — counters, gauges, and fixed-bucket histograms.

One registry instance per server (serving._GenerationServerBase owns
one); the same registry backs BOTH the JSON metrics payload
(`/v2/models/<name>/metrics` → `"histograms"`) and the Prometheus
text-exposition endpoint (`GET /metrics`, `ff_` prefix), so the two
surfaces can never disagree on a number.

Histograms are fixed-bucket (Prometheus-style cumulative `le` buckets):
observe() is a bisect + two increments — cheap enough to run
unconditionally on the decode tick path, unlike the span recorder which
is opt-in. Percentiles are estimated by linear interpolation inside the
owning bucket, the same estimate `histogram_quantile()` computes server
side in PromQL.
"""

from __future__ import annotations

import json
import re
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# exponential latency buckets, 100us .. ~100s (decode ticks sit in the
# ms band on TPU and the tens-of-ms band on the CPU test mesh)
TIME_BUCKETS_S: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    1e-1, 2.5e-1, 5e-1, 1.0, 2.5, 5.0, 10.0, 30.0, 100.0,
)
# small-count buckets (tokens emitted per tick, slots live, tree widths)
COUNT_BUCKETS: Tuple[float, ...] = (
    0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
)
# unit-interval buckets (acceptance rates, occupancies)
RATIO_BUCKETS: Tuple[float, ...] = (
    0.0, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0,
)


class Counter:
    """Monotonic counter. Name it `*_total` (Prometheus convention)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Fixed-bucket histogram with Prometheus `le` semantics: bucket i
    counts observations <= bounds[i]; one implicit +Inf bucket tails."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Sequence[float]):
        b = tuple(float(x) for x in bounds)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"bucket bounds must ascend, got {bounds}")
        self.bounds = b
        self.counts = [0] * (len(b) + 1)  # [..., +Inf]
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (q in [0,1]) by linear interpolation in
        the owning bucket; None when empty. Observations past the last
        bound clamp to it (no upper edge to interpolate toward)."""
        if self.count == 0:
            return None
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i >= len(self.bounds):
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.bounds[-1]

    def to_json(self) -> Dict:
        return {
            "buckets": list(self.bounds),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }


_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    out = _NAME_OK.sub("_", name)
    return out if not out[:1].isdigit() else "_" + out


def flatten_scalars(d: Dict, prefix: str = "") -> Dict[str, float]:
    """Flatten a nested metrics dict to {dotted_name: float}, keeping
    only numeric leaves (lists — per-request records — and None are
    skipped; bools count as 0/1)."""
    out: Dict[str, float] = {}
    for k, v in d.items():
        name = f"{prefix}{k}" if not prefix else f"{prefix}_{k}"
        if isinstance(v, dict):
            out.update(flatten_scalars(v, name))
        elif isinstance(v, bool):
            out[name] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[name] = float(v)
    return out


class MetricsRegistry:
    """Named counters/gauges/histograms; create-or-get accessors so the
    instrumentation sites stay one-liners."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = Counter()
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge()
        return g

    def histogram(self, name: str,
                  bounds: Sequence[float] = TIME_BUCKETS_S) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms[name] = Histogram(bounds)
        return h

    # -- export ----------------------------------------------------------

    def to_json(self) -> Dict:
        out: Dict = {}
        for name, c in sorted(self._counters.items()):
            out[name] = c.value
        for name, g in sorted(self._gauges.items()):
            out[name] = g.value
        for name, h in sorted(self._histograms.items()):
            out[name] = h.to_json()
        return out

    def prometheus_text(self, prefix: str = "ff_",
                        extra_scalars: Optional[Dict[str, float]] = None
                        ) -> str:
        """Prometheus text exposition (version 0.0.4). `extra_scalars`
        (e.g. the flattened server metrics dict) render as gauges —
        except `*_total`/`*_count`/counter-shaped names, which render as
        counters so scrape-side rate() works."""
        lines: List[str] = []

        def emit(name: str, kind: str, body: List[str]):
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(body)

        for name, c in sorted(self._counters.items()):
            n = prefix + _sanitize(name)
            emit(n, "counter", [f"{n} {_fmt(c.value)}"])
        for name, g in sorted(self._gauges.items()):
            n = prefix + _sanitize(name)
            emit(n, "gauge", [f"{n} {_fmt(g.value)}"])
        for name, h in sorted(self._histograms.items()):
            n = prefix + _sanitize(name)
            body = []
            cum = 0
            for bound, cnt in zip(h.bounds, h.counts):
                cum += cnt
                body.append(f'{n}_bucket{{le="{_fmt(bound)}"}} {cum}')
            cum += h.counts[-1]
            body.append(f'{n}_bucket{{le="+Inf"}} {cum}')
            body.append(f"{n}_sum {_fmt(h.sum)}")
            body.append(f"{n}_count {h.count}")
            emit(n, "histogram", body)
        for name, v in sorted((extra_scalars or {}).items()):
            n = prefix + _sanitize(name)
            kind = ("counter" if n.endswith(("_total", "_served", "_steps",
                                            "_ticks", "_tokens", "_hits",
                                            "_misses", "_evictions"))
                    else "gauge")
            emit(n, kind, [f"{n} {_fmt(v)}"])
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    """Shortest faithful float rendering (ints stay integral)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def registry_json_roundtrips(reg: MetricsRegistry) -> bool:
    """Debug helper: the JSON export must be json-serializable."""
    json.dumps(reg.to_json())
    return True
