"""reqlog — bounded per-request flight recorder with JSONL replay.

The span recorder (obs.trace) answers "what did the tick loop do";
this module answers "what did the SERVER serve": one record per
completed request carrying its lifecycle timestamps (arrival /
admission / first token / finish, the same monotonic clock the spans
stamp), the prompt's LENGTH and content-hash prefix chain (never the
raw tokens — the chain is the paged pool's sha1 page-block chain, so
two records share a chain prefix iff their prompts shared those
pages), sampling params, the pool's kv dtype, speculative
proposed/accepted counts, preemptions and peak pages held, and a
per-phase queue/prefill/decode breakdown derived from the stamps.

Cheap enough to leave ON in production: one dict append per COMPLETED
request (nothing per tick), bounded by a ring. The disabled path is a
true no-op like `obs.span`: `request_log(0)` returns the shared falsy
`NULL_REQLOG` singleton, and call sites guard record construction with
`if rl:` so a disabled server allocates nothing.

The JSONL export is the replay substrate: `tools/servesearch.py search
--replay log.jsonl` prices strategies against the RECORDED traffic
(search/traffic.py RecordedProfile), and `tools/fftrace.py replay`
re-serves it and reports recorded-vs-replayed deltas.
"""

from __future__ import annotations

import gzip
import json
import threading
from collections import deque
from typing import Iterable, Iterator, List, Optional

# bump when a record's field set changes incompatibly; the JSONL header
# line carries it so a replay of a future log fails loudly, not subtly
SCHEMA = "ff.reqlog/v1"

DEFAULT_CAPACITY = 4096


class BoundedRing:
    """THE bounded-retention code path: a keep-newest ring that COUNTS
    what it drops. Shared by the server's per-request metric records
    (`request_record_limit`) and the reqlog ring, and the drop counters
    ride the /v2 metrics payload — silent truncation is visible.

    Internally locked: appends happen on the serving loop thread while
    snapshots run on scrape/router threads, and iterating a deque that
    another thread is appending to raises RuntimeError (racecheck's
    router-vs-reqlog finding). Readers get a consistent list copy."""

    __slots__ = ("_ring", "_lock", "dropped")

    def __init__(self, capacity: int):
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.dropped = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def append(self, item) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(item)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def __iter__(self) -> Iterator:
        return iter(self.snapshot())

    def snapshot(self) -> List:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int) -> List:
        if n <= 0:
            return []
        with self._lock:
            return list(self._ring)[-n:]


class _NullRequestLog:
    """Falsy no-op stand-in when request logging is disabled — shared
    singleton, so the disabled path allocates nothing (the tracemalloc
    guard in tests/test_obs.py holds this to account)."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def log(self, record) -> None:
        pass

    @property
    def dropped(self) -> int:
        return 0

    @property
    def capacity(self) -> int:
        return 0

    def __len__(self) -> int:
        return 0

    def records(self) -> List[dict]:
        return []

    def tail(self, n: int) -> List[dict]:
        return []

    def export_jsonl(self, path: str) -> int:
        return 0


NULL_REQLOG = _NullRequestLog()


class RequestLog:
    """Bounded flight recorder of completed-request records. Appends
    happen on the serving loop thread; snapshots/export may run on any
    thread — the BoundedRing is internally locked, so readers always
    see a consistent list copy."""

    __slots__ = ("_ring",)

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._ring = BoundedRing(capacity)

    def __bool__(self) -> bool:
        return True

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        return self._ring.dropped

    @property
    def capacity(self) -> int:
        return self._ring.capacity

    def log(self, record: dict) -> None:
        self._ring.append(record)

    def records(self) -> List[dict]:
        return self._ring.snapshot()

    def tail(self, n: int) -> List[dict]:
        return self._ring.tail(n)

    def export_jsonl(self, path: str) -> int:
        """Write the retained records as JSONL (a schema header line,
        then one record per line); returns the record count."""
        return dump_jsonl(path, self.records())


def request_log(capacity: Optional[int]):
    """Factory mirroring `obs.span`'s null discipline: a live
    RequestLog, or the shared falsy NULL_REQLOG when `capacity` is 0
    (None means the default capacity)."""
    if capacity is None:
        return RequestLog(DEFAULT_CAPACITY)
    capacity = int(capacity)
    if capacity == 0:
        return NULL_REQLOG
    return RequestLog(capacity)


def _open(path: str, mode: str):
    if str(path).endswith(".gz"):
        return gzip.open(path, mode + "t")
    return open(path, mode)


def dump_jsonl(path: str, records: Iterable[dict]) -> int:
    """Export records to JSONL (gz-aware): first line is the schema
    header, each following line one record. Returns the record count."""
    n = 0
    with _open(path, "w") as f:
        f.write(json.dumps({"schema": SCHEMA}) + "\n")
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            n += 1
    return n


def load_jsonl(path: str) -> List[dict]:
    """Import a reqlog JSONL export (gz-aware). Tolerates a missing
    header (hand-built fixtures) but refuses a FOREIGN schema — a trace
    or metrics file fed to --replay should fail with a name, not price
    garbage."""
    out: List[dict] = []
    with _open(path, "r") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if i == 0 and "schema" in doc and "submit_ns" not in doc:
                if doc["schema"] != SCHEMA:
                    raise ValueError(
                        f"{path}: schema {doc['schema']!r} is not {SCHEMA!r}")
                continue
            out.append(doc)
    return out
