"""slo — live SLO monitoring over the request log, with breach capture.

An `SLOTarget` declares the latency contract a serving config was
picked for (the same quantities the serving-strategy search optimizes:
TTFT p95 and decode seconds per token — docs/search.md). The
`SLOMonitor` folds every completed request's reqlog record
(obs.reqlog) into sliding windows, maintains the window percentiles
and a GOODPUT ratio (the fraction of windowed requests that met every
declared target individually), and latches breach state: the first
record that tips a window percentile over its target is a breach
EVENT (counted once per excursion, `ff_slo_breaches_total`), and the
monitor stays "breached" until the window percentile recovers.

A breach event triggers the flight-recorder dump: the last-N reqlog
records, the span recorder's Chrome-trace tail (when `obs.enable()` is
live), and a full metrics snapshot, bundled into
`<dump_dir>/breach_NNNN/` — the post-incident artifact an operator
reads instead of reproducing the traffic.

Percentiles are NEAREST-RANK (ceil(q*n)-th of the sorted window) so a
breach test can hand-compute the exact trip point.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from collections import deque
from typing import Callable, List, Optional

from flexflow_tpu.obs import reqlog as _reqlog

# reqlog records / trace events a breach bundle keeps — a tail, not the
# whole ring, so dumps stay small enough to attach to an incident
DUMP_REQLOG_TAIL = 64
DUMP_TRACE_TAIL = 2048


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile of `values` (q in [0, 1])."""
    if not values:
        return 0.0
    vals = sorted(values)
    rank = max(1, math.ceil(len(vals) * q))
    return vals[min(rank, len(vals)) - 1]


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """The declared contract plus the window the monitor judges it
    over. Either latency target may be None (not declared — never
    breaches on that axis); at least one must be set.

    ttft_p95_s: windowed p95 of per-request TTFT (submit -> first
      token) must stay at or under this.
    s_per_token_p95: windowed p95 of per-request decode seconds per
      generated token must stay at or under this.
    window: completed requests the sliding window holds.
    min_samples: breach checks start only once the window has this
      many records (a single cold-start request is not an incident).
    """

    ttft_p95_s: Optional[float] = None
    s_per_token_p95: Optional[float] = None
    window: int = 256
    min_samples: int = 8

    def __post_init__(self):
        if self.ttft_p95_s is None and self.s_per_token_p95 is None:
            raise ValueError(
                "SLOTarget declares no target: set ttft_p95_s and/or "
                "s_per_token_p95")
        if self.window < 1 or self.min_samples < 1:
            raise ValueError("window and min_samples must be >= 1")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "SLOTarget":
        return cls(**doc)


def _ttft_s(record: dict) -> float:
    return max(0.0, (record["first_token_ns"] - record["submit_ns"]) / 1e9)


def _s_per_token(record: dict) -> float:
    decode_s = max(0.0, (record["done_ns"] - record["first_token_ns"]) / 1e9)
    return decode_s / max(1, int(record.get("decode_tokens", 1)))


class SLOMonitor:
    """Sliding-window SLO judge fed one reqlog record per completed
    request (from the serving loop thread; snapshots may run on any
    thread under the same relaxed-read discipline as the metrics)."""

    def __init__(self, target: SLOTarget, dump_dir: Optional[str] = None):
        if isinstance(target, dict):
            target = SLOTarget.from_json(target)
        self.target = target
        self.dump_dir = dump_dir
        self._ttft: deque = deque(maxlen=target.window)
        self._spt: deque = deque(maxlen=target.window)
        self._ok: deque = deque(maxlen=target.window)  # per-request pass
        self.samples = 0
        self.breaches = 0
        self.breached = False
        self.goodput = 1.0
        self.last_dump: Optional[str] = None

    def observe(self, record: dict) -> bool:
        """Fold one completed-request record in; returns True exactly
        when this record TRIPS a breach (ok -> breached transition) —
        the caller counts it and captures the dump."""
        t = self.target
        ttft = _ttft_s(record)
        spt = _s_per_token(record)
        self._ttft.append(ttft)
        self._spt.append(spt)
        ok = ((t.ttft_p95_s is None or ttft <= t.ttft_p95_s)
              and (t.s_per_token_p95 is None or spt <= t.s_per_token_p95))
        self._ok.append(ok)
        self.samples += 1
        self.goodput = sum(self._ok) / len(self._ok)
        if len(self._ttft) < t.min_samples:
            return False
        over = False
        if t.ttft_p95_s is not None:
            over = over or percentile(list(self._ttft), 0.95) > t.ttft_p95_s
        if t.s_per_token_p95 is not None:
            over = over or percentile(list(self._spt), 0.95) > t.s_per_token_p95
        tripped = over and not self.breached
        self.breached = over
        if tripped:
            self.breaches += 1
        return tripped

    def snapshot(self) -> dict:
        return {
            "target": self.target.to_json(),
            "samples": self.samples,
            "window_samples": len(self._ttft),
            "ttft_p95_s": percentile(list(self._ttft), 0.95),
            "s_per_token_p95": percentile(list(self._spt), 0.95),
            "goodput_ratio": self.goodput,
            "breaches": self.breaches,
            "breached": self.breached,
            "last_dump": self.last_dump,
        }

    # -- breach capture --------------------------------------------------

    def dump(self, reqlog=None, recorder=None,
             metrics: Optional[Callable[[], dict]] = None,
             strategy: Optional[dict] = None,
             compile_snapshot: Optional[dict] = None) -> Optional[str]:
        """Bundle the flight-recorder state into
        `<dump_dir>/breach_NNNN/`: the reqlog tail (JSONL), the span
        recorder's Chrome-trace tail (when one is live), the server
        metrics snapshot, and this monitor's own snapshot — plus, when
        the caller passes them, the active ServeStrategy JSON
        (`strategy.json`) and a compile-tracker snapshot
        (`compile.json`), so the bundle says WHAT configuration was
        breaching and whether recompiles were part of it. Returns the
        bundle dir (None when no dump_dir is configured). Capture must
        never take the server down: a failing snapshot is recorded as
        an error entry in the bundle, not raised into the loop."""
        if not self.dump_dir:
            return None
        bundle = os.path.join(self.dump_dir, f"breach_{self.breaches:04d}")
        os.makedirs(bundle, exist_ok=True)
        tail = reqlog.tail(DUMP_REQLOG_TAIL) if reqlog else []
        _reqlog.dump_jsonl(os.path.join(bundle, "reqlog_tail.jsonl"), tail)
        if recorder is not None:
            doc = recorder.chrome_trace()
            ev = doc.get("traceEvents", [])
            meta = [e for e in ev if e.get("ph") == "M"]
            rest = [e for e in ev if e.get("ph") != "M"]
            doc["traceEvents"] = meta + rest[-DUMP_TRACE_TAIL:]
            with open(os.path.join(bundle, "trace_tail.json"), "w") as f:
                json.dump(doc, f)
        if metrics is not None:
            try:
                snap = metrics()
            except Exception as e:  # capture, don't crash the loop
                snap = {"error": f"{type(e).__name__}: {e}"}
            with open(os.path.join(bundle, "metrics.json"), "w") as f:
                json.dump(snap, f, indent=1, sort_keys=True, default=str)
        if strategy is not None:
            with open(os.path.join(bundle, "strategy.json"), "w") as f:
                json.dump(strategy, f, indent=1, sort_keys=True)
        if compile_snapshot is not None:
            with open(os.path.join(bundle, "compile.json"), "w") as f:
                json.dump(compile_snapshot, f, indent=1, sort_keys=True,
                          default=str)
        with open(os.path.join(bundle, "slo.json"), "w") as f:
            json.dump(self.snapshot(), f, indent=1, sort_keys=True)
        self.last_dump = bundle
        return bundle
