"""Span recorder — thread-aware, monotonic-clock tracing for the
serving tick loop ("fftrace").

Design constraints, in order:

  1. TRUE NO-OP WHEN DISABLED. `obs.span(name)` returns one shared
     `_NULL_SPAN` singleton when no recorder is installed: no object is
     allocated per call, `with` enter/exit touch nothing, and the span
     is falsy so call sites guard their attribute computation
     (`if sp: sp.set(live=...)`) — the attrs dict is never even built.
     The decode tick path pays one module-global load + `is None` test.
  2. One clock. Spans stamp `time.monotonic_ns()`; request lifecycle
     events convert the `time.monotonic()` stamps _GenRequest already
     carries — same clock, so tick spans and request tracks line up in
     Perfetto without skew correction.
  3. Correlate with device traces. When enabled (and jax is importable)
     each span also enters `jax.profiler.TraceAnnotation(name)`, so a
     jax-profiler/XLA capture taken over the same window carries the
     host span names alongside the `jax.named_scope` Node.stable_key()
     metadata the executor stamps into HLO (see analysis/hloaudit.py) —
     one vocabulary from scheduler tick down to fused kernel.

Export is Chrome-trace/Perfetto `trace_event` JSON: tick-phase spans as
complete ("X") events on their thread's track, per-request lifecycle as
queued/prefill/decode "X" events on one synthetic track per request
(pid 2), thread/process names as "M" metadata events.
"""

from __future__ import annotations

import gzip
import json
import threading
import time
from typing import Dict, List, Optional

from flexflow_tpu.obs.ledger import TickLedger


class _NullSpan:
    """Falsy no-op span: the disabled-path singleton."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False

    def set(self, **attrs):
        return None


NULL_SPAN = _NullSpan()


class Span:
    """One live span; created only when a recorder is installed."""

    __slots__ = ("_rec", "name", "attrs", "_t0", "_tid", "_ann")

    def __init__(self, rec: "TraceRecorder", name: str):
        self._rec = rec
        self.name = name
        self.attrs: Optional[Dict] = None
        self._t0 = 0
        self._tid = 0
        self._ann = None

    def __bool__(self):
        return True

    def set(self, **attrs) -> "Span":
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def __enter__(self):
        self._tid = threading.get_ident()
        ann_cls = self._rec._annotation
        if ann_cls is not None:
            try:
                self._ann = ann_cls(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        self._rec._finish(self.name, self._t0, t1 - self._t0, self._tid,
                          self.attrs)
        return False


class TraceRecorder:
    """Collects span events in memory (bounded), owns the TickLedger,
    and exports Chrome-trace JSON. Appends happen from the scheduler
    thread while readers may export from another — all mutation is
    list.append / int adds, safe under the GIL, and export snapshots
    with list() first."""

    def __init__(self, max_events: int = 200_000,
                 annotate_device: bool = True):
        self.max_events = int(max_events)
        # (name, ts_ns, dur_ns, tid, attrs) complete events
        self.events: List[tuple] = []
        self.dropped = 0
        # (rid, label, submit_ns, admit_ns, first_ns, done_ns, attrs)
        self.requests: List[tuple] = []
        self._req_seq = 0
        self.ledger = TickLedger()
        self.t0_ns = time.monotonic_ns()
        self._annotation = None
        if annotate_device:
            try:
                import jax

                self._annotation = jax.profiler.TraceAnnotation
            except Exception:
                self._annotation = None

    # -- recording -------------------------------------------------------

    def span(self, name: str) -> Span:
        return Span(self, name)

    def _finish(self, name, t0, dur, tid, attrs):
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append((name, t0, dur, tid, attrs))

    def instant(self, name: str, **attrs):
        if len(self.events) < self.max_events:
            self.events.append((name, time.monotonic_ns(), 0,
                                threading.get_ident(), attrs or None))

    def record_request(self, submit_t: float, admit_t: Optional[float],
                       first_token_t: Optional[float], done_t: float,
                       label: str = "", attrs: Optional[Dict] = None
                       ) -> int:
        """One completed request's lifecycle from the monotonic-seconds
        stamps _GenRequest carries: queued [submit→admit], prefill
        [admit→first token], decode [first token→done]. Missing stamps
        collapse their phase to zero width at the next known edge."""
        self._req_seq += 1
        rid = self._req_seq
        to_ns = lambda s: int(s * 1e9)  # noqa: E731 — same monotonic clock
        admit = admit_t if admit_t is not None else done_t
        first = first_token_t if first_token_t is not None else done_t
        self.requests.append((rid, label or f"req {rid}", to_ns(submit_t),
                              to_ns(admit), to_ns(first), to_ns(done_t),
                              attrs))
        return rid

    # -- export ----------------------------------------------------------

    @staticmethod
    def _us(ns: int) -> float:
        return ns / 1e3

    def chrome_trace(self) -> Dict:
        """`trace_event` JSON: pid 1 = tick loop threads, pid 2 = one
        synthetic track per request. Loads in chrome://tracing and
        https://ui.perfetto.dev unmodified."""
        ev: List[Dict] = [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "fftrace: tick loop"}},
            {"ph": "M", "name": "process_name", "pid": 2,
             "args": {"name": "fftrace: requests"}},
        ]
        tids = set()
        for name, t0, dur, tid, attrs in list(self.events):
            tids.add(tid)
            e = {"name": name, "ph": "X", "cat": "tick", "pid": 1,
                 "tid": tid, "ts": self._us(t0 - self.t0_ns),
                 "dur": self._us(dur)}
            if attrs:
                e["args"] = attrs
            ev.append(e)
        for tid in sorted(tids):
            ev.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": tid, "args": {"name": f"loop thread {tid}"}})
        for rid, label, sub, adm, first, done, attrs in list(self.requests):
            ev.append({"ph": "M", "name": "thread_name", "pid": 2,
                       "tid": rid, "args": {"name": label}})
            for phase, a, b in (("queued", sub, adm),
                                ("prefill", adm, first),
                                ("decode", first, done)):
                e = {"name": phase, "ph": "X", "cat": "request", "pid": 2,
                     "tid": rid, "ts": self._us(a - self.t0_ns),
                     "dur": self._us(max(b - a, 0))}
                if phase == "decode" and attrs:
                    e["args"] = attrs
                ev.append(e)
        ev.sort(key=lambda e: (e.get("ts", -1.0), e["pid"]))
        return {"traceEvents": ev, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped}}

    def export_chrome_trace(self, path: str) -> str:
        """Write the trace JSON (gzipped when `path` ends in .gz)."""
        doc = self.chrome_trace()
        if path.endswith(".gz"):
            with gzip.open(path, "wt") as f:
                json.dump(doc, f)
        else:
            with open(path, "w") as f:
                json.dump(doc, f)
        return path
