"""Operator library.

Each operator is (1) a frozen attrs dataclass owning shape inference, weight
declaration, and FLOP/byte accounting (`flexflow_tpu.ops.attrs`), and (2) a
registered JAX lowering (`flexflow_tpu.ops.jax_ops`) that turns the op into
XLA HLO (or a Pallas kernel for the hot paths).

Reference analog: `src/ops/*` Op subclasses + `src/ops/kernels/*` CUDA/HIP
kernels (SURVEY.md §2.2). The Legion launch boilerplate disappears: lowering
happens inside one traced function; the `Params` structs' role (hashable op
descriptors for node dedup + cost cache keys) is played by the frozen attrs.
"""

from flexflow_tpu.ops.base import OpAttrs, WeightSpec
from flexflow_tpu.ops import attrs
from flexflow_tpu.ops.registry import get_lowering, register_lowering

__all__ = ["OpAttrs", "WeightSpec", "attrs", "get_lowering", "register_lowering"]
