"""Attribute dataclasses for every operator (shape inference + weights + FLOPs).

Covers the reference op inventory (SURVEY.md §2.2, src/ops/*) plus TPU-native
additions (RMSNorm, RingAttention). Shapes are numpy-ordered (dim 0 = batch);
degree/axes of sharded dims propagate through inference wherever an output
dim corresponds one-to-one to an input dim (the role of the reference's
ParallelDimMappingRecords, operator.h:22-49).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

from flexflow_tpu.ffconst import ActiMode, AggrMode, DataType, PoolType
from flexflow_tpu.ops.base import (
    OpAttrs,
    WeightSpec,
    broadcast_dims,
    elementwise_like,
    fresh,
)
from flexflow_tpu.pcg.tensor import ParallelDim, ParallelTensorShape, TensorShape

Shape = ParallelTensorShape


def _carry(dim: ParallelDim, size: Optional[int] = None) -> ParallelDim:
    """Copy a dim's sharding onto a (possibly resized) output dim; drops the
    sharding if the new size is not divisible by the degree."""
    size = dim.size if size is None else size
    if size % dim.degree == 0:
        return ParallelDim(size, dim.degree, dim.axes)
    return ParallelDim(size)


# ---------------------------------------------------------------------------
# sources


@dataclasses.dataclass(frozen=True)
class InputAttrs(OpAttrs):
    """PCG source node for a user input (reference NoOp/Input, noop.cc)."""

    shape: TensorShape

    def infer(self, *ins):
        return (ParallelTensorShape.from_shape(self.shape),)


@dataclasses.dataclass(frozen=True)
class WeightAttrs(OpAttrs):
    """PCG source node for a standalone weight (reference create_weight)."""

    shape: TensorShape
    initializer: str = "glorot_uniform"

    def infer(self, *ins):
        return (ParallelTensorShape.from_shape(self.shape),)

    def weights(self, *ins):
        return {"weight": WeightSpec(self.shape, self.initializer)}


@dataclasses.dataclass(frozen=True)
class NoOpAttrs(OpAttrs):
    def infer(self, *ins):
        return (elementwise_like(ins[0]),)


# ---------------------------------------------------------------------------
# dense / conv / embedding


@dataclasses.dataclass(frozen=True)
class LinearAttrs(OpAttrs):
    """Dense layer (reference src/ops/linear.cc): y = act(x @ W + b).

    x: (..., in_dim) -> y: (..., out_dim); W: (in_dim, out_dim), b: (out_dim,).
    Parallelizable on batch dims (data), out_dim (parameter/TP column), and
    in_dim with a Reduction afterwards (TP row) — the degree mappings the
    reference builds in LinearParams::construct_mappings (linear.cc:1095).
    """

    out_dim: int
    use_bias: bool = True
    activation: ActiMode = ActiMode.NONE
    dtype: Optional[DataType] = None

    def infer(self, x: Shape):
        out_dims = tuple(_carry(d) for d in x.dims[:-1]) + (ParallelDim(self.out_dim),)
        return (Shape(out_dims, self.dtype or x.dtype, x.replica),)

    def weights(self, x: Shape):
        in_dim = x.dims[-1].size
        w = {"kernel": WeightSpec(TensorShape((in_dim, self.out_dim), x.dtype))}
        if self.use_bias:
            w["bias"] = WeightSpec(TensorShape((self.out_dim,), x.dtype), "zeros")
        return w

    def flops(self, ins, outs):
        x = ins[0]
        batch = math.prod(d.size for d in x.dims[:-1])
        return 2 * batch * x.dims[-1].size * self.out_dim


@dataclasses.dataclass(frozen=True)
class Conv2DAttrs(OpAttrs):
    """2-D convolution, NCHW (reference src/ops/conv_2d.cc; lowered to
    lax.conv_general_dilated on TPU)."""

    out_channels: int
    kernel: Tuple[int, int]
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    groups: int = 1
    use_bias: bool = True
    activation: ActiMode = ActiMode.NONE

    def infer(self, x: Shape):
        n, c, h, w = (d.size for d in x.dims)
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        dims = (
            _carry(x.dims[0]),
            ParallelDim(self.out_channels),
            ParallelDim(oh),
            ParallelDim(ow),
        )
        return (Shape(dims, x.dtype, x.replica),)

    def weights(self, x: Shape):
        cin = x.dims[1].size
        w = {
            "kernel": WeightSpec(
                TensorShape(
                    (self.out_channels, cin // self.groups, *self.kernel), x.dtype
                )
            )
        }
        if self.use_bias:
            w["bias"] = WeightSpec(TensorShape((self.out_channels,), x.dtype), "zeros")
        return w

    def flops(self, ins, outs):
        x, y = ins[0], outs[0]
        cin = x.dims[1].size
        per_out = 2 * cin // self.groups * self.kernel[0] * self.kernel[1]
        return per_out * y.to_shape().num_elements()


@dataclasses.dataclass(frozen=True)
class EmbeddingAttrs(OpAttrs):
    """Embedding lookup (reference src/ops/embedding.cc). Input int ids
    (batch, bag); NONE -> (batch, bag, out_dim); SUM/AVG pool the bag dim ->
    (batch, out_dim)."""

    num_entries: int
    out_dim: int
    aggr: AggrMode = AggrMode.NONE
    dtype: DataType = DataType.FLOAT

    def infer(self, x: Shape):
        if self.aggr == AggrMode.NONE:
            dims = tuple(_carry(d) for d in x.dims) + (ParallelDim(self.out_dim),)
        else:
            dims = tuple(_carry(d) for d in x.dims[:-1]) + (ParallelDim(self.out_dim),)
        return (Shape(dims, self.dtype, x.replica),)

    def weights(self, x: Shape):
        return {
            "kernel": WeightSpec(
                TensorShape((self.num_entries, self.out_dim), self.dtype), "normal"
            )
        }

    def flops(self, ins, outs):
        return outs[0].to_shape().num_elements()


@dataclasses.dataclass(frozen=True)
class BatchMatmulAttrs(OpAttrs):
    """(b..., m, k) @ (b..., k, n) (reference src/ops/batch_matmul.cc).
    a_seq_length_dim/b_seq_length_dim support iteration-config truncation."""

    a_seq_length_dim: int = -1
    b_seq_length_dim: int = -1

    def infer(self, a: Shape, b: Shape):
        if a.ndim != b.ndim or a.ndim < 2:
            raise ValueError(f"batch_matmul rank mismatch: {a} vs {b}")
        if a.dims[-1].size != b.dims[-2].size:
            raise ValueError(f"batch_matmul inner dim mismatch: {a} vs {b}")
        dims = tuple(_carry(d) for d in a.dims[:-1]) + (_carry(b.dims[-1]),)
        return (Shape(dims, a.dtype, a.replica),)

    def flops(self, ins, outs):
        a, b = ins
        batch = math.prod(d.size for d in a.dims[:-2])
        return 2 * batch * a.dims[-2].size * a.dims[-1].size * b.dims[-1].size


# ---------------------------------------------------------------------------
# recurrent


@dataclasses.dataclass(frozen=True)
class LSTMAttrs(OpAttrs):
    """Single-layer LSTM over a full sequence (capability analog of the
    reference's legacy NMT LSTM node, nmt/rnn.h:161 add_lstm_node — which
    unrolls one CUDA node per LSTM_PER_NODE_LENGTH timesteps; on TPU the
    whole sequence is one lax.scan with the input projection hoisted into a
    single MXU matmul).

    Inputs: x (batch, seq, in_dim) [, h0 (batch, hidden), c0 (batch, hidden)].
    Outputs: y (batch, seq, hidden), h_n (batch, hidden), c_n (batch, hidden).
    Gate order i,f,g,o matches torch.nn.LSTM's weight layout (wx/wh are its
    weight_ih/weight_hh transposed, bias = b_ih + b_hh). Batch dim shards on
    the data axis; the sequence dim is the recurrence and never shards.
    """

    hidden: int
    use_bias: bool = True
    reverse: bool = False

    def infer(self, x: Shape, h0: Optional[Shape] = None,
              c0: Optional[Shape] = None):
        if x.ndim != 3:
            raise ValueError(f"lstm expects (batch, seq, in_dim), got {x}")
        for nm, st in (("h0", h0), ("c0", c0)):
            if st is None:
                continue
            if st.ndim != 2 or st.dims[0].size != x.dims[0].size \
                    or st.dims[1].size != self.hidden:
                raise ValueError(
                    f"lstm initial state {nm} must be (batch={x.dims[0].size},"
                    f" hidden={self.hidden}), got {st}"
                )
        b, s = x.dims[0], x.dims[1]
        h = ParallelDim(self.hidden)
        y = Shape((_carry(b), ParallelDim(s.size), h), x.dtype, x.replica)
        state = Shape((_carry(b), h), x.dtype, x.replica)
        return (y, state, state)

    def weights(self, x: Shape, *state):
        in_dim = x.dims[-1].size
        w = {
            "wx": WeightSpec(TensorShape((in_dim, 4 * self.hidden), x.dtype)),
            "wh": WeightSpec(TensorShape((self.hidden, 4 * self.hidden), x.dtype)),
        }
        if self.use_bias:
            w["bias"] = WeightSpec(TensorShape((4 * self.hidden,), x.dtype), "zeros")
        return w

    def flops(self, ins, outs):
        x = ins[0]
        b, s, d = (dim.size for dim in x.dims)
        return 2 * b * s * 4 * self.hidden * (d + self.hidden)


# ---------------------------------------------------------------------------
# attention


@dataclasses.dataclass(frozen=True)
class MultiHeadAttentionAttrs(OpAttrs):
    """Multi-head attention (reference src/ops/attention.cc — cuDNN
    multiHeadAttn; here lowered to fused einsum/flash attention).

    Inputs q, k, v: (batch, seq, embed). Weights packed per-head like the
    reference's {num_heads, qkvo} layout so head-parallelism ("attribute
    parallelism", attention.cc:210-230) shards one weight dim.
    GQA (kv_heads < num_heads) and causal masking are TPU-native extensions
    needed for the Llama family.
    """

    embed_dim: int
    num_heads: int
    kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    causal: bool = False
    use_bias: bool = False
    dropout: float = 0.0
    # rotary position embeddings (TPU-native addition for the Llama family)
    rope: bool = False
    rope_theta: float = 10000.0

    @property
    def kdim(self) -> int:
        return self.head_dim or self.embed_dim // self.num_heads

    @property
    def num_kv(self) -> int:
        return self.kv_heads or self.num_heads

    def infer(self, q: Shape, k: Shape = None, v: Shape = None):
        dims = tuple(_carry(d) for d in q.dims[:-1]) + (ParallelDim(self.embed_dim),)
        return (Shape(dims, q.dtype, q.replica),)

    def weights(self, q: Shape, k: Shape = None, v: Shape = None):
        k = k or q
        v = v or q
        dt = q.dtype
        hd = self.kdim
        w = {
            "wq": WeightSpec(TensorShape((q.dims[-1].size, self.num_heads, hd), dt)),
            "wk": WeightSpec(TensorShape((k.dims[-1].size, self.num_kv, hd), dt)),
            "wv": WeightSpec(TensorShape((v.dims[-1].size, self.num_kv, hd), dt)),
            "wo": WeightSpec(TensorShape((self.num_heads, hd, self.embed_dim), dt)),
        }
        if self.use_bias:
            w["bq"] = WeightSpec(TensorShape((self.num_heads, hd), dt), "zeros")
            w["bk"] = WeightSpec(TensorShape((self.num_kv, hd), dt), "zeros")
            w["bv"] = WeightSpec(TensorShape((self.num_kv, hd), dt), "zeros")
            w["bo"] = WeightSpec(TensorShape((self.embed_dim,), dt), "zeros")
        return w

    def flops(self, ins, outs):
        q = ins[0]
        b = q.dims[0].size
        s = q.dims[1].size
        e = q.dims[-1].size
        hd = self.kdim
        proj = 2 * b * s * e * (self.num_heads + 2 * self.num_kv + self.num_heads) * hd
        attn = 2 * 2 * b * self.num_heads * s * s * hd
        return proj + attn


@dataclasses.dataclass(frozen=True)
class RingAttentionAttrs(MultiHeadAttentionAttrs):
    """Sequence-parallel attention (net-new vs reference, SURVEY §5.7):
    identical math to MultiHeadAttention with the sequence dim sharded over
    a mesh axis. `seq_mode` picks the exchange pattern:
      - "ring":    k/v blocks rotate via ppermute, blockwise online softmax
                   overlapping compute with ICI transfer;
      - "ulysses": one all-to-all turns seq sharding into head sharding,
                   full attention runs locally, a second all-to-all turns
                   it back (DeepSpeed-Ulysses pattern)."""

    seq_mode: str = "ring"


# ---------------------------------------------------------------------------
# elementwise


@dataclasses.dataclass(frozen=True)
class ElementBinaryAttrs(OpAttrs):
    """add/sub/mul/div/max/min with numpy broadcast (reference
    src/ops/element_binary.cc)."""

    kind: str  # add|subtract|multiply|divide|max|min
    # marks an add of an absolute-position row table (GPT-2/BERT learned
    # positions): under KV-cache decode the lowering takes the table rows
    # at the cache position, and generate() guards total length against
    # the table size — an explicit graph property, not a shape heuristic
    position_table: bool = False

    def infer(self, a: Shape, b: Shape):
        out = broadcast_dims(
            tuple(d.size for d in a.dims), tuple(d.size for d in b.dims)
        )
        src = a if a.ndim >= b.ndim else b
        dims = []
        for i, size in enumerate(out):
            sd = src.dims[i]
            dims.append(_carry(sd, size) if sd.size == size else ParallelDim(size))
        return (Shape(tuple(dims), a.dtype, src.replica),)

    def flops(self, ins, outs):
        return outs[0].to_shape().num_elements()


@dataclasses.dataclass(frozen=True)
class ElementUnaryAttrs(OpAttrs):
    """exp/sin/cos/relu/gelu/sigmoid/tanh/elu/rsqrt/pow/identity and
    scalar_{add,sub,multiply,truediv} (reference src/ops/element_unary.cc);
    `scalar` feeds pow exponent / scalar operand."""

    kind: str
    scalar: float = 0.0
    inplace: bool = False

    def infer(self, x: Shape):
        return (elementwise_like(x),)

    def flops(self, ins, outs):
        return outs[0].to_shape().num_elements()


# ---------------------------------------------------------------------------
# shape ops


@dataclasses.dataclass(frozen=True)
class ReshapeAttrs(OpAttrs):
    shape: Tuple[int, ...]

    def infer(self, x: Shape):
        if math.prod(self.shape) != x.to_shape().num_elements():
            raise ValueError(f"reshape {x} -> {self.shape}: element count mismatch")
        return (fresh(self.shape, x.dtype),)


@dataclasses.dataclass(frozen=True)
class FlatAttrs(OpAttrs):
    """Flatten all non-batch dims (reference src/ops/flat.cc)."""

    def infer(self, x: Shape):
        rest = math.prod(d.size for d in x.dims[1:])
        return (Shape((_carry(x.dims[0]), ParallelDim(rest)), x.dtype, x.replica),)


@dataclasses.dataclass(frozen=True)
class TransposeAttrs(OpAttrs):
    perm: Tuple[int, ...]

    def infer(self, x: Shape):
        dims = tuple(_carry(x.dims[p]) for p in self.perm)
        return (Shape(dims, x.dtype, x.replica),)


@dataclasses.dataclass(frozen=True)
class ReverseAttrs(OpAttrs):
    axis: int

    def infer(self, x: Shape):
        return (elementwise_like(x),)


@dataclasses.dataclass(frozen=True)
class ConcatAttrs(OpAttrs):
    axis: int

    def infer(self, *ins: Shape):
        ax = self.axis % ins[0].ndim
        total = sum(s.dims[ax].size for s in ins)
        dims = []
        for i, d in enumerate(ins[0].dims):
            dims.append(ParallelDim(total) if i == ax else _carry(d))
        return (Shape(tuple(dims), ins[0].dtype, ins[0].replica),)


@dataclasses.dataclass(frozen=True)
class SplitAttrs(OpAttrs):
    sizes: Tuple[int, ...]
    axis: int

    def infer(self, x: Shape):
        ax = self.axis % x.ndim
        outs = []
        for sz in self.sizes:
            dims = tuple(
                ParallelDim(sz) if i == ax else _carry(d)
                for i, d in enumerate(x.dims)
            )
            outs.append(Shape(dims, x.dtype, x.replica))
        return tuple(outs)


@dataclasses.dataclass(frozen=True)
class CastAttrs(OpAttrs):
    dtype: DataType

    def infer(self, x: Shape):
        return (elementwise_like(x, self.dtype),)


# ---------------------------------------------------------------------------
# norm / pooling / softmax / dropout


@dataclasses.dataclass(frozen=True)
class Pool2DAttrs(OpAttrs):
    kernel: Tuple[int, int]
    stride: Tuple[int, int]
    padding: Tuple[int, int] = (0, 0)
    pool_type: PoolType = PoolType.MAX
    activation: ActiMode = ActiMode.NONE

    def infer(self, x: Shape):
        n, c, h, w = (d.size for d in x.dims)
        oh = (h + 2 * self.padding[0] - self.kernel[0]) // self.stride[0] + 1
        ow = (w + 2 * self.padding[1] - self.kernel[1]) // self.stride[1] + 1
        dims = (_carry(x.dims[0]), _carry(x.dims[1]), ParallelDim(oh), ParallelDim(ow))
        return (Shape(dims, x.dtype, x.replica),)


@dataclasses.dataclass(frozen=True)
class BatchNormAttrs(OpAttrs):
    """BatchNorm over the channel dim of NCHW (reference src/ops/batch_norm.cc).
    Running stats are non-trainable weights updated by the train step."""

    relu: bool = False
    momentum: float = 0.1
    eps: float = 1e-5

    def infer(self, x: Shape):
        return (elementwise_like(x),)

    def weights(self, x: Shape):
        c = TensorShape((x.dims[1].size,), x.dtype)
        return {
            "scale": WeightSpec(c, "ones"),
            "bias": WeightSpec(c, "zeros"),
            "running_mean": WeightSpec(c, "zeros", trainable=False),
            "running_var": WeightSpec(c, "ones", trainable=False),
        }


@dataclasses.dataclass(frozen=True)
class LayerNormAttrs(OpAttrs):
    """LayerNorm over trailing axes (reference src/ops/layer_norm.cc)."""

    axes: Tuple[int, ...] = (-1,)
    elementwise_affine: bool = True
    eps: float = 1e-5

    def infer(self, x: Shape):
        return (elementwise_like(x),)

    def weights(self, x: Shape):
        if not self.elementwise_affine:
            return {}
        norm_shape = tuple(x.dims[a].size for a in self.axes)
        return {
            "scale": WeightSpec(TensorShape(norm_shape, x.dtype), "ones"),
            "bias": WeightSpec(TensorShape(norm_shape, x.dtype), "zeros"),
        }


@dataclasses.dataclass(frozen=True)
class RMSNormAttrs(OpAttrs):
    """RMSNorm (TPU-native addition for the Llama family)."""

    eps: float = 1e-6

    def infer(self, x: Shape):
        return (elementwise_like(x),)

    def weights(self, x: Shape):
        return {"scale": WeightSpec(TensorShape((x.dims[-1].size,), x.dtype), "ones")}


@dataclasses.dataclass(frozen=True)
class SoftmaxAttrs(OpAttrs):
    axis: int = -1

    def infer(self, x: Shape):
        return (elementwise_like(x),)


@dataclasses.dataclass(frozen=True)
class DropoutAttrs(OpAttrs):
    rate: float
    seed: int = 0

    def infer(self, x: Shape):
        return (elementwise_like(x),)


# ---------------------------------------------------------------------------
# gather / reduce / topk


@dataclasses.dataclass(frozen=True)
class GatherAttrs(OpAttrs):
    """torch.gather semantics along `axis` (reference src/ops/gather.cc)."""

    axis: int

    def infer(self, x: Shape, index: Shape):
        return (Shape(tuple(_carry(d) for d in index.dims), x.dtype, x.replica),)


@dataclasses.dataclass(frozen=True)
class ReduceAttrs(OpAttrs):
    """reduce_sum / mean over axes (reference src/ops/reduce.cc, mean.cc)."""

    kind: str  # sum|mean
    axes: Tuple[int, ...]
    keepdims: bool = False

    def infer(self, x: Shape):
        for a in self.axes:
            # modulo would silently reduce the WRONG axis on out-of-range
            # input (axis 7 of a 2-D tensor -> axis 1)
            if not -x.ndim <= a < x.ndim:
                raise ValueError(
                    f"reduce axis {a} out of range for {x.ndim}-D input")
        ax = {a % x.ndim for a in self.axes}
        dims = []
        for i, d in enumerate(x.dims):
            if i in ax:
                if self.keepdims:
                    dims.append(ParallelDim(1))
            else:
                dims.append(_carry(d))
        return (Shape(tuple(dims), x.dtype, x.replica),)


@dataclasses.dataclass(frozen=True)
class TopKAttrs(OpAttrs):
    """Top-k along the last dim -> (values, indices) (reference src/ops/topk.cc)."""

    k: int
    sorted: bool = True

    def infer(self, x: Shape):
        dims = tuple(_carry(d) for d in x.dims[:-1]) + (ParallelDim(self.k),)
        return (
            Shape(dims, x.dtype, x.replica),
            Shape(dims, DataType.INT32, x.replica),
        )


# ---------------------------------------------------------------------------
# MoE ops


@dataclasses.dataclass(frozen=True)
class GroupByAttrs(OpAttrs):
    """Route tokens to per-expert buffers (reference src/ops/group_by.cc).

    Inputs: data (batch, dim), assignments (batch, k) int. Outputs: n_experts
    tensors (capacity, dim) where capacity = ceil(k*batch*alpha/n) — dense,
    capacity-dropped dispatch (TPU-native: one-hot matmul, no scatter).
    """

    n_experts: int
    alpha: float = 1.0  # capacity factor

    def capacity(self, batch: int, k: int) -> int:
        return max(1, int(math.ceil(k * batch * self.alpha / self.n_experts)))

    def infer(self, x: Shape, assign: Shape):
        batch = x.dims[0].size
        k = assign.dims[-1].size
        cap = self.capacity(batch, k)
        out = Shape((ParallelDim(cap), _carry(x.dims[-1])), x.dtype, x.replica)
        return tuple(out for _ in range(self.n_experts))


@dataclasses.dataclass(frozen=True)
class AggregateAttrs(OpAttrs):
    """Weighted combine of expert outputs (reference src/ops/aggregate.cc).

    Inputs: gate_preds (batch, k), gate_assign (batch, k), true_gate_assign
    (batch, k), gate gradients (batch, n), then n expert outputs (cap, dim).
    Output: (batch, dim). `lambda_bal` weighs the load-balancing gradient.
    """

    n_experts: int
    lambda_bal: float = 0.0

    def infer(self, *ins: Shape):
        gate_preds = ins[0]
        expert0 = ins[4]
        batch = gate_preds.dims[0].size
        dims = (_carry(gate_preds.dims[0], batch), _carry(expert0.dims[-1]))
        return (Shape(dims, expert0.dtype, expert0.replica),)


@dataclasses.dataclass(frozen=True)
class AggregateSpecAttrs(AggregateAttrs):
    """Speculative aggregate (reference src/ops/aggregate_spec.cc): outputs
    per-expert predictions stacked for replicated-label loss."""

    def infer(self, *ins: Shape):
        gate_preds = ins[0]
        expert0 = ins[4]
        batch = gate_preds.dims[0].size
        k = gate_preds.dims[-1].size
        dims = (ParallelDim(batch * k), _carry(expert0.dims[-1]))
        return (Shape(dims, expert0.dtype, expert0.replica),)


@dataclasses.dataclass(frozen=True)
class ExpertsAttrs(OpAttrs):
    """Fused expert-parallel FFN bank (TPU-native fusion of
    group_by -> per-expert dense stack -> aggregate into one op so the MoE
    hot path is a single einsum pair over an expert-sharded weight stack).

    Input: tokens (batch, dim), gate logits (batch, n_experts).
    Output: (batch, out_dim).
    """

    n_experts: int
    k: int
    hidden_dim: int
    out_dim: int
    alpha: float = 1.0
    activation: ActiMode = ActiMode.GELU
    lambda_bal: float = 1e-2
    # renormalize the top-k gate probs to sum 1 (Mixtral convention); False
    # matches the composite group_by/aggregate path, which combines with
    # raw softmax probs (reference aggregate.cc)
    normalize: bool = True
    # dispatch implementation: "sort" = token-sort + row scatter/gather
    # into a static (n*cap, d) buffer — O(tokens*dim) like the reference's
    # group_by.cu/aggregate.cu scatter kernels, the only design that
    # reaches Mixtral-scale shapes; "dense" = one-hot dispatch matmuls
    # (O(tokens*k*n*cap) fp32 mask) — kept as the numerics oracle
    dispatch: str = "sort"

    def capacity(self, batch: int) -> int:
        return max(1, int(math.ceil(self.k * batch * self.alpha / self.n_experts)))

    def infer(self, x: Shape, gate: Shape):
        dims = tuple(_carry(d) for d in x.dims[:-1]) + (ParallelDim(self.out_dim),)
        return (Shape(dims, x.dtype, x.replica),)

    def weights(self, x: Shape, gate: Shape):
        dim = x.dims[-1].size
        dt = x.dtype
        return {
            "w1": WeightSpec(TensorShape((self.n_experts, dim, self.hidden_dim), dt)),
            "w2": WeightSpec(
                TensorShape((self.n_experts, self.hidden_dim, self.out_dim), dt)
            ),
        }

    def flops(self, ins, outs):
        x = ins[0]
        tokens = math.prod(d.size for d in x.dims[:-1])
        dim = x.dims[-1].size
        return 2 * tokens * self.k * (dim * self.hidden_dim + self.hidden_dim * self.out_dim)


@dataclasses.dataclass(frozen=True)
class CacheAttrs(OpAttrs):
    """Activation cache with user score (reference src/ops/cache.cc):
    carries a non-trainable buffer of the input; the trigger/alter flow is
    handled by RecompileState in the runtime."""

    def infer(self, x: Shape):
        return (elementwise_like(x),)

    def weights(self, x: Shape):
        return {"cached": WeightSpec(x.to_shape(), "zeros", trainable=False)}


@dataclasses.dataclass(frozen=True)
class PipelineAttrs(OpAttrs):
    """Stacked transformer decoder blocks run as a GPipe pipeline.

    Fills the reference's OP_PIPELINE stub (ffconst.h / model.h:190-192 —
    enum + task IDs with no implementation) with a real TPU execution mode:
    the composite holds `layers` identical decoder blocks (RMSNorm -> GQA
    attention with RoPE -> RMSNorm -> SwiGLU MLP) with weights STACKED on a
    leading layer dim. On a mesh with a `pipe` axis the lowering runs them
    as layers/pipe_degree stages with microbatches circulating via
    lax.ppermute (parallel/pipeline.py); otherwise as a lax.scan over
    layers (layer-stacking — one compiled block instead of L copies).
    """

    layers: int
    heads: int
    kv_heads: int
    hidden: int
    n_microbatches: int = 4
    causal: bool = True
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5

    def infer(self, x: Shape):
        return (elementwise_like(x),)

    def weights(self, x: Shape):
        dim = x.dims[-1].size
        hd = dim // self.heads
        dt = x.dtype
        L = self.layers

        def w(*shape):
            return WeightSpec(TensorShape((L,) + shape, dt))

        return {
            "ln1": WeightSpec(TensorShape((L, dim), dt), "ones"),
            "wq": w(dim, self.heads, hd),
            "wk": w(dim, self.kv_heads, hd),
            "wv": w(dim, self.kv_heads, hd),
            "wo": w(self.heads, hd, dim),
            "ln2": WeightSpec(TensorShape((L, dim), dt), "ones"),
            "gate": w(dim, self.hidden),
            "up": w(dim, self.hidden),
            "down": w(self.hidden, dim),
        }

    def flops(self, ins, outs):
        x = ins[0]
        tokens = math.prod(d.size for d in x.dims[:-1])
        seq = x.dims[-2].size if x.ndim >= 2 else 1
        dim = x.dims[-1].size
        hd = dim // self.heads
        per_layer = (
            dim * self.heads * hd
            + 2 * dim * self.kv_heads * hd
            + self.heads * hd * dim
            + 3 * dim * self.hidden
        )
        dense = 2 * tokens * per_layer
        attn = 2 * tokens * seq * dim  # QK^T + PV at causal half density
        return self.layers * (dense + attn)
