"""Operator attrs base class and weight declaration.

The attrs dataclass is the hashable op descriptor — the analog of the
reference's per-op `Params` structs (model.h:676-704: used for node dedup and
as cost-cache keys). Shape inference (`infer`) replaces the output-shape
construction done in each Op subclass constructor; `weights` replaces weight
ParallelTensor creation; `flops`/`bytes_accessed` feed the cost model the way
`measure_operator_cost` fed the reference's simulator.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from flexflow_tpu.ffconst import DataType
from flexflow_tpu.pcg.tensor import ParallelDim, ParallelTensorShape, TensorShape


@dataclasses.dataclass(frozen=True)
class WeightSpec:
    """Declares one weight tensor of an op: logical shape + initializer.

    `initializer` is a default-initializer name ("glorot_uniform", "zeros",
    "ones", "normal"); FFModel layer methods may override with explicit
    Initializer objects. `trainable=False` marks running statistics
    (BatchNorm) excluded from grads but carried in the train state.
    """

    shape: TensorShape
    initializer: str = "glorot_uniform"
    trainable: bool = True


class OpAttrs:
    """Base class for operator attribute dataclasses.

    Subclasses are frozen dataclasses. Required: `infer`. Optional:
    `weights`, `flops`, `bytes_accessed`.
    """

    def infer(self, *ins: ParallelTensorShape) -> Tuple[ParallelTensorShape, ...]:
        raise NotImplementedError

    def weights(self, *ins: ParallelTensorShape) -> Dict[str, WeightSpec]:
        return {}

    def flops(self, ins, outs) -> int:
        """Forward FLOPs given input/output ParallelTensorShapes (global,
        unsharded counts; the cost model divides by parallelism)."""
        return 0

    def bytes_accessed(self, ins, outs) -> int:
        """HBM traffic estimate: read inputs + weights, write outputs."""
        total = sum(s.global_bytes() for s in ins)
        total += sum(s.global_bytes() for s in outs)
        for w in self.weights(*ins).values():
            total += w.shape.size_bytes()
        return total


def elementwise_like(s: ParallelTensorShape, dtype: Optional[DataType] = None) -> ParallelTensorShape:
    """Output shape identical to input (degrees propagate through)."""
    return dataclasses.replace(s, dtype=dtype or s.dtype)


def fresh(dims: Tuple[int, ...], dtype: DataType) -> ParallelTensorShape:
    """Unsharded shape from logical dims."""
    return ParallelTensorShape(tuple(ParallelDim(d) for d in dims), dtype)


def broadcast_dims(a: Tuple[int, ...], b: Tuple[int, ...]) -> Tuple[int, ...]:
    """Numpy broadcast of logical dims."""
    out = []
    la, lb = len(a), len(b)
    n = max(la, lb)
    for i in range(n):
        da = a[la - n + i] if la - n + i >= 0 else 1
        db = b[lb - n + i] if lb - n + i >= 0 else 1
        if da != db and da != 1 and db != 1:
            raise ValueError(f"cannot broadcast {a} with {b}")
        out.append(max(da, db))
    return tuple(out)
