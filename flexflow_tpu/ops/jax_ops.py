"""JAX lowerings for every operator.

Replaces the reference's CUDA/HIP kernel library (src/ops/kernels/*,
SURVEY.md §2.2) with XLA HLO: matmuls/convs hit the MXU via dot_general /
conv_general_dilated in the input dtype (bf16 when configured), elementwise
ops are fused by XLA, and the MoE dispatch uses dense one-hot matmuls
instead of scatter so it stays MXU-friendly. Pallas kernels for attention
live in flexflow_tpu.ops.pallas and are selected by the attention lowering
when profitable.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from flexflow_tpu.ffconst import ActiMode, AggrMode, OpType, PoolType
from flexflow_tpu.ops.registry import LowerCtx, register_lowering


def apply_activation(x, act: ActiMode):
    if act == ActiMode.NONE:
        return x
    if act == ActiMode.RELU:
        return jax.nn.relu(x)
    if act == ActiMode.SIGMOID:
        return jax.nn.sigmoid(x)
    if act == ActiMode.TANH:
        return jnp.tanh(x)
    if act == ActiMode.GELU:
        return jax.nn.gelu(x)
    if act == ActiMode.SILU:
        return jax.nn.silu(x)
    raise ValueError(f"unknown activation {act}")


# ---------------------------------------------------------------------------
# sources


@register_lowering(OpType.INPUT)
def _input(attrs, inputs, params, ctx):
    raise RuntimeError("INPUT nodes are bound by the executor, not lowered")


@register_lowering(OpType.WEIGHT)
def _weight(attrs, inputs, params, ctx):
    return [params["weight"]]


@register_lowering(OpType.NOOP)
def _noop(attrs, inputs, params, ctx):
    return [inputs[0]]


# ---------------------------------------------------------------------------
# dense / conv / embedding / matmul


@register_lowering(OpType.LINEAR)
def _linear(attrs, inputs, params, ctx):
    (x,) = inputs
    y = jnp.dot(x, params["kernel"].astype(x.dtype), preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if attrs.use_bias:
        y = y + params["bias"].astype(x.dtype)
    return [apply_activation(y, attrs.activation)]


@register_lowering(OpType.CONV2D)
def _conv2d(attrs, inputs, params, ctx):
    (x,) = inputs
    y = lax.conv_general_dilated(
        x,
        params["kernel"].astype(x.dtype),
        window_strides=attrs.stride,
        padding=[(attrs.padding[0], attrs.padding[0]), (attrs.padding[1], attrs.padding[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=attrs.groups,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    if attrs.use_bias:
        y = y + params["bias"].astype(x.dtype)[None, :, None, None]
    return [apply_activation(y, attrs.activation)]


@register_lowering(OpType.EMBEDDING)
def _embedding(attrs, inputs, params, ctx):
    (ids,) = inputs
    table = params["kernel"]
    out = jnp.take(table, ids, axis=0)
    if attrs.aggr == AggrMode.SUM:
        out = out.sum(axis=-2)
    elif attrs.aggr == AggrMode.AVG:
        out = out.mean(axis=-2)
    # masters are fp32; the op's declared dtype sets the activation dtype for
    # everything downstream (bf16 compute on the MXU)
    return [out.astype(attrs.dtype.jnp_dtype)]


@register_lowering(OpType.BATCH_MATMUL)
def _batch_matmul(attrs, inputs, params, ctx):
    a, b = inputs
    if ctx.seq_length is not None:
        # iteration-config truncation (reference a/b_seq_length_dim)
        if attrs.a_seq_length_dim >= 0:
            a = lax.slice_in_dim(a, 0, ctx.seq_length, axis=attrs.a_seq_length_dim)
        if attrs.b_seq_length_dim >= 0:
            b = lax.slice_in_dim(b, 0, ctx.seq_length, axis=attrs.b_seq_length_dim)
    y = jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
    return [y]


# ---------------------------------------------------------------------------
# attention


def apply_rope(x, theta: float, pos_offset=0):
    """Rotary position embedding, half-split (rotate_half) convention.
    x: (B, S, H, D). `pos_offset` is a scalar, a (B,) vector of per-row
    offsets (continuous-batching decode: every slot sits at its own
    absolute position), or a (B, S) matrix of ABSOLUTE per-token
    positions (speculative tree verify: sibling draft nodes share a
    depth, so the flat node axis is not a position axis).

    Angles and sin/cos are computed in fp32 (position precision), but the
    rotation itself runs in the ACTIVATION dtype: upcasting the whole
    (B,S,H,D) tensor to fp32 made the backward materialize fp32 cotangent
    converts+relayouts (~1.3 GB/step at the 1b bench config,
    tools/hlo_transpose_audit.py); rotation values are in [-1,1] so bf16
    rotation costs ~2^-8 relative error — far below bf16 matmul noise."""
    B, S, H, D = x.shape
    if D % 2 != 0:
        raise ValueError(f"RoPE requires an even head dim, got {D}")
    d2 = D // 2
    freqs = theta ** (-jnp.arange(0, d2, dtype=jnp.float32) / d2)
    off = jnp.asarray(pos_offset, jnp.float32)
    if off.ndim == 2:
        pos = off                                          # (B, S) absolute
    else:
        off = off.reshape(-1, 1)                           # (B|1, 1)
        pos = jnp.arange(S, dtype=jnp.float32)[None, :] + off  # (B|1, S)
    ang = pos[:, :, None] * freqs[None, None, :]  # (B|1, S, d2)
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                           axis=-1)


def qkv_project(x, w, dt):
    """(B,S,E) x (E,H,D) -> (B,S,H,D) through the weight's 2D [E, H*D]
    view. Contracting the 3D weight directly lets XLA's forward and
    weight-grad dots prefer DIFFERENT minor-to-major layouts for it, and
    with donated buffers that materializes per-step relayout copies of the
    parameter AND its Adam state (~2.1 GB/step measured at the 1b bench
    config, tools/hlo_transpose_audit.py); the reshape is a bitcast of the
    canonical layout, so every use agrees and the copies vanish."""
    E, H, D = w.shape
    y = jnp.einsum("bse,ef->bsf", x, w.reshape(E, H * D).astype(dt))
    return y.reshape(*x.shape[:-1], H, D)


def attn_out_project(o, w, dt):
    """(B,S,H,D) x (H,D,E) -> (B,S,E) through the [H*D, E] view (same
    layout-pinning rationale as qkv_project)."""
    H, D, E = w.shape
    return jnp.einsum("bsf,fe->bse", o.reshape(*o.shape[:-2], H * D),
                      w.reshape(H * D, E).astype(dt))


def _dot_product_attention(q, k, v, causal: bool, scale: float,
                           dropout_rate: float = 0.0, dropout_rng=None,
                           mask=None):
    """q: (B,S,H,D), k/v: (B,T,Hkv,D) -> (B,S,H,D). fp32 softmax accumulate.
    `mask` (S, T) or per-row (B, S, T) overrides the causal triangle
    (KV-cache decode passes the absolute-position mask)."""
    B, S, H, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if mask is None and causal:
        mask = jnp.tril(jnp.ones((S, T), dtype=bool))
    if mask is not None:
        m = mask[None, None] if mask.ndim == 2 else mask[:, None]
        logits = jnp.where(m, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0).astype(q.dtype)
    out = jnp.einsum("bhst,bthd->bshd", probs, v, preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _sharded_flash(q, k, v, mesh, causal, scale, interpret=False):
    """Run the Pallas flash kernel per shard under shard_map: batch stays
    sharded over `data`, heads over `model` (head-TP keeps the flash path —
    a bare pallas_call would force GSPMD to gather, VERDICT r1 weakness 3).
    The full sequence is local to every shard (seq-sharded attention goes
    through ring attention instead). GQA kv heads stay UNREPEATED when
    they divide the head axis (the kernel maps q heads onto kv heads);
    otherwise the repeat happens here so both specs shard evenly."""
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.ops.pallas import flash_attention
    from flexflow_tpu.parallel.compat import shard_map as _shard_map

    B, S, H, D = q.shape
    Hkv = k.shape[2]
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    b_ax = "data" if sizes.get("data", 1) > 1 and B % sizes["data"] == 0 else None
    h_ax = "model" if sizes.get("model", 1) > 1 and H % sizes["model"] == 0 else None
    from flexflow_tpu.parallel.comm_spec import flash_repeats_kv

    if flash_repeats_kv(H, Hkv, sizes.get("model", 1)):
        from flexflow_tpu.parallel.ring import repeat_kv

        k, v = repeat_kv(k, v, H // Hkv)
    spec = P(b_ax, None, h_ax, None)

    def fn(ql, kl, vl):
        return flash_attention(ql, kl, vl, causal=causal, scale=scale,
                               interpret=interpret)

    return _shard_map(fn, mesh, (spec, spec, spec), spec,
                      check_vma=False)(q, k, v)


def fused_attention(q, k, v, *, causal, scale, dropout=0.0, dropout_rng=None,
                    mesh=None):
    """Dispatch: Pallas flash kernel on TPU when shapes/config allow —
    wrapped in shard_map on multi-device meshes so DP/head-TP strategies
    keep the flash path — XLA dot-product attention otherwise. GQA kv
    heads reach the flash kernels unrepeated (the kernel index maps fold
    the repeat); the XLA fallback repeats internally. Sets
    LAST_ATTENTION_KERNEL for observability."""
    import os

    global LAST_ATTENTION_KERNEL

    from flexflow_tpu.ops.pallas import (
        flash_attention,
        flash_attention_available,
    )

    force_interp = os.environ.get("FF_TPU_FLASH_INTERPRET") == "1"
    single = mesh is None or getattr(mesh, "size", 1) == 1
    avail = flash_attention_available(q.shape[1], k.shape[1], dropout=dropout,
                                      interpret=force_interp)
    if avail and single:
        LAST_ATTENTION_KERNEL = "pallas_flash"
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=True if force_interp else None)
    if avail and not single:
        LAST_ATTENTION_KERNEL = "pallas_flash_shard_map"
        return _sharded_flash(q, k, v, mesh, causal, scale,
                              interpret=force_interp)
    LAST_ATTENTION_KERNEL = "xla_dot_product"
    return _dot_product_attention(q, k, v, causal, scale,
                                  dropout_rate=dropout, dropout_rng=dropout_rng)


LAST_ATTENTION_KERNEL = "none"


def cached_attention(q, k, v, cache_k, cache_v, pos, *, scale,
                     rope_theta=None):
    """Autoregressive decode/prefill step shared by MHA, ring attention,
    and the PIPELINE composite: rope at absolute positions (when
    `rope_theta`), append k/v into the cache at `pos`, attend over
    everything written so far with a causal absolute-position mask
    (slots past the write head stay masked). `pos` is a scalar for
    lockstep generate() or a (B,) vector for continuous batching (each
    slot decodes at its own depth; a freshly admitted slot's stale cache
    rows sit at kpos > qpos until overwritten).

    Returns (attention output, new k cache, new v cache)."""
    dt = q.dtype
    pos_v = jnp.asarray(pos)
    if rope_theta is not None:
        q = apply_rope(q, rope_theta, pos_offset=pos)
        k = apply_rope(k, rope_theta, pos_offset=pos)
    if pos_v.ndim == 0:
        kc = lax.dynamic_update_slice(
            cache_k, k.astype(cache_k.dtype), (0, pos, 0, 0)
        )
        vc = lax.dynamic_update_slice(
            cache_v, v.astype(cache_v.dtype), (0, pos, 0, 0)
        )
        qpos = pos + jnp.arange(q.shape[1])      # absolute q positions
        kpos = jnp.arange(kc.shape[1])           # cache slots
        mask = kpos[None, :] <= qpos[:, None]
    else:
        def write_row(cache_row, new_row, p):
            return lax.dynamic_update_slice(cache_row, new_row, (p, 0, 0))

        kc = jax.vmap(write_row)(cache_k, k.astype(cache_k.dtype), pos_v)
        vc = jax.vmap(write_row)(cache_v, v.astype(cache_v.dtype), pos_v)
        qpos = pos_v[:, None] + jnp.arange(q.shape[1])[None, :]  # (B,S)
        kpos = jnp.arange(kc.shape[1])
        mask = kpos[None, None, :] <= qpos[:, :, None]           # (B,S,T)
    out = _dot_product_attention(
        q, kc.astype(dt), vc.astype(dt), causal=False,
        scale=scale, mask=mask,
    )
    return out, kc, vc


@register_lowering(OpType.MULTIHEAD_ATTENTION)
def _mha(attrs, inputs, params, ctx):
    q_in = inputs[0]
    k_in = inputs[1] if len(inputs) > 1 else q_in
    v_in = inputs[2] if len(inputs) > 2 else k_in
    dt = q_in.dtype
    hd = attrs.kdim
    q = qkv_project(q_in, params["wq"], dt)
    k = qkv_project(k_in, params["wk"], dt)
    v = qkv_project(v_in, params["wv"], dt)
    if attrs.use_bias:
        q = q + params["bq"].astype(dt)
        k = k + params["bk"].astype(dt)
        v = v + params["bv"].astype(dt)
    if ctx.kv_cache is not None:
        if ctx.page_tables is not None:
            # every paged step — decode, chunked-prefill chunk, spec
            # tree verify — is the SAME ragged call: the cache is a
            # global page pool, this slot's rows are reached through
            # its page table, and the (q_lens, depths, anc) descriptor
            # says which of the S window rows are live and what they
            # may see (flexflow_tpu.paged.attention — one Pallas kernel
            # or the gather fallback behind one gate)
            from flexflow_tpu.paged.attention import ragged_paged_attention

            if "k_scale" in ctx.kv_cache:
                # quantized pool: the scale sidecar rides the same
                # per-node caches dict (paged/quant.py), so append
                # quantizes under grow-only scales and both attention
                # paths dequantize on load
                out, kc, vc, ks, vs = ragged_paged_attention(
                    q, k, v, ctx.kv_cache["k"], ctx.kv_cache["v"],
                    ctx.page_tables, ctx.cache_position,
                    ctx.ragged_q_lens, ctx.ragged_depths, ctx.ragged_anc,
                    scale=1.0 / (hd**0.5),
                    rope_theta=attrs.rope_theta if attrs.rope else None,
                    k_scales=ctx.kv_cache["k_scale"],
                    v_scales=ctx.kv_cache["v_scale"],
                )
                ctx.cache_updates["k_scale"] = ks
                ctx.cache_updates["v_scale"] = vs
            else:
                out, kc, vc = ragged_paged_attention(
                    q, k, v, ctx.kv_cache["k"], ctx.kv_cache["v"],
                    ctx.page_tables, ctx.cache_position,
                    ctx.ragged_q_lens, ctx.ragged_depths, ctx.ragged_anc,
                    scale=1.0 / (hd**0.5),
                    rope_theta=attrs.rope_theta if attrs.rope else None,
                )
        else:
            out, kc, vc = cached_attention(
                q, k, v, ctx.kv_cache["k"], ctx.kv_cache["v"],
                ctx.cache_position, scale=1.0 / (hd**0.5),
                rope_theta=attrs.rope_theta if attrs.rope else None,
            )
        ctx.cache_updates["k"] = kc
        ctx.cache_updates["v"] = vc
    else:
        if attrs.rope:
            q = apply_rope(q, attrs.rope_theta)
            k = apply_rope(k, attrs.rope_theta)
        drop_rng = ctx.rng if (ctx.training and attrs.dropout > 0.0) else None
        out = fused_attention(
            q, k, v, causal=attrs.causal, scale=1.0 / (hd**0.5),
            dropout=attrs.dropout if ctx.training else 0.0,
            dropout_rng=drop_rng, mesh=ctx.mesh,
        )
    y = attn_out_project(out, params["wo"], dt)
    if attrs.use_bias:
        y = y + params["bo"].astype(dt)
    return [y]


@register_lowering(OpType.RING_ATTENTION)
def _ring_attention(attrs, inputs, params, ctx):
    # Sequence-parallel lowering lives in flexflow_tpu.parallel.ring; when the
    # seq dim is unsharded this is plain attention.
    if ctx.kv_cache is not None:
        # autoregressive decode is sequential — there is no sequence to
        # shard — and ring attention's weights/math are identical to
        # MULTIHEAD_ATTENTION's, so the cached path is shared verbatim
        # (VERDICT r2 weakness 3: SP graphs previously could not decode)
        return _mha(attrs, inputs, params, ctx)
    from flexflow_tpu.parallel.ring import ring_attention_lowering

    return ring_attention_lowering(attrs, inputs, params, ctx)


# ---------------------------------------------------------------------------
# elementwise


_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "max": jnp.maximum,
    "min": jnp.minimum,
}


@register_lowering(OpType.ELEMENT_BINARY)
def _element_binary(attrs, inputs, params, ctx):
    a, b = inputs
    # learned-position tables (attrs.position_table, set by
    # add_position_embedding) under KV-cache decode: the (S, E) row table
    # adds its rows at the CURRENT cache position — prefill sees rows
    # [pos, pos+s), a single-token step its own row. An explicit graph
    # property rather than a shape heuristic: a chunked prefill starting
    # at pos>0 with chunk length == table size would fool any sniffing.
    # generate() guards total length against the table size up front
    # (dynamic_slice clamps rather than faults inside jit).
    if getattr(attrs, "position_table", False) and ctx.cache_position is not None:
        pos = jnp.asarray(ctx.cache_position)
        if pos.ndim == 0:
            rows = lax.dynamic_slice_in_dim(b, pos, a.shape[1], axis=0)
            b = rows[None]
        elif ctx.ragged_depths is not None:
            # ragged paged step: row i sits at absolute position
            # pos + depth[i] — arange for chunks/decode, node depth for
            # tree verify (sibling branches share a row of the table)
            b = b[pos[:, None] + ctx.ragged_depths]
        else:
            # continuous batching: per-row positions. S=1 is a decode
            # step; S>1 is a paged prefill CHUNK whose rows sit at
            # pos..pos+S (Executor.chunked_prefill_fn — the gather clamps
            # padded tail rows, which later writes overwrite anyway)
            rows = pos[:, None] + jnp.arange(a.shape[1])[None, :]
            b = b[rows]
    return [_BINARY[attrs.kind](a, b)]


@register_lowering(OpType.ELEMENT_UNARY)
def _element_unary(attrs, inputs, params, ctx):
    (x,) = inputs
    k, s = attrs.kind, attrs.scalar
    fns = {
        "exp": jnp.exp,
        "sin": jnp.sin,
        "cos": jnp.cos,
        "relu": jax.nn.relu,
        "gelu": jax.nn.gelu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "elu": jax.nn.elu,
        "rsqrt": lax.rsqrt,
        "silu": jax.nn.silu,
        "identity": lambda v: v,
        "pow": lambda v: jnp.power(v, s),
        "scalar_add": lambda v: v + s,
        "scalar_sub": lambda v: v - s,
        "scalar_multiply": lambda v: v * s,
        "scalar_truediv": lambda v: v / s,
    }
    return [fns[k](x)]


# ---------------------------------------------------------------------------
# shape ops


@register_lowering(OpType.RESHAPE)
def _reshape(attrs, inputs, params, ctx):
    return [inputs[0].reshape(attrs.shape)]


@register_lowering(OpType.FLAT)
def _flat(attrs, inputs, params, ctx):
    x = inputs[0]
    return [x.reshape(x.shape[0], -1)]


@register_lowering(OpType.TRANSPOSE)
def _transpose(attrs, inputs, params, ctx):
    return [jnp.transpose(inputs[0], attrs.perm)]


@register_lowering(OpType.REVERSE)
def _reverse(attrs, inputs, params, ctx):
    return [jnp.flip(inputs[0], axis=attrs.axis)]


@register_lowering(OpType.CONCAT)
def _concat(attrs, inputs, params, ctx):
    return [jnp.concatenate(inputs, axis=attrs.axis)]


@register_lowering(OpType.SPLIT)
def _split(attrs, inputs, params, ctx):
    x = inputs[0]
    outs = []
    off = 0
    for sz in attrs.sizes:
        outs.append(lax.slice_in_dim(x, off, off + sz, axis=attrs.axis))
        off += sz
    return outs


@register_lowering(OpType.CAST)
def _cast(attrs, inputs, params, ctx):
    return [inputs[0].astype(attrs.dtype.jnp_dtype)]


# ---------------------------------------------------------------------------
# norm / pool / softmax / dropout


@register_lowering(OpType.POOL2D)
def _pool2d(attrs, inputs, params, ctx):
    (x,) = inputs
    kh, kw = attrs.kernel
    sh, sw = attrs.stride
    ph, pw = attrs.padding
    window = (1, 1, kh, kw)
    strides = (1, 1, sh, sw)
    pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    if attrs.pool_type == PoolType.MAX:
        y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
        y = y.astype(x.dtype)
    else:
        s = lax.reduce_window(
            x.astype(jnp.float32), 0.0, lax.add, window, strides, pads
        )
        y = (s / (kh * kw)).astype(x.dtype)
    return [apply_activation(y, attrs.activation)]


@register_lowering(OpType.BATCH_NORM)
def _batch_norm(attrs, inputs, params, ctx):
    (x,) = inputs
    scale = params["scale"][None, :, None, None]
    bias = params["bias"][None, :, None, None]
    if ctx.training:
        xf = x.astype(jnp.float32)
        mean = xf.mean(axis=(0, 2, 3))
        var = xf.var(axis=(0, 2, 3))
        m = attrs.momentum
        ctx.state_updates["running_mean"] = (
            (1 - m) * params["running_mean"] + m * mean
        ).astype(params["running_mean"].dtype)
        ctx.state_updates["running_var"] = (
            (1 - m) * params["running_var"] + m * var
        ).astype(params["running_var"].dtype)
    else:
        mean, var = params["running_mean"], params["running_var"]
    inv = lax.rsqrt(var + attrs.eps)[None, :, None, None]
    y = (x - mean[None, :, None, None]) * inv * scale + bias
    y = y.astype(x.dtype)
    return [jax.nn.relu(y) if attrs.relu else y]


@register_lowering(OpType.LAYER_NORM)
def _layer_norm(attrs, inputs, params, ctx):
    (x,) = inputs
    axes = tuple(a % x.ndim for a in attrs.axes)
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=axes, keepdims=True)
    var = xf.var(axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + attrs.eps)
    if attrs.elementwise_affine:
        y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
    return [y.astype(x.dtype)]


@register_lowering(OpType.RMS_NORM)
def _rms_norm(attrs, inputs, params, ctx):
    (x,) = inputs
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(ms + attrs.eps) * params["scale"].astype(jnp.float32)
    return [y.astype(x.dtype)]


@register_lowering(OpType.SOFTMAX)
def _softmax(attrs, inputs, params, ctx):
    return [jax.nn.softmax(inputs[0], axis=attrs.axis)]


@register_lowering(OpType.DROPOUT)
def _dropout(attrs, inputs, params, ctx):
    (x,) = inputs
    if not ctx.training or attrs.rate == 0.0:
        return [x]
    keep = 1.0 - attrs.rate
    mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
    return [jnp.where(mask, x / keep, 0).astype(x.dtype)]


# ---------------------------------------------------------------------------
# gather / reduce / topk


@register_lowering(OpType.GATHER)
def _gather(attrs, inputs, params, ctx):
    x, idx = inputs
    return [jnp.take_along_axis(x, idx, axis=attrs.axis)]


@register_lowering(OpType.REDUCE_SUM)
def _reduce(attrs, inputs, params, ctx):
    (x,) = inputs
    fn = jnp.sum if attrs.kind == "sum" else jnp.mean
    return [fn(x, axis=attrs.axes, keepdims=attrs.keepdims)]


@register_lowering(OpType.MEAN)
def _mean(attrs, inputs, params, ctx):
    (x,) = inputs
    return [jnp.mean(x, axis=attrs.axes, keepdims=attrs.keepdims)]


@register_lowering(OpType.TOPK)
def _topk(attrs, inputs, params, ctx):
    (x,) = inputs
    vals, idx = lax.top_k(x, attrs.k)
    return [vals, idx.astype(jnp.int32)]


# ---------------------------------------------------------------------------
# recurrent


@register_lowering(OpType.LSTM)
def _lstm(attrs, inputs, params, ctx):
    """LSTM over the whole sequence (reference nmt/lstm.cu, one cuDNN node
    per timestep-block). TPU shape: the input projection x@wx for ALL
    timesteps is one big MXU matmul outside the recurrence; lax.scan carries
    only the (batch, 4*hidden) recurrent matmul. Cell state accumulates in
    fp32; gate order i,f,g,o matches torch.nn.LSTM."""
    x = inputs[0]  # (B, S, D)
    B, S, _ = x.shape
    H = attrs.hidden
    wx = params["wx"].astype(x.dtype)
    wh = params["wh"].astype(x.dtype)
    h0 = inputs[1] if len(inputs) > 1 else jnp.zeros((B, H), x.dtype)
    c0 = (inputs[2] if len(inputs) > 2 else jnp.zeros((B, H), x.dtype))
    if attrs.reverse:
        x = jnp.flip(x, axis=1)
    xg = jnp.dot(x, wx, preferred_element_type=jnp.float32).astype(x.dtype)
    if attrs.use_bias:
        xg = xg + params["bias"].astype(x.dtype)

    def step(carry, xt):
        h, c = carry  # (B,H) activation dtype, (B,H) fp32
        gates = (
            xt + jnp.dot(h, wh, preferred_element_type=jnp.float32).astype(x.dtype)
        ).astype(jnp.float32)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = (jax.nn.sigmoid(o) * jnp.tanh(c)).astype(x.dtype)
        return (h, c), h

    (h_n, c_n), ys = lax.scan(
        step, (h0, c0.astype(jnp.float32)), xg.transpose(1, 0, 2)
    )
    y = ys.transpose(1, 0, 2)
    if attrs.reverse:
        y = jnp.flip(y, axis=1)
    return [y, h_n, c_n.astype(x.dtype)]


# ---------------------------------------------------------------------------
# MoE: group_by / aggregate / fused experts
#
# TPU-native design: dense capacity-based dispatch. Scatter/gather per token
# (the reference's group_by/aggregate CUDA kernels) is replaced by one-hot
# dispatch/combine matmuls which run on the MXU and shard cleanly over an
# expert mesh axis.


def _dispatch_mask(assign, n_experts: int, capacity: int):
    """assign: (batch, k) int expert ids -> dispatch (batch, k, n_experts,
    capacity) one-hot, with tokens beyond capacity dropped (priority = batch
    order, matching the reference's sequential scan in group_by.cu)."""
    onehot = jax.nn.one_hot(assign, n_experts, dtype=jnp.float32)  # (b,k,n)
    # position of each (token, slot) within its expert queue, flattened in
    # (k-major, batch) order like the reference's linear scan
    b, k = assign.shape
    flat = onehot.transpose(1, 0, 2).reshape(b * k, n_experts)  # k-major
    pos = jnp.cumsum(flat, axis=0) - flat  # (b*k, n)
    pos = pos.reshape(k, b, n_experts).transpose(1, 0, 2)  # (b,k,n)
    keep = pos < capacity
    onehot = onehot * keep
    cap_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    return onehot[..., None] * cap_onehot  # (b,k,n,cap)


@register_lowering(OpType.GROUP_BY)
def _group_by(attrs, inputs, params, ctx):
    x, assign = inputs  # (b, d), (b, k)
    b = x.shape[0]
    k = assign.shape[-1]
    cap = attrs.capacity(b, k)
    disp = _dispatch_mask(assign, attrs.n_experts, cap)  # (b,k,n,cap)
    disp = disp.sum(axis=1)  # (b,n,cap) — a token goes to each assigned expert
    outs = jnp.einsum("bnc,bd->ncd", disp.astype(x.dtype), x)
    return [outs[i] for i in range(attrs.n_experts)]


@register_lowering(OpType.AGGREGATE)
def _aggregate(attrs, inputs, params, ctx):
    # inputs: gate_preds (b,k), gate_assign (b,k), true_gate_assign (b,k),
    # full_gate probs (b,n), expert outputs n×(cap, d)
    gate_preds, gate_assign = inputs[0], inputs[1]
    experts = jnp.stack(inputs[4:], axis=0)  # (n, cap, d)
    b, k = gate_preds.shape
    cap = experts.shape[1]
    disp = _dispatch_mask(gate_assign.astype(jnp.int32), attrs.n_experts, cap)
    # combine weights: gate prob on kept (token, expert, slot) triples
    combine = (disp * gate_preds[..., None, None].astype(jnp.float32)).sum(axis=1)
    y = jnp.einsum("bnc,ncd->bd", combine.astype(experts.dtype), experts)
    if attrs.lambda_bal > 0.0 and ctx.training:
        # load-balance gradient through the full gate distribution — the
        # reference computes this in aggregate's backward (aggregate.cu,
        # lambda_bal); functionally it is the Switch-style aux loss
        # n·Σ_e f_e·p̄_e, differentiable through inputs[3]
        full_gate = inputs[3].astype(jnp.float32)  # (b, n)
        counts = disp.sum(axis=(0, 1, 3))  # tokens kept per expert
        frac = counts / jnp.maximum(counts.sum(), 1.0)
        mean_prob = full_gate.mean(axis=0)
        ctx.state_updates["__aux_loss__"] = (
            attrs.n_experts * jnp.sum(frac * mean_prob) * attrs.lambda_bal
        )
    return [y]


@register_lowering(OpType.AGGREGATE_SPEC)
def _aggregate_spec(attrs, inputs, params, ctx):
    gate_preds, gate_assign = inputs[0], inputs[1]
    experts = jnp.stack(inputs[4:], axis=0)
    b, k = gate_preds.shape
    cap = experts.shape[1]
    disp = _dispatch_mask(gate_assign.astype(jnp.int32), attrs.n_experts, cap)
    # (b,k,n,cap) -> per-slot outputs stacked to (b*k, d)
    per_slot = jnp.einsum("bknc,ncd->bkd", disp.astype(experts.dtype), experts)
    return [per_slot.reshape(b * k, -1)]


def _sorted_dispatch(topi, t: int, n_experts: int, cap: int):
    """Token-sort dispatch plan. `topi` (t, k) int expert ids.

    Slots are prioritized in the same k-major arrival order as
    _dispatch_mask's cumsum (slot f = k_idx * t + token), so the two
    implementations drop exactly the same tokens at capacity. Returns
      slot_of_flat: (t*k,) buffer row per flat slot (n*cap = dropped)
      kept_per_expert: (n,) tokens kept per expert after capacity
    All O(t*k log(t*k)) sort work — no (t, n, cap) materialization.
    Reference analog: the sequential expert-queue scan in group_by.cu,
    re-expressed as sort + rank for a data-parallel machine."""
    k = topi.shape[1]
    flat_e = topi.astype(jnp.int32).transpose(1, 0).reshape(-1)  # k-major
    order = jnp.argsort(flat_e, stable=True)  # arrival order within expert
    sorted_e = flat_e[order]
    # rank within its expert = global sorted position - expert start
    start_of_own = jnp.searchsorted(sorted_e, sorted_e, side="left")
    pos_in_e = jnp.arange(t * k, dtype=jnp.int32) - start_of_own.astype(jnp.int32)
    valid = pos_in_e < cap
    buf_slot = jnp.where(valid, sorted_e * cap + pos_in_e, n_experts * cap)
    # invert the sort: flat slot f -> its buffer row
    slot_of_flat = jnp.zeros((t * k,), jnp.int32).at[order].set(buf_slot)
    counts = jnp.searchsorted(
        sorted_e, jnp.arange(n_experts, dtype=jnp.int32), side="right"
    ) - jnp.searchsorted(
        sorted_e, jnp.arange(n_experts, dtype=jnp.int32), side="left"
    )
    kept = jnp.minimum(counts, cap)
    return slot_of_flat, kept


@register_lowering(OpType.EXPERTS)
def _experts(attrs, inputs, params, ctx):
    """Fused MoE FFN: top-k gate -> capacity dispatch -> two-layer expert
    FFN (einsum over stacked expert weights) -> weighted combine. Auxiliary
    load-balance loss (Switch-style) is written into ctx.state_updates for
    the executor to add to the loss.

    attrs.dispatch picks the dispatch implementation:
      "sort"  (default) — argsort tokens by expert, scatter rows into a
        static (n*cap, d) buffer, gather back after the expert matmuls.
        O(tokens*dim) data movement like the reference's scatter kernels
        (group_by.cu / aggregate.cu); scales to Mixtral shapes where the
        one-hot mask alone would be GiBs.
      "dense" — one-hot dispatch/combine einsums; numerics oracle.
    """
    x, gate_logits = inputs  # (..., d), (..., n)
    orig_shape = x.shape
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    gl = gate_logits.reshape(-1, attrs.n_experts)
    t = xt.shape[0]
    probs = jax.nn.softmax(gl.astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(probs, attrs.k)  # (t,k)
    if attrs.normalize:
        topv = topv / topv.sum(axis=-1, keepdims=True)
    cap = attrs.capacity(t)
    n = attrs.n_experts

    if getattr(attrs, "dispatch", "sort") == "sort":
        slot_of_flat, kept = _sorted_dispatch(topi, t, n, cap)
        token_of_flat = jnp.tile(jnp.arange(t, dtype=jnp.int32), attrs.k)
        # scatter token rows into the expert buffer; row n*cap collects
        # dropped slots and is sliced off. (token, expert) pairs are
        # unique (top_k), so kept rows get exactly one write.
        buf = jnp.zeros((n * cap + 1, d), xt.dtype).at[slot_of_flat].set(
            xt[token_of_flat], mode="drop", unique_indices=False
        )
        buf = buf[:-1].reshape(n, cap, d)
        # expert-parallel: pin the buffer to the weights' expert axis so
        # the scatter lowers to the token all-to-all over that axis and
        # each device runs only its expert slice of the matmuls (the
        # reference's Repartition/Combine EP over NCCL, done by GSPMD)
        view = ctx.sharding
        if (ctx.mesh is not None and view is not None
                and "w1" in getattr(view, "weight_specs", {})):
            from jax.sharding import NamedSharding

            from flexflow_tpu.parallel.sharding import (
                prune_spec,
                spec_to_partition_spec,
            )

            spec = prune_spec(
                view.weight_specs["w1"][:1] + ((), ()),
                buf.shape, ctx.mesh,
            )
            buf = lax.with_sharding_constraint(
                buf, NamedSharding(ctx.mesh, spec_to_partition_spec(spec))
            )
        h = jnp.einsum("ncd,ndh->nch", buf, params["w1"].astype(xt.dtype))
        h = apply_activation(h, attrs.activation)
        o = jnp.einsum("nch,nho->nco", h, params["w2"].astype(xt.dtype))
        o_flat = jnp.concatenate(
            [o.reshape(n * cap, attrs.out_dim),
             jnp.zeros((1, attrs.out_dim), o.dtype)], axis=0
        )
        per_slot = o_flat[slot_of_flat]  # (t*k, out) — dropped slots -> 0
        w = topv.transpose(1, 0).reshape(-1, 1).astype(per_slot.dtype)
        y = (per_slot * w).reshape(attrs.k, t, attrs.out_dim).sum(axis=0)
        kept_f = kept.astype(jnp.float32)
        frac = kept_f / jnp.maximum(kept_f.sum(), 1.0)
    else:
        disp = _dispatch_mask(topi.astype(jnp.int32), n, cap)  # (t,k,n,c)
        combine = disp * topv[..., None, None]
        disp_tok = disp.sum(axis=1)  # (t,n,c)
        buf = jnp.einsum("tnc,td->ncd", disp_tok.astype(xt.dtype), xt)
        h = jnp.einsum("ncd,ndh->nch", buf, params["w1"].astype(xt.dtype))
        h = apply_activation(h, attrs.activation)
        o = jnp.einsum("nch,nho->nco", h, params["w2"].astype(xt.dtype))
        y = jnp.einsum("tknc,nco->to", combine.astype(o.dtype), o)
        frac = disp_tok.sum(axis=(0, 2)) / jnp.maximum(disp_tok.sum(), 1.0)
    # Switch-transformer load-balance aux loss: n * sum_e f_e * p_e
    mean_prob = probs.mean(axis=0)
    aux = attrs.n_experts * jnp.sum(frac * mean_prob) * attrs.lambda_bal
    ctx.state_updates["__aux_loss__"] = aux
    return [y.reshape(*orig_shape[:-1], attrs.out_dim)]


@register_lowering(OpType.CACHE)
def _cache(attrs, inputs, params, ctx):
    (x,) = inputs
    if ctx.training:
        ctx.state_updates["cached"] = x
        return [x]
    return [params["cached"]]


# ---------------------------------------------------------------------------
# pipeline composite (fills the reference's OP_PIPELINE stub — see
# ops/attrs.py PipelineAttrs and parallel/pipeline.py)


def _decoder_block(p, h, attrs, mesh=None, cache=None):
    """One llama decoder block on per-layer params `p` (matches the
    unstacked builder: rms_norm -> GQA+RoPE attention -> rms_norm ->
    SwiGLU, residuals around both halves). `mesh` must be None inside the
    GPipe shard_map worker (already device-local) and ctx.mesh on the
    fallback scan path (the flash dispatcher needs it to pick the
    shard_map-wrapped kernel on multi-device meshes).

    `cache` = (cache_k, cache_v, pos) switches the attention into the
    shared autoregressive cached path; the return becomes
    (h, new_k_cache, new_v_cache)."""
    dt = h.dtype

    def rms(x, scale):
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        return (xf * lax.rsqrt(ms + attrs.norm_eps)
                * scale.astype(jnp.float32)).astype(dt)

    hd = h.shape[-1] // attrs.heads
    a = rms(h, p["ln1"])
    q = qkv_project(a, p["wq"], dt)
    k = qkv_project(a, p["wk"], dt)
    v = qkv_project(a, p["wv"], dt)
    kc = vc = None
    if cache is not None:
        cache_k, cache_v, pos = cache
        o, kc, vc = cached_attention(
            q, k, v, cache_k, cache_v, pos, scale=1.0 / (hd**0.5),
            rope_theta=attrs.rope_theta,
        )
    else:
        q = apply_rope(q, attrs.rope_theta)
        k = apply_rope(k, attrs.rope_theta)
        o = fused_attention(q, k, v, causal=attrs.causal,
                            scale=1.0 / (hd**0.5), mesh=mesh)
    h = h + attn_out_project(o, p["wo"], dt)
    m = rms(h, p["ln2"])
    g = jnp.einsum("bse,eh->bsh", m, p["gate"].astype(dt))
    u = jnp.einsum("bse,eh->bsh", m, p["up"].astype(dt))
    h = h + jnp.einsum("bsh,he->bse", jax.nn.silu(g) * u,
                       p["down"].astype(dt))
    return h if cache is None else (h, kc, vc)


@register_lowering(OpType.PIPELINE)
def _pipeline(attrs, inputs, params, ctx):
    (x,) = inputs
    mesh = ctx.mesh
    pipe_deg = 1
    if mesh is not None and "pipe" in mesh.axis_names:
        pipe_deg = dict(zip(mesh.axis_names, mesh.devices.shape))["pipe"]

    if ctx.kv_cache is not None:
        # autoregressive decode: scan the layer stack threading each
        # layer's (b, maxlen, kv, hd) cache slice; caches are stacked on
        # a leading layer dim. Decode always takes the scan path — with
        # pipe-sharded weights GSPMD gathers each layer's slice, which is
        # correct (a real pipe decode schedule would stream tokens; one
        # token at a time has no microbatches to pipeline).
        pos = ctx.cache_position

        def body(carry, xs):
            p, ck, cv = xs
            h, kc, vc = _decoder_block(p, carry, attrs, cache=(ck, cv, pos))
            return h, (kc, vc)

        # the layered decode cache shares the "k"/"v" key convention with
        # the paged pool but is never quantized — no scale sidecar exists
        ck_all = ctx.kv_cache["k"]  # fflint: dtype-ok (fp layered cache)
        cv_all = ctx.kv_cache["v"]  # fflint: dtype-ok (fp layered cache)
        h, (kcs, vcs) = lax.scan(body, x, (params, ck_all, cv_all))
        ctx.cache_updates["k"] = kcs
        ctx.cache_updates["v"] = vcs
        return [h]

    # GPipe only when the node's ASSIGNED view pipe-shards the stacked
    # weights — a default-DP view was priced as a plain scan and must run
    # as one (dispatching on the mesh alone would pay an unpriced bubble)
    view = ctx.sharding
    ln1 = view.weight_specs.get("ln1") if view is not None else None
    pipe_view = bool(ln1 and ln1[0] and "pipe" in ln1[0])

    def scan_layers(h, layer_params, block_mesh=None):
        def body(carry, p):
            return _decoder_block(p, carry, attrs, mesh=block_mesh), None

        out, _ = lax.scan(body, h, layer_params)
        return out

    micro = max(attrs.n_microbatches, 1)
    if (pipe_deg > 1 and pipe_view and attrs.layers % pipe_deg == 0
            and x.shape[0] % micro == 0):
        from flexflow_tpu.parallel.pipeline import pipeline_apply

        per = attrs.layers // pipe_deg
        stacked = jax.tree.map(
            lambda a: a.reshape(pipe_deg, per, *a.shape[1:]), params
        )
        y = pipeline_apply(
            # inside the shard_map worker everything is device-local:
            # the block must NOT re-enter the mesh-aware flash dispatch
            lambda p, h: scan_layers(h, p, block_mesh=None),
            stacked, x, mesh=mesh,
            n_microbatches=micro, axis="pipe",
        )
        return [y]
    # no pipe axis: layer-stacked scan (one compiled block instead of L)
    return [scan_layers(x, params, block_mesh=mesh)]
