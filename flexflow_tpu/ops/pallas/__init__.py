"""Pallas TPU kernels for the hot ops.

The reference implements its hot ops as hand-written CUDA kernels
(src/ops/kernels/*.cu, SURVEY.md §2.2); on TPU most ops are best left to
XLA fusion, but attention benefits from a blockwise flash kernel that never
materializes the S×S score matrix in HBM. These kernels are selected by the
attention lowerings when running on a TPU backend and shapes allow;
otherwise the XLA fallback in flexflow_tpu.ops.jax_ops is used.
"""

from flexflow_tpu.ops.pallas.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_available,
)
from flexflow_tpu.ops.pallas.ring_flash import (  # noqa: F401
    ring_flash_attention,
    ring_flash_available,
)
