"""Blockwise flash attention as a Pallas TPU kernel, with a custom VJP.

Replaces the reference's cuDNN multiHeadAttn path (src/ops/attention.cu,
SURVEY.md §2.2) with a TPU-native kernel: q/k/v stream HBM→VMEM in blocks,
scores are computed on the MXU in fp32 and reduced with an online softmax
(running max + denominator held in VMEM scratch), so the S×T score matrix
never touches HBM. The backward pass recomputes scores from the saved
logsumexp (standard flash-attention recomputation) with one kernel for dq
and one for dk/dv.

Layout: kernels operate on (BH, S, D) with the batch×head product as the
outer grid axis; the lane-dim (head_dim) is padded to a multiple of 128 to
match TPU tiling. The logsumexp residual is stored 128-lane-broadcast
((BH, S, 128) fp32) so backward reads stay in native tiling — the same
convention XLA-compatible TPU attention kernels use.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _causal_mask(s, iq, ik, bq, bk):
    qpos = iq * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ik * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # blocks past the diagonal are fully masked under causal attention —
    # skip their compute entirely (memory is still streamed by the grid)
    live = (iq * bq + bq - 1 >= ik * bk) if causal else (ik >= 0)

    @pl.when(live)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l_safe)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd(q, k, v, causal, scale, bq, bk, interpret):
    """q,k,v: (BH, S|T, D). Returns out (BH,S,D), lse (BH,S,128) fp32."""
    BH, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    grid = (BH, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               nk=nk, bq=bq, bk=bk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# ring-step forward: same online softmax, but the (m, l, acc) statistics
# carry IN from previous ring steps and OUT to the next — one call per
# rotating k/v block (used by ring_flash_attention below)


def _fwd_carry_kernel(q_ref, k_ref, v_ref, m_in, l_in, acc_in,
                      m_out, l_out, acc_out, m_scr, l_scr, acc_scr,
                      *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        m_scr[:] = m_in[0]
        l_scr[:] = l_in[0]
        acc_scr[:] = acc_in[0]

    live = (iq * bq + bq - 1 >= ik * bk) if causal else (ik >= 0)

    @pl.when(live)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _():
        m_out[0] = m_scr[:]
        l_out[0] = l_scr[:]
        acc_out[0] = acc_scr[:]


def _fwd_carry(q, k, v, m, l, acc, causal, scale, bq, bk, interpret):
    """One ring step: fold k/v's contribution into carried (m, l, acc).
    q: (BH,S,D); k,v: (BH,T,D); m,l: (BH,S,LANES) f32; acc: (BH,S,D) f32."""
    BH, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    kernel = functools.partial(_fwd_carry_kernel, scale=scale, causal=causal,
                               nk=nk, bq=bq, bk=bk)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, LANES), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, LANES), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, m, l, acc)


# ---------------------------------------------------------------------------
# backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, dq_scr,
               *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = (iq * bq + bq - 1 >= ik * bk) if causal else (ik >= 0)

    @pl.when(live)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk)
        p = jnp.exp(s - lse_ref[0][:, 0:1])
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0][:, 0:1]) * scale
        dq_scr[:] += lax.dot_general(ds.astype(k.dtype), k,
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, scale, causal, nq, bq, bk):
    ik, iq = pl.program_id(1), pl.program_id(2)

    @pl.when(iq == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = (iq * bq + bq - 1 >= ik * bk) if causal else (iq >= 0)

    @pl.when(live)
    def _():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, iq, ik, bq, bk)
        p = jnp.exp(s - lse_ref[0][:, 0:1])
        # dv += pᵀ @ do ; contract the q dim of both
        dv_scr[:] += lax.dot_general(p.astype(do.dtype), do,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0][:, 0:1]) * scale
        dk_scr[:] += lax.dot_general(ds.astype(q.dtype), q,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, causal, scale, bq, bk, interpret):
    BH, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    # delta_i = Σ_d dO_id · O_id, lane-broadcast like lse
    delta = jnp.einsum("bsd,bsd->bs", do.astype(jnp.float32),
                       out.astype(jnp.float32))
    delta = jnp.broadcast_to(delta[..., None], (BH, S, LANES))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, nk=nk,
                          bq=bq, bk=bk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, nq=nq,
                          bq=bq, bk=bk),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper over (BH, S, D) layout


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, bq, bk, interpret):
    out, _ = _fwd(q, k, v, causal, scale, bq, bk, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, bq, bk, interpret):
    out, lse = _fwd(q, k, v, causal, scale, bq, bk, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, bq, bk, interpret, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _bwd(q, k, v, out, lse, do, causal, scale, bq, bk, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# public entry


def _pick_block(n: int, want: int) -> Optional[int]:
    for b in (want, 512, 256, 128):
        if b <= n and n % b == 0:
            return b
    return n if n % LANES == 0 else None


def flash_attention_available(S: int, T: int, *, dropout: float = 0.0,
                              interpret: bool = False) -> bool:
    """True when the Pallas path supports these shapes on this backend.
    FF_TPU_NO_FLASH=1 disables every flash dispatch site (plain, ring,
    Ulysses) — A/B runs and kernel-bug escape hatch."""
    import os

    if os.environ.get("FF_TPU_NO_FLASH") == "1":
        return False
    if dropout > 0.0:
        return False
    if _pick_block(S, 512) is None or _pick_block(T, 512) is None:
        return False
    return interpret or jax.default_backend() == "tpu"


def flash_attention(q, k, v, *, causal: bool = False, scale: float = 1.0,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Flash attention. q: (B,S,H,D); k,v: (B,T,Hkv,D) with H % Hkv == 0.
    Returns (B,S,H,D) in q.dtype; softmax statistics accumulate in fp32.

    Default blocking is picked by head dim (measured on v5e, fwd+bwd at
    S=1024-4096): d<=64 runs ~16-20% faster at 1024x1024 blocks, while
    d=128 doubles the VMEM footprint per tile and prefers 512x512."""
    B, S, H, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None:
        block_q = 1024 if D <= 64 else 512
    if block_k is None:
        block_k = 1024 if D <= 64 else 512
    bq, bk = _pick_block(S, block_q), _pick_block(T, block_k)
    if bq is None or bk is None:
        raise ValueError(f"seq lens ({S},{T}) not tileable by {LANES}")
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    pad = (-D) % LANES
    if pad:
        qb, kb, vb = (jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
                      for x in (qb, kb, vb))
    out = _flash(qb, kb, vb, causal, scale, bq, bk, interpret)
    if pad:
        out = out[..., :D]
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)
