"""Blockwise flash attention as a Pallas TPU kernel, with a custom VJP.

Replaces the reference's cuDNN multiHeadAttn path (src/ops/attention.cu,
SURVEY.md §2.2) with a TPU-native kernel: q/k/v stream HBM→VMEM in blocks,
scores are computed on the MXU in fp32 and reduced with an online softmax
(running max + denominator held in VMEM scratch), so the S×T score matrix
never touches HBM. The backward pass recomputes scores from the saved
logsumexp (standard flash-attention recomputation) with one kernel for dq
and one for dk/dv.

Layouts: the PUBLIC path operates directly on the model's (B, S, H, D)
tensors — the (batch, head) pair is folded into the outer grid axis and
the head dim is squeezed out of each block, so no transpose to a
head-major layout ever materializes in HBM (the r2-r4 benches paid
~1.6 GB/step of such transposes plus their backward mirrors at the 1b
config; tools/hlo_transpose_audit.py). GQA is handled by the kernel index
maps (each q head reads kv head h // rep), so the head repeat and its
backward reduce-sum never materialize either, and dk/dv come out at the
UNREPEATED kv head count. The ring path (ring_flash.py) keeps the older
(BH, S, D) kernels, whose statistics-carry variants it drives step by
step; both share the same block-math bodies. The logsumexp residual is
stored 128-lane-broadcast fp32 so backward reads stay in native tiling.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _causal_mask(s, iq, ik, bq, bk):
    qpos = iq * bq + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ik * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _live(causal, iq, ik, bq, bk):
    """Blocks fully past the diagonal are masked out under causal
    attention — their compute is skipped entirely."""
    return (iq * bq + bq - 1 >= ik * bk) if causal else (ik >= 0)


# ---------------------------------------------------------------------------
# shared block-math bodies (2D tiles; every kernel variant calls these)


def _online_block(q, k, v, m_scr, l_scr, acc_scr, scale, causal, iq, ik,
                  bq, bk):
    """One (bq, bk) tile of the online softmax: fold k/v's scores into the
    carried (m, l, acc) statistics."""
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, iq, ik, bq, bk)
    m_prev = m_scr[:, 0:1]
    l_prev = l_scr[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
    pv = lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    acc_scr[:] = acc_scr[:] * corr + pv
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)


def _dq_block(q, k, v, do, lse, delta, dq_scr, scale, causal, iq, ik,
              bq, bk):
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, iq, ik, bq, bk)
    p = jnp.exp(s - lse[:, 0:1])
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, 0:1]) * scale
    dq_scr[:] += lax.dot_general(ds.astype(k.dtype), k,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)


def _dkv_block(q, k, v, do, lse, delta, dk_scr, dv_scr, scale, causal,
               iq, ik, bq, bk):
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, iq, ik, bq, bk)
    p = jnp.exp(s - lse[:, 0:1])
    # dv += pᵀ @ do ; contract the q dim of both
    dv_scr[:] += lax.dot_general(p.astype(do.dtype), do,
                                 (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, 0:1]) * scale
    dk_scr[:] += lax.dot_general(ds.astype(q.dtype), q,
                                 (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# flat-lane kernels: tensors stay in the PROJECTION layout (B, S, H*D) and
# the grid's head coordinate selects a D-wide LANE block — legal TPU tiling
# (the lane dim is sliced at 128-aligned offsets), no head-major transpose,
# and GQA resolved by indexing kv head h // rep. Requires D % 128 == 0; the
# public entry falls back to the (BH, S, D) transpose path otherwise.


def _fwd_kernel_bshd(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                     acc_scr, *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_live(causal, iq, ik, bq, bk))
    def _():
        _online_block(q_ref[...], k_ref[...], v_ref[...], m_scr, l_scr,
                      acc_scr, scale, causal, iq, ik, bq, bk)

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[...] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l_safe)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _fwd_bshd(q, k, v, causal, scale, bq, bk, interpret, H, D):
    """q: (B,S,H*D); k,v: (B,T,Hkv*D). Returns out (B,S,H*D) and
    lse (B,S,H*LANES) fp32."""
    B, S, _ = q.shape
    T, Hkv = k.shape[1], k.shape[2] // D
    rep = H // Hkv
    nq, nk = S // bq, T // bk
    qmap = lambda b, i, j: (b // H, i, b % H)            # noqa: E731
    kvmap = lambda b, i, j: (b // H, j, (b % H) // rep)  # noqa: E731
    return pl.pallas_call(
        functools.partial(_fwd_kernel_bshd, scale=scale, causal=causal,
                          nk=nk, bq=bq, bk=bk),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((None, bq, D), qmap),
            pl.BlockSpec((None, bk, D), kvmap),
            pl.BlockSpec((None, bk, D), kvmap),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, D), qmap),
            pl.BlockSpec((None, bq, LANES), qmap),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, H * D), q.dtype),
            jax.ShapeDtypeStruct((B, S, H * LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _dq_kernel_bshd(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref,
                    dq_scr, *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_live(causal, iq, ik, bq, bk))
    def _():
        _dq_block(q_ref[...], k_ref[...], v_ref[...], do_ref[...],
                  lse_ref[...], dl_ref[...], dq_scr, scale, causal, iq, ik,
                  bq, bk)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[...] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel_bshd(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref,
                     dv_ref, dk_scr, dv_scr, *, scale, causal, nq, nt, bq,
                     bk):
    """Grid (B*Hkv, nk, rep*nq): the innermost axis sweeps every (q head
    in the kv group) x (q block), accumulating this kv block's dk/dv
    across the whole group — GQA's head-repeat backward without ever
    materializing repeated k/v or a reduce over repeats."""
    ik, t = pl.program_id(1), pl.program_id(2)
    iq = t % nq

    @pl.when(t == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_live(causal, iq, ik, bq, bk))
    def _():
        _dkv_block(q_ref[...], k_ref[...], v_ref[...], do_ref[...],
                   lse_ref[...], dl_ref[...], dk_scr, dv_scr, scale, causal,
                   iq, ik, bq, bk)

    @pl.when(t == nt - 1)
    def _():
        dk_ref[...] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_bshd(q, k, v, out, lse, do, causal, scale, bq, bk, interpret,
              H, D):
    B, S, _ = q.shape
    T, Hkv = k.shape[1], k.shape[2] // D
    rep = H // Hkv
    nq, nk = S // bq, T // bk
    # delta_i = Σ_d dO_id · O_id per head, lane-broadcast like lse
    delta = jnp.einsum("bshd,bshd->bsh",
                       do.reshape(B, S, H, D).astype(jnp.float32),
                       out.reshape(B, S, H, D).astype(jnp.float32))
    delta = jnp.broadcast_to(delta[..., None],
                             (B, S, H, LANES)).reshape(B, S, H * LANES)

    qmap = lambda b, i, j: (b // H, i, b % H)            # noqa: E731
    kvmap = lambda b, i, j: (b // H, j, (b % H) // rep)  # noqa: E731
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_bshd, scale=scale, causal=causal,
                          nk=nk, bq=bq, bk=bk),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((None, bq, D), qmap),
            pl.BlockSpec((None, bk, D), kvmap),
            pl.BlockSpec((None, bk, D), kvmap),
            pl.BlockSpec((None, bq, D), qmap),
            pl.BlockSpec((None, bq, LANES), qmap),
            pl.BlockSpec((None, bq, LANES), qmap),
        ],
        out_specs=pl.BlockSpec((None, bq, D), qmap),
        out_shape=jax.ShapeDtypeStruct((B, S, H * D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # q-side blocks walk (head-in-group, q block) on the innermost axis
    gqmap = lambda g, j, t: (g // Hkv, t % nq,           # noqa: E731
                             (g % Hkv) * rep + t // nq)
    gkvmap = lambda g, j, t: (g // Hkv, j, g % Hkv)      # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_bshd, scale=scale, causal=causal,
                          nq=nq, nt=rep * nq, bq=bq, bk=bk),
        grid=(B * Hkv, nk, rep * nq),
        in_specs=[
            pl.BlockSpec((None, bq, D), gqmap),
            pl.BlockSpec((None, bk, D), gkvmap),
            pl.BlockSpec((None, bk, D), gkvmap),
            pl.BlockSpec((None, bq, D), gqmap),
            pl.BlockSpec((None, bq, LANES), gqmap),
            pl.BlockSpec((None, bq, LANES), gqmap),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), gkvmap),
            pl.BlockSpec((None, bk, D), gkvmap),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, Hkv * D), k.dtype),
            jax.ShapeDtypeStruct((B, T, Hkv * D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_bshd(q, k, v, causal, scale, bq, bk, interpret, H, D):
    out, _ = _fwd_bshd(q, k, v, causal, scale, bq, bk, interpret, H, D)
    return out


def _flash_bshd_fwd(q, k, v, causal, scale, bq, bk, interpret, H, D):
    out, lse = _fwd_bshd(q, k, v, causal, scale, bq, bk, interpret, H, D)
    return out, (q, k, v, out, lse)


def _flash_bshd_bwd(causal, scale, bq, bk, interpret, H, D, res, do):
    q, k, v, out, lse = res
    return _bwd_bshd(q, k, v, out, lse, do, causal, scale, bq, bk,
                     interpret, H, D)


_flash_bshd.defvjp(_flash_bshd_fwd, _flash_bshd_bwd)


# ---------------------------------------------------------------------------
# (BH, S, D) forward — kept for the ring path (ring_flash.py drives the
# statistics-carry variant hop by hop on per-shard head-major blocks)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_live(causal, iq, ik, bq, bk))
    def _():
        _online_block(q_ref[0], k_ref[0], v_ref[0], m_scr, l_scr, acc_scr,
                      scale, causal, iq, ik, bq, bk)

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, 0:1]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(l_safe)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd(q, k, v, causal, scale, bq, bk, interpret):
    """q,k,v: (BH, S|T, D). Returns out (BH,S,D), lse (BH,S,128) fp32."""
    BH, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    grid = (BH, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               nk=nk, bq=bq, bk=bk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# ring-step forward: same online softmax, but the (m, l, acc) statistics
# carry IN from previous ring steps and OUT to the next — one call per
# rotating k/v block (used by ring_flash_attention)


def _fwd_carry_kernel(q_ref, k_ref, v_ref, m_in, l_in, acc_in,
                      m_out, l_out, acc_out, m_scr, l_scr, acc_scr,
                      *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        m_scr[:] = m_in[0]
        l_scr[:] = l_in[0]
        acc_scr[:] = acc_in[0]

    @pl.when(_live(causal, iq, ik, bq, bk))
    def _():
        _online_block(q_ref[0], k_ref[0], v_ref[0], m_scr, l_scr, acc_scr,
                      scale, causal, iq, ik, bq, bk)

    @pl.when(ik == nk - 1)
    def _():
        m_out[0] = m_scr[:]
        l_out[0] = l_scr[:]
        acc_out[0] = acc_scr[:]


def _fwd_carry(q, k, v, m, l, acc, causal, scale, bq, bk, interpret):
    """One ring step: fold k/v's contribution into carried (m, l, acc).
    q: (BH,S,D); k,v: (BH,T,D); m,l: (BH,S,LANES) f32; acc: (BH,S,D) f32."""
    BH, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    kernel = functools.partial(_fwd_carry_kernel, scale=scale, causal=causal,
                               nk=nk, bq=bq, bk=bk)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, LANES), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, LANES), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, m, l, acc)


# ---------------------------------------------------------------------------
# (BH, S, D) backward — ring path support


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, dq_scr,
               *, scale, causal, nk, bq, bk):
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_live(causal, iq, ik, bq, bk))
    def _():
        _dq_block(q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0],
                  dl_ref[0], dq_scr, scale, causal, iq, ik, bq, bk)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, scale, causal, nq, bq, bk):
    ik, iq = pl.program_id(1), pl.program_id(2)

    @pl.when(iq == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_live(causal, iq, ik, bq, bk))
    def _():
        _dkv_block(q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0],
                   dl_ref[0], dk_scr, dv_scr, scale, causal, iq, ik, bq, bk)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, causal, scale, bq, bk, interpret):
    BH, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    # delta_i = Σ_d dO_id · O_id, lane-broadcast like lse
    delta = jnp.einsum("bsd,bsd->bs", do.astype(jnp.float32),
                       out.astype(jnp.float32))
    delta = jnp.broadcast_to(delta[..., None], (BH, S, LANES))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, nk=nk,
                          bq=bq, bk=bk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, nq=nq,
                          bq=bq, bk=bk),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper over (BH, S, D) layout (ring path)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, bq, bk, interpret):
    out, _ = _fwd(q, k, v, causal, scale, bq, bk, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, bq, bk, interpret):
    out, lse = _fwd(q, k, v, causal, scale, bq, bk, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, bq, bk, interpret, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _bwd(q, k, v, out, lse, do, causal, scale, bq, bk, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# public entry


def _pick_block(n: int, want: int) -> Optional[int]:
    for b in (want, 512, 256, 128):
        if b <= n and n % b == 0:
            return b
    return n if n % LANES == 0 else None


def flash_attention_available(S: int, T: int, *, dropout: float = 0.0,
                              interpret: bool = False) -> bool:
    """True when the Pallas path supports these shapes on this backend.
    FF_TPU_NO_FLASH=1 disables every flash dispatch site (plain, ring,
    Ulysses) — A/B runs and kernel-bug escape hatch."""
    import os

    if os.environ.get("FF_TPU_NO_FLASH") == "1":
        return False
    if dropout > 0.0:
        return False
    if _pick_block(S, 512) is None or _pick_block(T, 512) is None:
        return False
    return interpret or jax.default_backend() == "tpu"


def flash_attention(q, k, v, *, causal: bool = False, scale: float = 1.0,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Flash attention. q: (B,S,H,D); k,v: (B,T,Hkv,D) with H % Hkv == 0.
    Returns (B,S,H,D) in q.dtype; softmax statistics accumulate in fp32.

    When D is a lane multiple the kernels consume the flat projection
    layout (B,S,H*D) directly — the grid's head coordinate picks a
    128-aligned lane block, so neither a head-major transpose nor a
    kv-head repeat ever materializes in HBM (GQA is resolved by the index
    maps). Smaller head dims fall back to the padded (BH,S,D) transpose
    path. Default blocking is picked by head dim (measured on v5e,
    fwd+bwd at S=1024-4096): d<=64 runs ~16-20% faster at 1024x1024
    blocks, while d=128 doubles the VMEM footprint per tile and prefers
    512x512."""
    B, S, H, D = q.shape
    T, Hkv = k.shape[1], k.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None:
        block_q = 1024 if D <= 64 else 512
    if block_k is None:
        block_k = 1024 if D <= 64 else 512
    bq, bk = _pick_block(S, block_q), _pick_block(T, block_k)
    if bq is None or bk is None:
        raise ValueError(f"seq lens ({S},{T}) not tileable by {LANES}")

    if D % LANES == 0:
        out = _flash_bshd(q.reshape(B, S, H * D),
                          k.reshape(B, T, Hkv * D),
                          v.reshape(B, T, Hkv * D),
                          causal, scale, bq, bk, interpret, H, D)
        return out.reshape(B, S, H, D)

    # fallback: head-major transpose + lane padding (D < 128 models)
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    pad = (-D) % LANES
    if pad:
        qb, kb, vb = (jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
                      for x in (qb, kb, vb))
    out = _flash(qb, kb, vb, causal, scale, bq, bk, interpret)
    if pad:
        out = out[..., :D]
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)
