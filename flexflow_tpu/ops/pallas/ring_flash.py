"""Ring attention with the Pallas flash kernel as the per-block body.

Upgrades parallel/ring.py's einsum-based online softmax (VERDICT r1
weakness 3): each ring step folds the currently-held k/v block into
carried (m, l, acc) statistics with `_fwd_carry` — the blockwise flash
kernel — so the S_loc×S_loc score tile never materializes in HBM, while
`lax.ppermute` rotates k/v around the ICI ring between steps.

Causality per ring step is STATIC relative to block positions (the k/v
block is entirely before / at / after the local queries), so the step
dispatches through `lax.switch` over three fixed kernels — no dynamic
masks, no scalar prefetch:

  src <  my : full (unmasked) flash block
  src == my : standard causal flash block
  src >  my : fully masked — skip entirely

The backward is a second ring pass: the standard flash decomposition
(p_ij = exp(s_ij − lse_i), ds = p·(dp − Δ)) makes each block's dq/dk/dv
contribution computable independently from the FINAL lse/Δ, so the
existing `_bwd` kernels run per block, dq accumulates locally, and dk/dv
accumulators rotate with their k/v blocks — each arrives home after n
steps. Everything runs inside shard_map over the seq axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from flexflow_tpu.ops.pallas.flash_attention import (
    LANES,
    NEG_INF,
    _bwd,
    _fwd_carry,
    _pick_block,
)


def _modes(src, my):
    """0 = full, 1 = causal, 2 = masked (static branch index per step)."""
    return jnp.where(src == my, 1, jnp.where(src < my, 0, 2)).astype(jnp.int32)


def _rep_heads(x, rep):
    """(B*Hkv, s, D) -> (B*H, s, D): repeat each kv head `rep` times in
    the head-major BH layout (matches to_bh's b*H + h ordering)."""
    if rep == 1:
        return x
    BHkv, s, D = x.shape
    return jnp.repeat(x.reshape(BHkv, 1, s, D), rep, axis=1).reshape(
        BHkv * rep, s, D)


def _sum_heads(g, rep):
    """(B*H, s, D) -> (B*Hkv, s, D): sum the `rep` q-head gradients that
    share each kv head (the backward of _rep_heads)."""
    if rep == 1:
        return g
    BH, s, D = g.shape
    return g.reshape(BH // rep, rep, s, D).sum(axis=1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def ring_flash(q, k, v, axis_name, n_shards, causal, scale, blk, interpret,
               rep=1):
    out, _ = _ring_fwd_impl(q, k, v, axis_name, n_shards, causal, scale,
                            blk, interpret, rep)
    return out


def _ring_fwd_impl(q, k, v, axis_name, n_shards, causal, scale, blk,
                   interpret, rep):
    """q: (B*H, S, D); k, v: (B*Hkv, S, D) with H = Hkv*rep. GQA kv stays
    UNREPEATED on the ring — every ppermute hop moves 1/rep of the bytes
    the pre-repeated form did; the repeat is a LOCAL broadcast right
    before each block's kernel call (the cost model prices ring hops at
    unrepeated kv bytes — cost_model.py kv_bytes uses num_kv — so this
    makes the implementation match its own pricing)."""
    BH, S, D = q.shape
    my = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    m = jnp.full((BH, S, LANES), NEG_INF, jnp.float32)
    l = jnp.zeros((BH, S, LANES), jnp.float32)
    acc = jnp.zeros((BH, S, D), jnp.float32)

    def full_step(ops):
        qq, kk, vv, m_, l_, a_ = ops
        return _fwd_carry(qq, _rep_heads(kk, rep), _rep_heads(vv, rep),
                          m_, l_, a_, False, scale, blk, blk, interpret)

    def causal_step(ops):
        qq, kk, vv, m_, l_, a_ = ops
        return _fwd_carry(qq, _rep_heads(kk, rep), _rep_heads(vv, rep),
                          m_, l_, a_, True, scale, blk, blk, interpret)

    def masked_step(ops):
        _, _, _, m_, l_, a_ = ops
        return m_, l_, a_

    k_blk, v_blk = k, v
    for i in range(n_shards):
        src = (my - i) % n_shards
        if causal:
            m_, l_, acc_ = lax.switch(
                _modes(src, my), (full_step, causal_step, masked_step),
                (q, k_blk, v_blk, m, l, acc),
            )
        else:
            m_, l_, acc_ = full_step((q, k_blk, v_blk, m, l, acc))
        m, l, acc = m_, l_, acc_
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)

    l_safe = jnp.maximum(l[:, :, 0:1], 1e-30)
    out = (acc / l_safe).astype(q.dtype)
    lse = jnp.broadcast_to(m[:, :, 0:1] + jnp.log(l_safe), (BH, S, LANES))
    return out, lse


def _ring_fwd(q, k, v, axis_name, n_shards, causal, scale, blk, interpret,
              rep):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, n_shards, causal, scale,
                              blk, interpret, rep)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, n_shards, causal, scale, blk, interpret, rep, res,
              do):
    q, k, v, out, lse = res
    my = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    def grads(ops, blk_causal):
        qq, kk, vv = ops
        dq_c, dk_r, dv_r = _bwd(qq, _rep_heads(kk, rep),
                                _rep_heads(vv, rep), out, lse, do,
                                blk_causal, scale, blk, blk, interpret)
        # fold the rep q-heads' contributions back onto each kv head so
        # the accumulators (and their ring hops) stay unrepeated
        return dq_c, _sum_heads(dk_r, rep), _sum_heads(dv_r, rep)

    def full_step(ops):
        return grads(ops, False)

    def causal_step(ops):
        return grads(ops, True)

    def masked_step(ops):
        qq, kk, vv = ops
        return (jnp.zeros_like(qq), jnp.zeros_like(kk), jnp.zeros_like(vv))

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    k_blk, v_blk = k, v
    # per-step grads arrive in op dtype; upcasting feeds the f32 ring
    # accumulators below — loop-variant, cannot hoist
    for i in range(n_shards):  # fflint: dtype-ok (f32 grad accumulate)
        src = (my - i) % n_shards
        if causal:
            dq_c, dk_c, dv_c = lax.switch(
                _modes(src, my), (full_step, causal_step, masked_step),
                (q, k_blk, v_blk),
            )
        else:
            dq_c, dk_c, dv_c = full_step((q, k_blk, v_blk))
        dq = dq + dq_c.astype(jnp.float32)
        dk_acc = dk_acc + dk_c.astype(jnp.float32)
        dv_acc = dv_acc + dv_c.astype(jnp.float32)
        # dk/dv accumulators travel WITH their k/v blocks; after n_shards
        # permutes each is back on the block owner's device
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


ring_flash.defvjp(_ring_fwd, _ring_bwd)


def ring_flash_available(s_loc: int, *, interpret: bool = False) -> bool:
    """The Pallas ring body needs a tileable local sequence and a TPU (or
    interpret mode)."""
    from flexflow_tpu.ops.pallas.flash_attention import (
        flash_attention_available,
    )

    return flash_attention_available(s_loc, s_loc, interpret=interpret)


def ring_flash_attention(q, k, v, *, axis_name: str, n_shards: int,
                         causal: bool, scale: float,
                         interpret: bool = False):
    """Per-shard entry (inside shard_map). q: (B, s_loc, H, D); k, v:
    (B, s_loc, Hkv, D) with H % Hkv == 0 — GQA kv rides the ring
    UNREPEATED (1/rep of the hop bytes); the repeat happens locally per
    block inside ring_flash."""
    B, s_loc, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2], s_loc, D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    pad = (-D) % LANES
    if pad:
        qb, kb, vb = (jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
                      for x in (qb, kb, vb))
    blk = _pick_block(s_loc, 512)
    out = ring_flash(qb, kb, vb, axis_name, n_shards, causal, scale, blk,
                     interpret, rep)
    if pad:
        out = out[..., :D]
    return out.reshape(B, H, s_loc, D).transpose(0, 2, 1, 3)
