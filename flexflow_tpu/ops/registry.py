"""Lowering registry: OpType -> JAX lowering function.

A lowering has signature `fn(attrs, inputs, params, ctx) -> list[Array]`
where `params` is the op's weight dict and `ctx` a LowerCtx. This replaces
the reference's per-op Legion task bodies + kernel wrappers
(e.g. Linear::forward_task -> forward_kernel_wrapper, linear.cc:370,
kernels/linear_kernels.cu:83): on TPU every op lowers inline into the single
traced step function and XLA fuses/schedules.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

from flexflow_tpu.ffconst import OpType


@dataclasses.dataclass
class LowerCtx:
    """Per-trace lowering context."""

    training: bool = True
    rng: Optional[object] = None  # jax PRNG key, folded per-op by the executor
    mesh: Optional[object] = None
    seq_length: Optional[int] = None  # FFIterationConfig truncation
    node_guid: int = 0
    # the node's assigned ShardingView (composites like PIPELINE dispatch
    # on it: a pipe-sharded view selects the GPipe schedule)
    sharding: Optional[object] = None
    # autoregressive decoding (net-new vs the reference): when kv_cache is
    # set ({"k","v"} buffers for THIS attention node) the MHA lowering
    # attends over the cache at cache_position and writes the updated
    # buffers into cache_updates
    kv_cache: Optional[dict] = None
    cache_position: Optional[object] = None
    # paged decode (flexflow_tpu.paged): kv_cache buffers are a global
    # page POOL (num_pages, page_size, Hkv, D) and page_tables maps each
    # decode slot's positions onto pool pages ((slots, max_pages) int32)
    page_tables: Optional[object] = None
    # the ragged work descriptor (flexflow_tpu.paged.attention module
    # docstring): with page_tables set, every paged step — decode,
    # chunked prefill, speculative tree verify — carries per-slot
    # ragged_q_lens ((B,) int32 live query rows), ragged_depths
    # ((B, S) int32 — row i scores at absolute position
    # cache_position + depth, so sibling tree branches share one) and
    # ragged_anc ((B, S, S) bool window visibility: tril for causal
    # chains, ancestor-or-self for trees)
    ragged_q_lens: Optional[object] = None
    ragged_depths: Optional[object] = None
    ragged_anc: Optional[object] = None
    cache_updates: Dict[str, object] = dataclasses.field(default_factory=dict)
    # lowering writes non-trainable state updates here (BatchNorm running
    # stats, Cache buffers): key = weight name within the op
    state_updates: Dict[str, object] = dataclasses.field(default_factory=dict)


_LOWERINGS: Dict[OpType, Callable] = {}


def register_lowering(op_type: OpType):
    def deco(fn):
        _LOWERINGS[op_type] = fn
        return fn

    return deco


def get_lowering(op_type: OpType) -> Callable:
    # imports populate the registry on first use
    from flexflow_tpu.ops import jax_ops  # noqa: F401
    from flexflow_tpu.parallel import parallel_ops  # noqa: F401

    if op_type not in _LOWERINGS:
        raise NotImplementedError(f"no lowering registered for {op_type}")
    return _LOWERINGS[op_type]
