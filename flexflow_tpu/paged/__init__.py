"""Paged KV-cache + continuous-batching generation subsystem.

vLLM-style block paging, TPU-idiomatically: a global pool of fixed-size
KV pages with per-request page tables (host-side numpy bookkeeping,
int32 device mirrors), ONE ragged paged-attention step that serves
decode, chunked prefill and speculative tree verify alike (a Pallas TPU
kernel with a pure-JAX gather fallback behind a single gate), and a
continuous-batching scheduler that admits by free-page budget instead
of fixed dense slots and packs each tick's mixed work into ragged
launches.

Layering:
  pool.py       host-side page allocator/free-list/defrag (plain numpy)
  attention.py  ragged paged attention (Pallas kernel + jnp.take fallback)
  scheduler.py  PagedGenerationServer (admission, preemption, metrics)

See docs/paged.md for the page-table layout and scheduler policy.
"""

from flexflow_tpu.paged.attention import (
    paged_attention_available,
    ragged_flash_attention,
    ragged_gather_attention,
    ragged_paged_attention,
    ragged_visibility_mask,
    reset_rejection_log,
    tree_visibility_mask,
)
from flexflow_tpu.paged.pool import PagePool
from flexflow_tpu.paged.scheduler import PagedGenerationServer

__all__ = [
    "PagePool",
    "PagedGenerationServer",
    "paged_attention_available",
    "ragged_flash_attention",
    "ragged_gather_attention",
    "ragged_paged_attention",
    "ragged_visibility_mask",
    "reset_rejection_log",
    "tree_visibility_mask",
]
