"""Paged KV-cache + continuous-batching generation subsystem.

vLLM-style block paging, TPU-idiomatically: a global pool of fixed-size
KV pages with per-request page tables (host-side numpy bookkeeping,
int32 device mirrors), a paged-attention decode path (Pallas TPU kernel
with a pure-JAX gather fallback), and a continuous-batching scheduler
that admits by free-page budget instead of fixed dense slots.

Layering:
  pool.py       host-side page allocator/free-list/defrag (plain numpy)
  attention.py  paged decode attention (Pallas kernel + jnp.take fallback)
  scheduler.py  PagedGenerationServer (admission, preemption, metrics)

See docs/paged.md for the page-table layout and scheduler policy.
"""

from flexflow_tpu.paged.attention import (
    paged_attention_available,
    paged_cached_attention,
    paged_cached_tree_attention,
    paged_gather_attention,
    paged_tree_verify,
    tree_visibility_mask,
)
from flexflow_tpu.paged.pool import PagePool
from flexflow_tpu.paged.scheduler import PagedGenerationServer

__all__ = [
    "PagePool",
    "PagedGenerationServer",
    "paged_attention_available",
    "paged_cached_attention",
    "paged_cached_tree_attention",
    "paged_gather_attention",
    "paged_tree_verify",
    "tree_visibility_mask",
]
