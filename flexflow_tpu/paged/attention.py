"""Paged decode attention: gather K/V through a page table.

Two selectable paths, chosen exactly the way ops/pallas/flash_attention
picks its kernel (backend probe + env kill switch + shape gate):

  * a Pallas TPU kernel whose grid walks (batch, kv head, page) with the
    page table and per-slot positions SCALAR-PREFETCHED, so each page's
    K/V block DMAs straight from its pooled HBM location into VMEM — no
    gathered copy of the sequence ever materializes. GQA is handled by
    grouping the q heads of one kv head into a single (rep, D) block, so
    kv pages are read once per GROUP (not per q head) and never repeated.
  * a pure-JAX `jnp.take` fallback (`pool[page_table]` gather + masked
    dot-product attention) that runs anywhere and is the reference the
    kernel is validated against.

The decode step is S=1 by construction (prefill runs through the dense
cached path and its rows are scattered into pages afterwards —
scheduler.py), so q is (B, 1, H, D) here.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def paged_attention_available(head_dim: int, page_size: int,
                              interpret: bool = False,
                              dtype=jnp.float32) -> bool:
    """True when the Pallas paged kernel supports these shapes on this
    backend. FF_TPU_NO_PAGED=1 disables the kernel everywhere (A/B runs
    and kernel-bug escape hatch, like FF_TPU_NO_FLASH). On real TPUs the
    head dim must be a lane multiple (the kernel reads lane-aligned D
    blocks; smaller head dims take the gather fallback, mirroring the
    flash bshd gate) and pages must tile the sublane dim AT THE POOL'S
    DTYPE — (8, 128) tiles for fp32 but (16, 128) for bf16/fp16 and
    (32, 128) for int8/fp8, so a bf16 pool needs page_size % 16 == 0."""
    if os.environ.get("FF_TPU_NO_PAGED") == "1":
        return False
    if interpret:
        return True
    itemsize = jnp.dtype(dtype).itemsize
    if itemsize > 4:
        return False  # 8-byte dtypes have no TPU tiling story
    sublane = 8 * (4 // max(itemsize, 1))
    if head_dim % LANES != 0 or page_size % sublane != 0:
        return False
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# pure-JAX fallback (and numerical reference)


def paged_gather_attention(q, kc_pages, vc_pages, page_tables, pos, *,
                           scale: float):
    """q: (B, S, H, D); kc/vc_pages: (N, P, Hkv, D); page_tables:
    (B, max_pages) int32; pos: (B,) int32 — the absolute position of each
    row's FIRST query token. Gathers every table-mapped page and attends
    with the same absolute-position mask as the dense cached path (rows
    past a slot's write head — including everything in the null page —
    stay masked)."""
    B, S, _, D = q.shape
    Hkv = kc_pages.shape[2]
    dt = q.dtype
    kg = kc_pages[page_tables].reshape(B, -1, Hkv, D)
    vg = vc_pages[page_tables].reshape(B, -1, Hkv, D)
    qpos = pos[:, None] + jnp.arange(S)[None, :]            # (B, S)
    kpos = jnp.arange(kg.shape[1])                          # (T,)
    mask = kpos[None, None, :] <= qpos[:, :, None]          # (B, S, T)
    from flexflow_tpu.ops.jax_ops import _dot_product_attention

    return _dot_product_attention(q, kg.astype(dt), vg.astype(dt),
                                  causal=False, scale=scale, mask=mask)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (B, Hkv, n_pages); page table + positions prefetched


def _paged_decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale, page_size,
                         n_pages):
    b, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # pages wholly past the slot's write head contribute nothing — skip
    # their MXU work entirely (the masked-out math would be exp(-inf)=0)
    @pl.when(j * page_size <= pos_ref[b])
    def _():
        q = q_ref[...]                       # (rep, D)
        k = k_ref[...]                       # (P, D)
        v = v_ref[...]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        kpos = j * page_size + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos <= pos_ref[b], s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(p.astype(v.dtype), v,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_pages - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:, 0:1], 1e-30)
        o_ref[...] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_flash_decode(q, kc_pages, vc_pages, page_tables, pos, *,
                       scale: float, interpret: bool = False):
    """Pallas paged-attention decode step. q: (B, 1, H, D); kc/vc_pages:
    (N, P, Hkv, D); page_tables: (B, max_pages); pos: (B,). The page
    table rides scalar prefetch, so each grid step's BlockSpec index map
    resolves `pt[b, j]` BEFORE the DMA — K/V stream page-by-page from
    their pooled locations."""
    B, S, H, D = q.shape
    if S != 1:
        raise ValueError(f"paged decode is single-token (S=1), got S={S}")
    N, P, Hkv, _ = kc_pages.shape
    rep = H // Hkv
    n_pages = page_tables.shape[1]
    qr = q[:, 0].reshape(B, Hkv, rep, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, n_pages),
        in_specs=[
            pl.BlockSpec((None, None, rep, D),
                         lambda b, g, j, pt, ps: (b, g, 0, 0)),
            pl.BlockSpec((None, P, None, D),
                         lambda b, g, j, pt, ps: (pt[b, j], 0, g, 0)),
            pl.BlockSpec((None, P, None, D),
                         lambda b, g, j, pt, ps: (pt[b, j], 0, g, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, rep, D),
                               lambda b, g, j, pt, ps: (b, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, LANES), jnp.float32),
            pltpu.VMEM((rep, LANES), jnp.float32),
            pltpu.VMEM((rep, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale, page_size=P,
                          n_pages=n_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), pos.astype(jnp.int32), qr,
      kc_pages, vc_pages)
    return out.reshape(B, 1, H, D)


# ---------------------------------------------------------------------------
# the lowering entry: rope + page write + attend (mirrors cached_attention)


def paged_cached_attention(q, k, v, cache_k, cache_v, page_tables, pos, *,
                           scale: float, rope_theta: Optional[float] = None):
    """One paged decode step, the drop-in analog of
    ops.jax_ops.cached_attention: rope at each slot's absolute position,
    scatter the new K/V row into its slot's current page, attend over the
    table-mapped pages. Idle slots (page table all-null, pos 0) write
    into the null page and read garbage that their mask discards.

    Returns (attention output, new k pool, new v pool)."""
    from flexflow_tpu.ops.jax_ops import apply_rope

    if q.shape[1] != 1:
        raise ValueError(
            f"paged decode is single-token (S=1), got S={q.shape[1]}; "
            "prefill runs through the dense cached path and its rows are "
            "scattered into pages (paged/scheduler.py)")
    P = cache_k.shape[1]
    pos_v = jnp.asarray(pos)
    if rope_theta is not None:
        q = apply_rope(q, rope_theta, pos_offset=pos_v)
        k = apply_rope(k, rope_theta, pos_offset=pos_v)
    B = q.shape[0]
    rows = jnp.arange(B)
    page = page_tables[rows, pos_v // P]                  # (B,)
    off = pos_v % P
    kc = cache_k.at[page, off].set(k[:, 0].astype(cache_k.dtype))
    vc = cache_v.at[page, off].set(v[:, 0].astype(cache_v.dtype))

    force_interp = os.environ.get("FF_TPU_FLASH_INTERPRET") == "1"
    if paged_attention_available(q.shape[-1], P, interpret=force_interp,
                                 dtype=kc.dtype):
        out = paged_flash_decode(q, kc, vc, page_tables, pos_v,
                                 scale=scale, interpret=force_interp)
    else:
        out = paged_gather_attention(q, kc, vc, page_tables, pos_v,
                                     scale=scale)
    return out, kc, vc
