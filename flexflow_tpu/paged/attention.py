"""Ragged paged attention: ONE kernel for decode, chunked prefill, and
speculative tree verify.

Every unit of paged work — a decode step, a chunk of a mid-prefill
prompt, a drafted token tree — is the same shape of problem: S query
rows per batch entry whose K/V rows land at cache rows pos..pos+S-1
through a page table, attending over the committed prefix plus some
subset of the in-flight window. The only thing that differs is the
per-slot metadata:

  * ``pos``    (B,)     absolute committed position (the write head);
  * ``q_lens`` (B,)     how many of the S query rows are real work
                        (decode 1, a chunk its token count, a tree its
                        node count; 0 marks a padded batch entry);
  * ``depths`` (B, S)   rope offset of row i relative to pos (chunks:
                        arange(S); trees: node depth, so sibling
                        branches score at the SAME absolute position);
  * ``anc``    (B, S, S) the visibility relation INSIDE the window
                        (chunks: lower-triangular causal; trees: the
                        ancestor-or-self mask; decode: ones((1, 1))).

One Pallas kernel consumes that descriptor: the grid walks
(batch, kv head, page) with the page table, positions and query lengths
SCALAR-PREFETCHED, so each page's K/V block DMAs straight from its
pooled HBM location into VMEM — no gathered copy of the sequence ever
materializes, and no (B, S, L) HBM mask is built either: the window
visibility is derived IN-KERNEL from `anc` via a one-hot matmul against
the page's relative positions. Pages wholly past a slot's visible
horizon (pos + q_len - 1) are skipped, as are padded batch entries
(q_len == 0). GQA groups the q heads of one kv head into a single
(rep, S, D) block, so kv pages are read once per GROUP and never
repeated.

The single pure-JAX fallback (`ragged_gather_attention`) gathers
``pool[page_table]`` and applies the same visibility as a materialized
(B, S, L) mask (`ragged_visibility_mask`) — it runs anywhere and is the
reference the kernel is validated against in tests/test_paged.py.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128

logger = logging.getLogger(__name__)
_fallback_logged: set = set()


def reset_rejection_log() -> None:
    """Forget which kernel rejections were already logged. Server
    construction calls this so a SECOND server (or an A/B run flipping
    FF_TPU_NO_PAGED between runs in one process) logs its own gate
    decisions instead of inheriting the first server's silence."""
    _fallback_logged.clear()


def _reject(reason: str, cfg: tuple) -> bool:
    """Log the CONCRETE kernel-rejection reason once per
    (reason, gate-config) pair (the flash-attention selection
    discipline: a silent fallback looks like a 10x paged-decode
    slowdown with no explanation in any log). Keying on the gate config
    too means two servers with different shapes each get their own
    line."""
    key = (reason, cfg)
    if key not in _fallback_logged:
        _fallback_logged.add(key)
        logger.info(
            "paged attention: ragged Pallas kernel rejected (%s) for "
            "gate config %s; using the jnp.take gather fallback",
            reason, cfg)
    return False


def paged_attention_available(head_dim: int, page_size: int,
                              interpret: bool = False,
                              dtype=jnp.float32) -> bool:
    """True when the ragged Pallas kernel supports these shapes on this
    backend — the ONE gate for decode, chunked prefill and tree verify
    (there is no per-variant rejection matrix any more).
    FF_TPU_NO_PAGED=1 disables the kernel everywhere (A/B runs and
    kernel-bug escape hatch, like FF_TPU_NO_FLASH). On real TPUs the
    head dim must be a lane multiple (the kernel reads lane-aligned D
    blocks; smaller head dims take the gather fallback, mirroring the
    flash bshd gate) and pages must tile the sublane dim AT THE POOL'S
    DTYPE — (8, 128) tiles for fp32 but (16, 128) for bf16/fp16 and
    (32, 128) for int8/fp8, so a bf16 pool needs page_size % 16 == 0
    and a QUANTIZED int8 pool (kv_dtype="int8", paged/quant.py) needs
    page_size % 32 == 0 for the kernel's dequant-on-load path.
    Rejections log their concrete reason once per (reason, config)."""
    dt = jnp.dtype(dtype)
    cfg = (head_dim, page_size, dt.name, jax.default_backend())
    if os.environ.get("FF_TPU_NO_PAGED") == "1":
        return _reject("FF_TPU_NO_PAGED=1 kill switch set", cfg)
    if interpret:
        return True
    itemsize = dt.itemsize
    if itemsize > 4:
        return _reject(
            f"pool dtype {dt.name} is 8-byte (no TPU tiling story)", cfg)
    sublane = 8 * (4 // max(itemsize, 1))
    if head_dim % LANES != 0:
        return _reject(
            f"head_dim={head_dim} is not a multiple of the {LANES}-lane "
            "tile", cfg)
    if page_size % sublane != 0:
        return _reject(
            f"page_size={page_size} does not tile the {sublane}-row "
            f"sublane dim at pool dtype {dt.name}", cfg)
    if jax.default_backend() != "tpu":
        return _reject(f"backend is {jax.default_backend()!r}, not tpu",
                       cfg)
    return True


# ---------------------------------------------------------------------------
# visibility reference + pure-JAX fallback


def ragged_visibility_mask(page_tables, pos, q_lens, anc_mask,
                           page_size: int):
    """(B, S, L) bool visibility, L = max_pages x P: the REFERENCE
    semantics both paths implement. Cache row kpos is visible to query
    row t of slot b when it is committed (kpos < pos[b]) or lies in the
    slot's in-flight window (rel = kpos - pos[b] in [0, q_lens[b])) on
    t's visibility path (anc_mask[b, t, rel]). Everything else — padded
    window rows past q_len, stale rows from earlier wider launches, the
    null page — stays masked. Chunks pass a lower-triangular anc_mask
    (causal within the chunk); trees pass the ancestor-or-self
    relation; decode is the S=1 special case of either."""
    B, S, _ = anc_mask.shape
    L = page_tables.shape[1] * page_size
    kpos = jnp.arange(L)
    rel = jnp.broadcast_to(kpos[None, None, :] - pos[:, None, None],
                           (B, S, L))
    in_window = (rel >= 0) & (rel < q_lens[:, None, None])
    anc = jnp.take_along_axis(anc_mask, jnp.clip(rel, 0, S - 1), axis=2)
    return (kpos[None, None, :] < pos[:, None, None]) | (in_window & anc)


def tree_visibility_mask(page_tables, pos, anc_mask, page_size: int):
    """Tree-verify visibility (the pre-ragged name, kept as the test /
    fallback reference): all S window rows are live, so this is
    ragged_visibility_mask with q_lens = S."""
    B, S, _ = anc_mask.shape
    full = jnp.full((B,), S, jnp.int32)
    return ragged_visibility_mask(page_tables, pos, full, anc_mask,
                                  page_size)


def ragged_gather_attention(q, kc_pages, vc_pages, page_tables, pos,
                            q_lens, anc_mask, *, scale: float,
                            k_scales=None, v_scales=None):
    """Pure-JAX fallback AND numerical reference for the ragged kernel:
    gather every table-mapped page (`pool[page_table]`) and run dense
    masked dot-product attention under ragged_visibility_mask. q:
    (B, S, H, D); kc/vc_pages: (N, P, Hkv, D); page_tables:
    (B, max_pages) int32; pos/q_lens: (B,) int32; anc_mask: (B, S, S)
    bool. For a quantized pool, k_scales/v_scales are the (N, Hkv)
    per-page sidecar (paged/quant.py) and the gathered int8 pages are
    dequantized by the SAME table gather before the dense attention.
    Rows with no visible keys (padded entries) come out of the
    all-masked softmax as a uniform average — garbage a caller's
    q_len bookkeeping already discards, exactly like the kernel's
    zero rows."""
    B, S, _, D = q.shape
    Hkv = kc_pages.shape[2]
    P = kc_pages.shape[1]
    dt = q.dtype
    if k_scales is not None:
        from flexflow_tpu.paged.quant import dequantize_pages

        kg = dequantize_pages(kc_pages[page_tables],
                              k_scales[page_tables])
        vg = dequantize_pages(vc_pages[page_tables],
                              v_scales[page_tables])
    else:
        kg = kc_pages[page_tables]
        vg = vc_pages[page_tables]
    kg = kg.reshape(B, -1, Hkv, D)
    vg = vg.reshape(B, -1, Hkv, D)
    mask = ragged_visibility_mask(page_tables, pos, q_lens, anc_mask, P)
    from flexflow_tpu.ops.jax_ops import _dot_product_attention

    if k_scales is not None:
        # match the Pallas kernel's quantized discipline: compute the
        # whole attention in f32 (dequantized pages stay f32, q is
        # upcast) and cast only the output back — downcasting the
        # dequantized gather to a bf16 q dtype would re-quantize it
        out = _dot_product_attention(q.astype(jnp.float32), kg, vg,
                                     causal=False, scale=scale,
                                     mask=mask)
        return out.astype(dt)
    return _dot_product_attention(q, kg.astype(dt), vg.astype(dt),
                                  causal=False, scale=scale, mask=mask)


# ---------------------------------------------------------------------------
# the ragged Pallas kernel: grid (B, Hkv, page); page table, positions and
# query lengths prefetched; window visibility derived in-kernel


def _ragged_kernel(pt_ref, pos_ref, qlen_ref, q_ref, k_ref, v_ref,
                   anc_ref, o_ref, m_scr, l_scr, acc_scr, *, scale,
                   page_size, n_pages, window, ks_ref=None,
                   vs_ref=None):
    b, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qlen = qlen_ref[b]
    # pages wholly past the slot's visible horizon (committed prefix +
    # its own q_len window rows) contribute nothing, and padded batch
    # entries (q_len == 0) do no work at all — skip the MXU work
    # entirely (the masked-out math would be exp(-inf) = 0)
    @pl.when((j * page_size <= pos_ref[b] + qlen - 1) & (qlen > 0))
    def _():
        q = q_ref[...]                       # (rep, S, D)
        k = k_ref[...]                       # (P, D)
        v = v_ref[...]
        if ks_ref is not None:
            # quantized pool: this grid step's page/head scale rode in
            # as a (1, 1) block addressed by the SAME prefetched-table
            # index map as the page itself, so dequant-on-load is one
            # broadcast multiply in VMEM — the int8 page is what DMA'd
            # from HBM, the fp K/V never round-trips
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32) * ks_ref[0, 0]
            v = v.astype(jnp.float32) * vs_ref[0, 0]
        elif k.dtype != q.dtype:
            # mixed-precision pool (e.g. bf16 kv_dtype under an fp32
            # model): dot_general needs matching operand dtypes
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        s = lax.dot_general(q, k, (((2,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        # window visibility without a gather and without an HBM mask:
        # column c holds cache row j*P + c, i.e. window index
        # rel[c] = j*P + c - pos. One-hot it against the window rows
        # (zeroing indices past q_len) and contract with the (S, S)
        # anc relation: (anc @ onehot)[t, c] = anc[t, rel[c]] when
        # 0 <= rel[c] < q_len, else 0.
        col = j * page_size + lax.broadcasted_iota(
            jnp.int32, (window, page_size), 1)          # (S, P) abs row
        rel = col - pos_ref[b]
        krow = lax.broadcasted_iota(jnp.int32, (window, page_size), 0)
        onehot = ((rel == krow) & (krow < qlen)).astype(jnp.float32)
        tree_vis = lax.dot_general(
            anc_ref[...], onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) > 0.5   # (S, P)
        vis = (col < pos_ref[b]) | tree_vis
        s = jnp.where(vis[None], s, NEG_INF)
        m_prev = m_scr[:, :, 0:1]
        l_prev = l_scr[:, :, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=2, keepdims=True)
        pv = lax.dot_general(p.astype(v.dtype), v,
                             (((2,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # finalize UNCONDITIONALLY: a padded entry whose every page was
    # skipped must still write (zeros), not leave o_ref as garbage —
    # and rows at or past q_len are forced to zero even when they
    # accumulated prefix attention (they share the entry's pages, so
    # the compute loop cannot skip them row-wise)
    @pl.when(j == n_pages - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:, :, 0:1], 1e-30)
        live = lax.broadcasted_iota(jnp.int32, acc_scr.shape, 1) < qlen
        o_ref[...] = jnp.where(live, acc_scr[:] / l_safe,
                               0.0).astype(o_ref.dtype)


def _ragged_kernel_quant(pt_ref, pos_ref, qlen_ref, q_ref, k_ref, v_ref,
                         ks_ref, vs_ref, anc_ref, o_ref, m_scr, l_scr,
                         acc_scr, *, scale, page_size, n_pages, window):
    """Positional-arity shim for the quantized launch: same body, two
    extra (1, 1) scale blocks between the pool inputs and the anc
    relation (matching the in_specs order below)."""
    _ragged_kernel(pt_ref, pos_ref, qlen_ref, q_ref, k_ref, v_ref,
                   anc_ref, o_ref, m_scr, l_scr, acc_scr, scale=scale,
                   page_size=page_size, n_pages=n_pages, window=window,
                   ks_ref=ks_ref, vs_ref=vs_ref)


def ragged_flash_attention(q, kc_pages, vc_pages, page_tables, pos,
                           q_lens, anc_mask, *, scale: float,
                           interpret: bool = False, k_scales=None,
                           v_scales=None):
    """The ragged Pallas launch. q: (B, S, H, D) — S is the launch's
    window width, per-entry real work is q_lens[b] <= S rows;
    kc/vc_pages: (N, P, Hkv, D); page_tables: (B, max_pages); pos,
    q_lens: (B,); anc_mask: (B, S, S) bool window visibility. The page
    table, positions AND query lengths ride scalar prefetch, so each
    grid step's BlockSpec index map resolves `pt[b, j]` BEFORE the DMA
    and the horizon/padding skip predicates on prefetched scalars. The
    anc relation is one (S, S) VMEM block per batch entry — the only
    mask state, O(B*S^2) instead of the old (B, S, L) HBM add_mask.
    For a quantized pool, k_scales/v_scales are the (N, Hkv) sidecar;
    each grid step's (page, head) scale arrives as a (1, 1) block
    through the SAME pt[b, j] index map as its page, and the kernel
    dequantizes in VMEM (paged/quant.py has the layout story). Rows at
    or past q_lens[b] output zeros."""
    B, S, H, D = q.shape
    N, P, Hkv, _ = kc_pages.shape
    rep = H // Hkv
    n_pages = page_tables.shape[1]
    qr = q.transpose(0, 2, 1, 3).reshape(B, Hkv, rep, S, D)
    anc_f = anc_mask.astype(jnp.float32)

    in_specs = [
        pl.BlockSpec((None, None, rep, S, D),
                     lambda b, g, j, pt, ps, ql: (b, g, 0, 0, 0)),
        pl.BlockSpec((None, P, None, D),
                     lambda b, g, j, pt, ps, ql: (pt[b, j], 0, g, 0)),
        pl.BlockSpec((None, P, None, D),
                     lambda b, g, j, pt, ps, ql: (pt[b, j], 0, g, 0)),
    ]
    operands = [qr, kc_pages, vc_pages]
    kernel = _ragged_kernel
    if k_scales is not None:
        in_specs += [
            pl.BlockSpec((1, 1),
                         lambda b, g, j, pt, ps, ql: (pt[b, j], g)),
            pl.BlockSpec((1, 1),
                         lambda b, g, j, pt, ps, ql: (pt[b, j], g)),
        ]
        operands += [k_scales, v_scales]
        kernel = _ragged_kernel_quant
    in_specs.append(pl.BlockSpec((None, S, S),
                                 lambda b, g, j, pt, ps, ql: (b, 0, 0)))
    operands.append(anc_f)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Hkv, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, rep, S, D),
                               lambda b, g, j, pt, ps, ql: (b, g, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, S, LANES), jnp.float32),
            pltpu.VMEM((rep, S, LANES), jnp.float32),
            pltpu.VMEM((rep, S, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(kernel, scale=scale, page_size=P,
                          n_pages=n_pages, window=S),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, S, D), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), pos.astype(jnp.int32),
      q_lens.astype(jnp.int32), *operands)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, H, D)


# ---------------------------------------------------------------------------
# the ONE lowering entry: rope + page write + attend, for every variant


def ragged_paged_attention(q, k, v, cache_k, cache_v, page_tables, pos,
                           q_lens, depths, anc_mask, *, scale: float,
                           rope_theta: Optional[float] = None,
                           k_scales=None, v_scales=None):
    """The single paged-attention step every caller lowers to — decode,
    chunked prefill and tree verify are the same call with different
    descriptors (module docstring). Ropes q/k at pos + depths, scatters
    the live K/V rows into their table-mapped pages (rows past q_len or
    past the table land in the null page with the other garbage — a
    padded row must clobber neither a real row nor the pool bounds),
    then attends via the ragged kernel or the gather fallback behind
    the one availability gate.

    When k_scales/v_scales are passed, the pools are int8 and the write
    becomes quantize-on-append under grow-only per-(page, head) scales
    (paged/quant.py): the roped fp rows never reach HBM, and BOTH
    attention paths dequantize on load.

    Returns (attention output, new k pool, new v pool) — plus
    (new k_scales, new v_scales) in the quantized case. Output rows at
    or past q_lens[b] are garbage by contract (kernel: zeros; gather:
    an unmasked-softmax average) — callers index by their own q_len
    bookkeeping."""
    from flexflow_tpu.ops.jax_ops import apply_rope
    from flexflow_tpu.paged.quant import quantized_append

    B, S = q.shape[0], q.shape[1]
    P = cache_k.shape[1]
    pos_v = jnp.asarray(pos)
    qlen_v = jnp.asarray(q_lens)
    if rope_theta is not None:
        positions = pos_v[:, None] + depths                # (B, S)
        q = apply_rope(q, rope_theta, pos_offset=positions)
        k = apply_rope(k, rope_theta, pos_offset=positions)
    L = page_tables.shape[1] * P
    rows = pos_v[:, None] + jnp.arange(S)[None, :]         # (B, S)
    safe = jnp.minimum(rows, L - 1)
    bidx = jnp.arange(B)[:, None]
    page = page_tables[bidx, safe // P]                    # (B, S)
    live = (rows < L) & (jnp.arange(S)[None, :] < qlen_v[:, None])
    page = jnp.where(live, page, 0)
    off = safe % P
    if k_scales is not None:
        kc, ks = quantized_append(cache_k, k_scales, k, page, off, live)
        vc, vs = quantized_append(cache_v, v_scales, v, page, off, live)
    else:
        kc = cache_k.at[page, off].set(k.astype(cache_k.dtype))
        vc = cache_v.at[page, off].set(v.astype(cache_v.dtype))
        ks = vs = None

    force_interp = os.environ.get("FF_TPU_FLASH_INTERPRET") == "1"
    if paged_attention_available(q.shape[-1], P, interpret=force_interp,
                                 dtype=kc.dtype):
        out = ragged_flash_attention(q, kc, vc, page_tables, pos_v,
                                     qlen_v, anc_mask, scale=scale,
                                     interpret=force_interp,
                                     k_scales=ks, v_scales=vs)
    else:
        out = ragged_gather_attention(q, kc, vc, page_tables, pos_v,
                                      qlen_v, anc_mask, scale=scale,
                                      k_scales=ks, v_scales=vs)
    if k_scales is not None:
        return out, kc, vc, ks, vs
    return out, kc, vc


def chain_descriptor(batch: int, window: int):
    """The default (causal-chain) ragged descriptor: every window row
    live, row i at depth i, lower-triangular visibility — exactly the
    old kpos <= qpos chunk/decode semantics. Returns
    (q_lens, depths, anc_mask) as traced-constant jnp arrays."""
    q_lens = jnp.full((batch,), window, jnp.int32)
    depths = jnp.broadcast_to(jnp.arange(window, dtype=jnp.int32),
                              (batch, window))
    anc = jnp.broadcast_to(
        jnp.tril(jnp.ones((window, window), jnp.bool_)),
        (batch, window, window))
    return q_lens, depths, anc
