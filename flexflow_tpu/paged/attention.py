"""Paged decode attention: gather K/V through a page table.

Two selectable paths, chosen exactly the way ops/pallas/flash_attention
picks its kernel (backend probe + env kill switch + shape gate):

  * a Pallas TPU kernel whose grid walks (batch, kv head, page) with the
    page table and per-slot positions SCALAR-PREFETCHED, so each page's
    K/V block DMAs straight from its pooled HBM location into VMEM — no
    gathered copy of the sequence ever materializes. GQA is handled by
    grouping the q heads of one kv head into a single (rep, D) block, so
    kv pages are read once per GROUP (not per q head) and never repeated.
  * a pure-JAX `jnp.take` fallback (`pool[page_table]` gather + masked
    dot-product attention) that runs anywhere and is the reference the
    kernel is validated against.

A decode step is S=1; a chunked-prefill CHUNK is the same entry point
with S>1 (rows land at pos+i through the table, causal kpos <= qpos
mask), writing K/V straight into pool pages — there is no dense staging
prefill (scheduler.py).

A third path extends both for SPECULATIVE tree verify
(flexflow_tpu.spec): the step scores a whole token tree per slot in one
pass — S = max_nodes queries whose visibility is committed-rows plus the
query's own ancestor path (tree attention). The Pallas tree kernel
reuses the scalar-prefetched page walk with a per-page mask block; the
gather fallback is selected by the same availability gate.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128

logger = logging.getLogger(__name__)
_fallback_logged: set = set()


def _reject(reason: str) -> bool:
    """Log the CONCRETE kernel-rejection reason once per reason (the
    flash-attention selection discipline: a silent fallback looks like a
    10x paged-decode slowdown with no explanation in any log)."""
    if reason not in _fallback_logged:
        _fallback_logged.add(reason)
        logger.info(
            "paged attention: Pallas kernel rejected (%s); using the "
            "jnp.take gather fallback", reason)
    return False


def paged_attention_available(head_dim: int, page_size: int,
                              interpret: bool = False,
                              dtype=jnp.float32) -> bool:
    """True when the Pallas paged kernel supports these shapes on this
    backend. FF_TPU_NO_PAGED=1 disables the kernel everywhere (A/B runs
    and kernel-bug escape hatch, like FF_TPU_NO_FLASH). On real TPUs the
    head dim must be a lane multiple (the kernel reads lane-aligned D
    blocks; smaller head dims take the gather fallback, mirroring the
    flash bshd gate) and pages must tile the sublane dim AT THE POOL'S
    DTYPE — (8, 128) tiles for fp32 but (16, 128) for bf16/fp16 and
    (32, 128) for int8/fp8, so a bf16 pool needs page_size % 16 == 0.
    Rejections log their concrete reason once (head_dim/page_size/dtype/
    backend) instead of silently falling back."""
    if os.environ.get("FF_TPU_NO_PAGED") == "1":
        return _reject("FF_TPU_NO_PAGED=1 kill switch set")
    if interpret:
        return True
    dt = jnp.dtype(dtype)
    itemsize = dt.itemsize
    if itemsize > 4:
        return _reject(
            f"pool dtype {dt.name} is 8-byte (no TPU tiling story)")
    sublane = 8 * (4 // max(itemsize, 1))
    if head_dim % LANES != 0:
        return _reject(
            f"head_dim={head_dim} is not a multiple of the {LANES}-lane "
            "tile")
    if page_size % sublane != 0:
        return _reject(
            f"page_size={page_size} does not tile the {sublane}-row "
            f"sublane dim at pool dtype {dt.name}")
    if jax.default_backend() != "tpu":
        return _reject(f"backend is {jax.default_backend()!r}, not tpu")
    return True


# ---------------------------------------------------------------------------
# pure-JAX fallback (and numerical reference)


def paged_gather_attention(q, kc_pages, vc_pages, page_tables, pos, *,
                           scale: float):
    """q: (B, S, H, D); kc/vc_pages: (N, P, Hkv, D); page_tables:
    (B, max_pages) int32; pos: (B,) int32 — the absolute position of each
    row's FIRST query token. Gathers every table-mapped page and attends
    with the same absolute-position mask as the dense cached path (rows
    past a slot's write head — including everything in the null page —
    stay masked)."""
    B, S, _, D = q.shape
    Hkv = kc_pages.shape[2]
    dt = q.dtype
    kg = kc_pages[page_tables].reshape(B, -1, Hkv, D)
    vg = vc_pages[page_tables].reshape(B, -1, Hkv, D)
    qpos = pos[:, None] + jnp.arange(S)[None, :]            # (B, S)
    kpos = jnp.arange(kg.shape[1])                          # (T,)
    mask = kpos[None, None, :] <= qpos[:, :, None]          # (B, S, T)
    from flexflow_tpu.ops.jax_ops import _dot_product_attention

    return _dot_product_attention(q, kg.astype(dt), vg.astype(dt),
                                  causal=False, scale=scale, mask=mask)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (B, Hkv, n_pages); page table + positions prefetched


def _paged_decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale, page_size,
                         n_pages):
    b, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # pages wholly past the slot's write head contribute nothing — skip
    # their MXU work entirely (the masked-out math would be exp(-inf)=0)
    @pl.when(j * page_size <= pos_ref[b])
    def _():
        q = q_ref[...]                       # (rep, D)
        k = k_ref[...]                       # (P, D)
        v = v_ref[...]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        kpos = j * page_size + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos <= pos_ref[b], s, NEG_INF)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(p.astype(v.dtype), v,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_pages - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:, 0:1], 1e-30)
        o_ref[...] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_flash_decode(q, kc_pages, vc_pages, page_tables, pos, *,
                       scale: float, interpret: bool = False):
    """Pallas paged-attention decode step. q: (B, 1, H, D); kc/vc_pages:
    (N, P, Hkv, D); page_tables: (B, max_pages); pos: (B,). The page
    table rides scalar prefetch, so each grid step's BlockSpec index map
    resolves `pt[b, j]` BEFORE the DMA — K/V stream page-by-page from
    their pooled locations."""
    B, S, H, D = q.shape
    if S != 1:
        raise ValueError(f"paged decode is single-token (S=1), got S={S}")
    N, P, Hkv, _ = kc_pages.shape
    rep = H // Hkv
    n_pages = page_tables.shape[1]
    qr = q[:, 0].reshape(B, Hkv, rep, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, n_pages),
        in_specs=[
            pl.BlockSpec((None, None, rep, D),
                         lambda b, g, j, pt, ps: (b, g, 0, 0)),
            pl.BlockSpec((None, P, None, D),
                         lambda b, g, j, pt, ps: (pt[b, j], 0, g, 0)),
            pl.BlockSpec((None, P, None, D),
                         lambda b, g, j, pt, ps: (pt[b, j], 0, g, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, rep, D),
                               lambda b, g, j, pt, ps: (b, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, LANES), jnp.float32),
            pltpu.VMEM((rep, LANES), jnp.float32),
            pltpu.VMEM((rep, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale, page_size=P,
                          n_pages=n_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), pos.astype(jnp.int32), qr,
      kc_pages, vc_pages)
    return out.reshape(B, 1, H, D)


# ---------------------------------------------------------------------------
# the lowering entry: rope + page write + attend (mirrors cached_attention)


def paged_cached_attention(q, k, v, cache_k, cache_v, page_tables, pos, *,
                           scale: float, rope_theta: Optional[float] = None):
    """One paged decode step OR one chunked-prefill chunk, the drop-in
    analog of ops.jax_ops.cached_attention: rope at absolute positions
    pos + i, scatter the new K/V rows into their table-mapped pages,
    attend over everything written so far (kpos <= qpos). S=1 is the
    per-tick decode step; S>1 is a prefill CHUNK writing straight into
    pool pages (Executor.chunked_prefill_fn) — chunk lengths mix freely
    across ticks, each compiles once per bucket. Idle slots (page table
    all-null, pos 0) write into the null page and read garbage that
    their mask discards; padded chunk rows past the table's last row are
    redirected to the null page (their positions are garbage anyway and
    later writes overwrite the in-range ones).

    Returns (attention output, new k pool, new v pool)."""
    from flexflow_tpu.ops.jax_ops import apply_rope

    B, S = q.shape[0], q.shape[1]
    P = cache_k.shape[1]
    pos_v = jnp.asarray(pos)
    if rope_theta is not None:
        offs = pos_v if S == 1 else pos_v[:, None] + jnp.arange(S)[None, :]
        q = apply_rope(q, rope_theta, pos_offset=offs)
        k = apply_rope(k, rope_theta, pos_offset=offs)
    L = page_tables.shape[1] * P
    rows = pos_v[:, None] + jnp.arange(S)[None, :]        # (B, S)
    safe = jnp.minimum(rows, L - 1)
    bidx = jnp.arange(B)[:, None]
    page = page_tables[bidx, safe // P]                   # (B, S)
    # rows past the table (padded chunk tails) must not clobber the last
    # real row — dump them in the null page with the other garbage
    page = jnp.where(rows < L, page, 0)
    off = safe % P
    kc = cache_k.at[page, off].set(k.astype(cache_k.dtype))
    vc = cache_v.at[page, off].set(v.astype(cache_v.dtype))

    force_interp = os.environ.get("FF_TPU_FLASH_INTERPRET") == "1"
    avail = paged_attention_available(q.shape[-1], P, interpret=force_interp,
                                      dtype=kc.dtype)
    if S == 1:
        if avail:
            out = paged_flash_decode(q, kc, vc, page_tables, pos_v,
                                     scale=scale, interpret=force_interp)
        else:
            out = paged_gather_attention(q, kc, vc, page_tables, pos_v,
                                         scale=scale)
    elif avail:
        # a chunk is a degenerate token tree (one chain): reuse the tree
        # kernel's scalar-prefetched page walk with the causal chunk mask
        kpos = jnp.arange(L)
        qpos = pos_v[:, None] + jnp.arange(S)[None, :]
        mask = kpos[None, None, :] <= qpos[:, :, None]    # (B, S, L)
        out = paged_tree_verify(q, kc, vc, page_tables, pos_v, mask,
                                scale=scale, interpret=force_interp)
    else:
        out = paged_gather_attention(q, kc, vc, page_tables, pos_v,
                                     scale=scale)
    return out, kc, vc


# ---------------------------------------------------------------------------
# speculative tree verify (flexflow_tpu.spec): score a token tree per slot
# in ONE pass. Tree node j's K/V row lands at cache row pos + j; queries
# see committed rows (kpos < pos) plus their own ancestor path.


def tree_visibility_mask(page_tables, pos, anc_mask, page_size: int):
    """(B, T, L) bool visibility for tree verify, L = max_pages x P.
    anc_mask is the (B, T, T) ancestor-or-self relation of the flattened
    tree; row kpos is visible to query q when it is committed
    (kpos < pos) or holds a tree node on q's root path. Everything else —
    padding nodes' rows, stale rows from earlier (wider) trees, the null
    page — stays masked."""
    B, T, _ = anc_mask.shape
    L = page_tables.shape[1] * page_size
    kpos = jnp.arange(L)
    rel = jnp.broadcast_to(kpos[None, None, :] - pos[:, None, None],
                           (B, T, L))
    in_tree = (rel >= 0) & (rel < T)
    anc = jnp.take_along_axis(anc_mask, jnp.clip(rel, 0, T - 1), axis=2)
    return (kpos[None, None, :] < pos[:, None, None]) | (in_tree & anc)


def paged_tree_gather_attention(q, kc_pages, vc_pages, page_tables, mask, *,
                                scale: float):
    """Pure-JAX tree-verify reference: gather every table-mapped page and
    attend under the precomputed (B, T, L) visibility mask. q is
    (B, T, H, D) — T tree nodes, not sequence positions."""
    B, T, _, D = q.shape
    Hkv = kc_pages.shape[2]
    dt = q.dtype
    kg = kc_pages[page_tables].reshape(B, -1, Hkv, D)
    vg = vc_pages[page_tables].reshape(B, -1, Hkv, D)
    from flexflow_tpu.ops.jax_ops import _dot_product_attention

    return _dot_product_attention(q, kg.astype(dt), vg.astype(dt),
                                  causal=False, scale=scale, mask=mask)


def _paged_tree_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, mask_ref,
                       o_ref, m_scr, l_scr, acc_scr, *, scale, page_size,
                       n_pages, tree):
    b, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # visible rows reach at most pos + tree - 1 (committed prefix + the
    # tree's own rows); pages wholly past that contribute nothing
    @pl.when(j * page_size <= pos_ref[b] + tree - 1)
    def _():
        q = q_ref[...]                       # (rep, T, D)
        k = k_ref[...]                       # (P, D)
        v = v_ref[...]
        s = lax.dot_general(q, k, (((2,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        s = s + mask_ref[...][None]          # additive (T, P) mask block
        m_prev = m_scr[:, :, 0:1]
        l_prev = l_scr[:, :, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=2, keepdims=True)
        pv = lax.dot_general(p.astype(v.dtype), v,
                             (((2,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_pages - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:, :, 0:1], 1e-30)
        o_ref[...] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_tree_verify(q, kc_pages, vc_pages, page_tables, pos, mask, *,
                      scale: float, interpret: bool = False):
    """Pallas tree-verify step. q: (B, T, H, D) tree-node queries;
    kc/vc_pages: (N, P, Hkv, D); mask: (B, T, L) bool visibility
    (tree_visibility_mask). Same scalar-prefetched page walk as
    paged_flash_decode — each grid step DMAs one page's K/V from its
    pooled HBM location — plus one (T, P) mask block per page, so the
    gathered sequence never materializes and the tree structure rides a
    VMEM-resident additive mask."""
    B, T, H, D = q.shape
    N, P, Hkv, _ = kc_pages.shape
    rep = H // Hkv
    n_pages = page_tables.shape[1]
    qr = q.transpose(0, 2, 1, 3).reshape(B, Hkv, rep, T, D)
    add_mask = jnp.where(mask, 0.0, NEG_INF).astype(jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, n_pages),
        in_specs=[
            pl.BlockSpec((None, None, rep, T, D),
                         lambda b, g, j, pt, ps: (b, g, 0, 0, 0)),
            pl.BlockSpec((None, P, None, D),
                         lambda b, g, j, pt, ps: (pt[b, j], 0, g, 0)),
            pl.BlockSpec((None, P, None, D),
                         lambda b, g, j, pt, ps: (pt[b, j], 0, g, 0)),
            pl.BlockSpec((None, T, P),
                         lambda b, g, j, pt, ps: (b, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, None, rep, T, D),
                               lambda b, g, j, pt, ps: (b, g, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, T, LANES), jnp.float32),
            pltpu.VMEM((rep, T, LANES), jnp.float32),
            pltpu.VMEM((rep, T, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_tree_kernel, scale=scale, page_size=P,
                          n_pages=n_pages, tree=T),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, T, D), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), pos.astype(jnp.int32), qr,
      kc_pages, vc_pages, add_mask)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, D)


def paged_cached_tree_attention(q, k, v, cache_k, cache_v, page_tables,
                                pos, depths, anc_mask, *, scale: float,
                                rope_theta: Optional[float] = None):
    """One speculative TREE-VERIFY step — the multi-node analog of
    paged_cached_attention. q/k/v carry T tree nodes per slot; node j's
    rope position is pos + depths[b, j] (siblings share a depth, so
    alternative branches are scored at the SAME absolute position), its
    K/V row is written at cache row pos + j, and attention runs under the
    ancestor visibility mask. Accept/rollback afterwards is pure index
    bookkeeping: the scheduler copies the accepted path's rows onto the
    contiguous committed positions (Executor.paged_commit_fn) and
    advances pos — rejected rows sit past the new write head, masked
    exactly like any stale page content.

    Returns (attention output, new k pool, new v pool)."""
    from flexflow_tpu.ops.jax_ops import apply_rope

    B, T = q.shape[0], q.shape[1]
    P = cache_k.shape[1]
    pos_v = jnp.asarray(pos)
    positions = pos_v[:, None] + depths                    # (B, T)
    if rope_theta is not None:
        q = apply_rope(q, rope_theta, pos_offset=positions)
        k = apply_rope(k, rope_theta, pos_offset=positions)
    L = page_tables.shape[1] * P
    rows = jnp.minimum(pos_v[:, None] + jnp.arange(T)[None, :], L - 1)
    bidx = jnp.arange(B)[:, None]
    page = page_tables[bidx, rows // P]                    # (B, T)
    off = rows % P
    kc = cache_k.at[page, off].set(k.astype(cache_k.dtype))
    vc = cache_v.at[page, off].set(v.astype(cache_v.dtype))

    mask = tree_visibility_mask(page_tables, pos_v, anc_mask, P)
    force_interp = os.environ.get("FF_TPU_FLASH_INTERPRET") == "1"
    if paged_attention_available(q.shape[-1], P, interpret=force_interp,
                                 dtype=kc.dtype):
        out = paged_tree_verify(q, kc, vc, page_tables, pos_v, mask,
                                scale=scale, interpret=force_interp)
    else:
        out = paged_tree_gather_attention(q, kc, vc, page_tables, mask,
                                          scale=scale)
    return out, kc, vc
