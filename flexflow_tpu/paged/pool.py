"""Host-side page-pool bookkeeping for the paged KV cache.

All allocation state is plain numpy/python on the host; the device only
ever sees int32 page tables (one row per decode slot), so the jitted
decode step stays a single compiled program regardless of which requests
hold which pages. Page 0 is reserved as the NULL page: unallocated page
table entries point at it, and idle decode slots write their garbage
K/V row into it (those rows sit past every live request's position and
are masked by the absolute-position attention mask).

Pages are REFCOUNTED and CONTENT-ADDRESSED (vLLM-style prefix caching):
a sha1 hash chain over page-aligned token blocks names each full page by
the entire token prefix it closes, so two requests whose prompts share a
page-aligned prefix map the SAME physical pages (refcount counts the
mappers). A page whose refcount drops to zero is not erased: if it is
hash-registered it parks on an LRU dead list — still addressable as a
cache hit, reclaimed lazily when a fresh allocation needs it. Partially
filled tail pages are registered under (parent chain hash, tail tokens)
and are served copy-on-write: a hit clones the rows into a private page
before the new owner writes past them (paged/scheduler.py owns the
device copy; the pool only does the bookkeeping).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

# chain hash of the empty prefix (parent of the first block)
EMPTY_HASH = hashlib.sha1().hexdigest()


class PagePool:
    """Fixed-size page allocator over `num_pages` KV pages of `page_size`
    tokens each. Page 0 is never handed out (the null page), so usable
    capacity is `num_pages - 1` pages."""

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_seq: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (one is the null page), "
                             f"got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = int(max_pages_per_seq)
        # LIFO free list: freshly freed pages are reused first (their HBM
        # is warm) — order is a host-side detail, invisible to the device
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs: Dict[int, int] = {}          # page id -> refcount > 0
        # dead-but-cached pages, oldest first (refcount 0, still indexed);
        # an OrderedDict so revival and LRU eviction are both O(1)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # content addressing: chain hash -> page for FULL blocks; parent
        # chain hash -> (page, tail tokens) for the partial tail block.
        # _keys_of tracks every index entry naming a page, for O(1)
        # unregister on eviction and id rewrite on defrag.
        self._full: Dict[str, int] = {}
        self._partial: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
        self._keys_of: Dict[int, List[Tuple[str, str]]] = {}
        # prefix-cache counters (served by scheduler/server metrics)
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.hits = 0          # lookups that mapped at least one row
        self.misses = 0
        self.evictions = 0     # cached pages reclaimed for fresh allocs
        # host-memory tier (disagg/host_tier.py), attached lazily: dead-
        # list evictions SPILL full pages' payloads instead of dropping
        # them, and lookups transparently FETCH spilled hashes back into
        # fresh pages. The pool only moves bookkeeping; payloads travel
        # through the attached reader/writer closures.
        self._tier = None
        self._tier_read = None   # page id -> opaque payload (+ scales)
        self._tier_write = None  # (page id, payload) -> None
        self.spilled_pages = 0   # pages pushed to the tier (evict+handoff)
        self.fetched_pages = 0   # pages pulled back from the tier

    # -- accounting -----------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        """Allocatable pages: truly free + dead-but-cached (the LRU list
        is reclaimed lazily, so admission math treats it as free)."""
        return len(self._free) + len(self._lru)

    @property
    def pages_in_use(self) -> int:
        """Live (refcount > 0) pages — shared pages count ONCE; that is
        the whole point of prefix sharing."""
        return self.capacity - self.free_pages

    @property
    def cached_pages(self) -> int:
        return len(self._lru)

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    # -- host-memory tier (disagg) ---------------------------------------

    @property
    def tier(self):
        """The attached HostTier, or None (untired pool — evictions
        drop, lookups never fetch; the pre-disagg behaviour)."""
        return self._tier

    def attach_tier(self, tier, read_page, write_page) -> None:
        """Arm the host tier: `read_page(page) -> payload` snapshots one
        device page's rows AND its scale-sidecar entries into an opaque
        host payload; `write_page(page, payload)` restores one. The
        scheduler supplies device_get/device_put closures; the poolcheck
        model supplies its bookkeeping mirrors. Attach before the pool
        serves traffic — the closures run inside alloc()/lookup()."""
        if tier is None or read_page is None or write_page is None:
            raise ValueError(
                "attach_tier needs a tier and both payload closures")
        self._tier = tier
        self._tier_read = read_page
        self._tier_write = write_page

    def _spill_page(self, page: int) -> int:
        """Push `page`'s payload into the tier under every FULL chain
        hash naming it (a hash-addressed page is its payload — partial
        tail entries are COW hints and just drop). Returns the number of
        tier entries written. The caller unregisters afterwards, so the
        hash is never resident and spilled at once."""
        if self._tier is None:
            return 0
        fulls = [h for kind, h in self._keys_of.get(page, ())
                 if kind == "full"]
        if not fulls:
            return 0
        payload = self._tier_read(page)
        for h in fulls:
            self._tier.spill(h, payload)
        self.spilled_pages += len(fulls)
        return len(fulls)

    def _fetch_full(self, chain_hash: str) -> Optional[int]:
        """Pull one spilled full page back: pop the tier entry (move
        semantics — a fetched hash leaves the tier), allocate a device
        page, restore the payload (scales included), and re-register the
        hash. Returns the page PINNED at refcount 1 (the allocation is
        the lookup's retain), or None when the pool is too full to land
        it (the tier entry is rolled back — still fetchable later)."""
        payload = self._tier.fetch(chain_hash)
        if payload is None:
            return None  # raced a tier-capacity drop
        got = self.alloc(1)  # may itself evict-and-spill the LRU oldest
        if got is None:
            self._tier.unfetch(chain_hash, payload)
            return None
        page = got[0]
        self._tier_write(page, payload)
        self._full[chain_hash] = page
        self._keys_of.setdefault(page, []).append(("full", chain_hash))
        self.fetched_pages += 1
        return page

    def spill_request(self, pages: List[int]) -> int:
        """Handoff spill (disagg/workers.py): push every full-registered
        page of a request into the tier and UNREGISTER it here — the
        pages' content moves to host RAM where another server's pool can
        fetch it, and this pool's hash index stays disjoint from the
        tier's. The caller still holds the refcounts and frees the now
        index-less pages normally (they return to the free list).
        Returns tier entries written. Requires an attached tier."""
        if self._tier is None:
            raise RuntimeError("spill_request needs an attached tier")
        moved = 0
        for p in pages:
            moved += self._spill_page(p)
            self._unregister(p)
        return moved

    def spill_oldest(self) -> Optional[int]:
        """Force-spill the OLDEST dead-cached page (the next eviction
        victim) to the tier ahead of allocation pressure — the proactive
        variant of alloc()'s spill, used by the poolcheck `spill` op and
        available to background pressure-relief. Returns the freed page
        id, or None when nothing is dead-cached or no tier is armed."""
        if self._tier is None or not self._lru:
            return None
        p, _ = self._lru.popitem(last=False)
        self._spill_page(p)
        self._unregister(p)
        self._free.append(p)
        return p

    def prefetch(self, chain_hash: str) -> Optional[int]:
        """Pull one spilled hash back WITHOUT pinning it: the fetched
        page parks dead-cached (registered, refcount 0 — LRU newest), so
        a later lookup hits it at device speed. The poolcheck `fetch` op
        and warm-up paths use this. Returns the page id or None."""
        if self._tier is None or not self._tier.contains(chain_hash):
            return None
        page = self._fetch_full(chain_hash)
        if page is None:
            return None
        self.free([page])  # registered: parks on the LRU dead list
        return page

    def fragmentation(self) -> float:
        """Hole fraction of the occupied span: 1 - occupied/span where
        span reaches the highest non-free page. 0.0 when compact (or
        empty); defrag drives it back to 0."""
        # metrics threads (server.metrics(), the HTTP endpoint) call this
        # while the scheduler thread allocates/frees; dict iteration can
        # race a resize, so retry the cheap snapshot instead of locking
        # the hot path
        for _ in range(8):
            try:
                used = set(self._refs) | set(self._lru)
                break
            except RuntimeError:  # dict resized mid-iteration
                continue
        else:
            return 0.0
        if not used:
            return 0.0
        return 1.0 - len(used) / max(used)

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold `n_tokens` cache rows."""
        return -(-int(n_tokens) // self.page_size)

    # -- content addressing ---------------------------------------------

    def chain_hashes(self, tokens) -> List[str]:
        """Chain hash of every FULL page-aligned block of `tokens`:
        entry i names blocks 0..i — the whole prefix, not just block i —
        so equal hashes mean equal prefixes (position is implicit)."""
        toks = np.asarray(tokens, np.int32)
        h = hashlib.sha1()
        out = []
        P = self.page_size
        for i in range(len(toks) // P):
            h.update(toks[i * P:(i + 1) * P].tobytes())
            out.append(h.hexdigest())
        return out

    def _is_free(self, page: int) -> bool:
        """Neither refcounted nor dead-cached — O(1), unlike a `_free`
        list scan (publication runs per page boundary on the hot loop)."""
        return page not in self._refs and page not in self._lru

    def register_full(self, page: int, chain_hash: str) -> None:
        """Publish a fully written page under its prefix chain hash.
        First writer wins — an existing entry keeps its page (the rows
        are identical by construction; re-pointing would orphan refs)."""
        if self._is_free(page) or chain_hash in self._full:
            return
        self._full[chain_hash] = page
        self._keys_of.setdefault(page, []).append(("full", chain_hash))
        if self._tier is not None:
            # a writer recomputed this prefix while a spilled copy sat in
            # the tier: residency wins, the tier entry drops — resident ⊎
            # spilled stays a true partition of the hash index
            self._tier.drop(chain_hash)

    def register_partial(self, page: int, parent_hash: str,
                         tokens) -> None:
        """Publish a partially filled tail page: rows [0, len(tokens))
        hold the K/V of `tokens` continuing the `parent_hash` prefix.
        Latest wins (the entry is a hint, hits are COW-copied anyway)."""
        toks = tuple(int(t) for t in tokens)
        if self._is_free(page) or not toks or len(toks) >= self.page_size:
            return
        prev = self._partial.get(parent_hash)
        if prev is not None and prev[0] != page:
            keys = self._keys_of.get(prev[0])
            if keys and ("partial", parent_hash) in keys:
                keys.remove(("partial", parent_hash))
            if not keys and prev[0] in self._lru:
                # the displaced donor lost its last index entry: it can
                # never hit again, so free it rather than let it squat
                # in the LRU ahead of genuinely hittable pages
                del self._lru[prev[0]]
                self._keys_of.pop(prev[0], None)
                self._free.append(prev[0])
        self._partial[parent_hash] = (page, toks)
        keys = self._keys_of.setdefault(page, [])
        if ("partial", parent_hash) not in keys:
            keys.append(("partial", parent_hash))

    def lookup(self, tokens) -> Tuple[List[int], int, Optional[int]]:
        """Map the longest cached prefix of `tokens`. Returns
        (full_pages, cached_tokens, cow_page):

          full_pages — one page per matched FULL block, refcount bumped
          (revived from the LRU dead list when necessary);
          cached_tokens — rows covered: len(full_pages) * page_size plus
          any tail rows matched in cow_page;
          cow_page — a partial tail page whose leading rows continue the
          matched prefix, refcount bumped. The CALLER must clone its rows
          into a private page before anyone writes past them and then
          free() this reference (copy-on-write).

        Every returned page is pinned (refcounted) until freed."""
        toks = np.asarray(tokens, np.int32)
        n = len(toks)
        self.lookup_tokens += n
        chain = self.chain_hashes(toks)
        pages: List[int] = []
        parent = EMPTY_HASH
        for h in chain:
            p = self._full.get(h)
            if p is not None:
                # pin AS we walk (not after): a tier fetch further down
                # the chain allocates, and allocation may evict exactly
                # the dead-cached pages this walk already matched
                self._retain(p)
                if self._tier is not None:
                    # residency wins over a spilled twin: a SHARED tier
                    # (disagg handoff) can re-receive a prefix this pool
                    # still holds — e.g. the prefill worker re-spills a
                    # repeat prompt the decode pool never released. Drop
                    # the duplicate so resident ⊎ spilled is a partition
                    # again once the walk that observed it completes.
                    self._tier.drop(h)
            elif self._tier is not None and self._tier.contains(h):
                # transparent fetch: the prefix was spilled, not lost —
                # _fetch_full re-registers it and returns it pinned
                p = self._fetch_full(h)
            if p is None:
                break
            pages.append(p)
            parent = h
        cached = len(pages) * self.page_size
        cow_page = None
        # wherever the full-chain match stopped, a registered partial
        # tail continuing the matched prefix can still serve its leading
        # rows (identical prompts, prompt extensions, resume)
        if cached < n:
            ent = self._partial.get(parent)
            if ent is not None:
                pg, ptoks = ent
                rest = toks[cached:]
                m = 0
                for a, b in zip(rest, ptoks):
                    if int(a) != int(b):
                        break
                    m += 1
                if m > 0:
                    cow_page = pg
                    cached += m
        if cow_page is not None:
            self._retain(cow_page)
        self.hit_tokens += cached
        if cached > 0:
            self.hits += 1
        else:
            self.misses += 1
        return pages, cached, cow_page

    def _retain(self, page: int) -> None:
        self._refs[page] = self._refs.get(page, 0) + 1
        self._lru.pop(page, None)  # revive a dead-cached page

    def _unregister(self, page: int) -> None:
        for kind, h in self._keys_of.pop(page, []):
            if kind == "full" and self._full.get(h) == page:
                del self._full[h]
            elif kind == "partial" and \
                    self._partial.get(h, (None,))[0] == page:
                del self._partial[h]

    # -- alloc / free ---------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate `n` PRIVATE pages (refcount 1), or None when the pool
        cannot satisfy the request (callers queue or preempt — never
        partial). Truly free pages first; then the oldest dead-but-cached
        pages are evicted (their hash entries drop — a future lookup of
        that prefix misses and recomputes)."""
        if n > self.free_pages:
            return None
        pages = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
            else:
                p, _ = self._lru.popitem(last=False)  # oldest first
                # with a host tier armed, eviction SPILLS instead of
                # dropping: the payload moves to host RAM under its
                # chain hashes, then the hash leaves the resident index
                self._spill_page(p)
                self._unregister(p)
                self.evictions += 1
            self._refs[p] = 1
            pages.append(p)
        return pages

    def free(self, pages: List[int]) -> None:
        """Drop one reference per page. At refcount 0 a hash-registered
        page parks on the LRU dead list (reusable as a cache hit); an
        unregistered one returns to the free list."""
        for p in pages:
            r = self._refs.get(p)
            if r is None:
                continue
            if r > 1:
                self._refs[p] = r - 1
                continue
            del self._refs[p]
            if self._keys_of.get(p):
                self._lru[p] = None  # newest at the end
            else:
                self._keys_of.pop(p, None)
                self._free.append(p)

    # -- defrag ---------------------------------------------------------

    def defrag(self) -> tuple:
        """Compact occupied pages (live AND dead-cached) to the low end
        of the pool. Returns (perm, old_to_new):

          perm[new_id] = old_id  — gather indices for moving the DEVICE
          pool buffers (`new_pool = old_pool[perm]`);
          old_to_new[old_id]     — rewrite for every live page table
          (`table = old_to_new[table]`; null stays null).

        Every owner's table AND the hash index are rewritten: the caller
        applies old_to_new to each slot's table row and every request's
        page list; the pool rewrites refcounts, the LRU list (order
        preserved) and the content-address indexes here. Pure bookkeeping
        on this side; the caller owns applying the device gather
        atomically (the scheduler does this between decode ticks, when no
        jitted program is in flight)."""
        allocated = sorted(set(self._refs) | set(self._lru))
        perm = np.arange(self.num_pages, dtype=np.int32)
        old_to_new = np.arange(self.num_pages, dtype=np.int32)
        for new_id, old_id in enumerate(allocated, start=1):
            perm[new_id] = old_id
            old_to_new[old_id] = new_id
        # remaining slots of perm point at the (now free) old pages, keeping
        # perm a true permutation; their content is garbage either way
        occupied = set(allocated)
        free_old = [p for p in range(1, self.num_pages)
                    if p not in occupied]
        for i, old_id in zip(range(len(allocated) + 1, self.num_pages),
                             free_old):
            perm[i] = old_id
        remap = lambda p: int(old_to_new[p])  # noqa: E731
        self._refs = {remap(p): r for p, r in self._refs.items()}
        self._lru = OrderedDict((remap(p), None) for p in self._lru)
        self._keys_of = {remap(p): ks for p, ks in self._keys_of.items()}
        self._full = {h: remap(p) for h, p in self._full.items()}
        self._partial = {h: (remap(p), t)
                         for h, (p, t) in self._partial.items()}
        self._free = list(range(self.num_pages - 1, len(allocated), -1))
        return perm, old_to_new

    def check_invariants(self, owners: Optional[dict] = None) -> None:
        """Debug hook: assert the declarative invariant catalog
        (analysis/pool_invariants.py, rendered in docs/paged.md) over
        the current bookkeeping state. `owners` is an optional
        {owner_id: [pages]} map of every live page list, enabling the
        refcount-equals-owner-references check. Raises AssertionError
        naming every violated invariant. O(pages + index entries) —
        cheap enough for tests after every op (tests/test_paged.py's
        fuzz harness), too hot for the serving loop."""
        from flexflow_tpu.analysis import pool_invariants  # lazy: no cycle
        violations = pool_invariants.check_pool(self, owners)
        if violations:
            raise AssertionError(
                "PagePool invariant violation(s):\n  "
                + "\n  ".join(violations))
