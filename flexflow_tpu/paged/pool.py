"""Host-side page-pool bookkeeping for the paged KV cache.

All allocation state is plain numpy/python on the host; the device only
ever sees int32 page tables (one row per decode slot), so the jitted
decode step stays a single compiled program regardless of which requests
hold which pages. Page 0 is reserved as the NULL page: unallocated page
table entries point at it, and idle decode slots write their garbage
K/V row into it (those rows sit past every live request's position and
are masked by the absolute-position attention mask).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class PagePool:
    """Fixed-size page allocator over `num_pages` KV pages of `page_size`
    tokens each. Page 0 is never handed out (the null page), so usable
    capacity is `num_pages - 1` pages."""

    def __init__(self, num_pages: int, page_size: int,
                 max_pages_per_seq: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (one is the null page), "
                             f"got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = int(max_pages_per_seq)
        # LIFO free list: freshly freed pages are reused first (their HBM
        # is warm) — order is a host-side detail, invisible to the device
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._owner: Dict[int, int] = {}  # page id -> owner token

    # -- accounting -----------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.capacity - len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold `n_tokens` cache rows."""
        return -(-int(n_tokens) // self.page_size)

    # -- alloc / free ---------------------------------------------------

    def alloc(self, n: int, owner: int = -1) -> Optional[List[int]]:
        """Allocate `n` pages for `owner`, or None when the pool cannot
        satisfy the request (callers queue or preempt — never partial)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._owner[p] = owner
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p in self._owner:
                del self._owner[p]
                self._free.append(p)

    # -- defrag ---------------------------------------------------------

    def defrag(self) -> tuple:
        """Compact allocated pages to the low end of the pool. Returns
        (perm, old_to_new):

          perm[new_id] = old_id  — gather indices for moving the DEVICE
          pool buffers (`new_pool = old_pool[perm]`);
          old_to_new[old_id]     — rewrite for every live page table
          (`table = old_to_new[table]`; null stays null).

        Pure bookkeeping here; the caller owns applying both sides
        atomically (the scheduler does this between decode ticks, when no
        jitted program is in flight)."""
        allocated = sorted(self._owner)
        perm = np.arange(self.num_pages, dtype=np.int32)
        old_to_new = np.arange(self.num_pages, dtype=np.int32)
        new_owner: Dict[int, int] = {}
        for new_id, old_id in enumerate(allocated, start=1):
            perm[new_id] = old_id
            old_to_new[old_id] = new_id
            new_owner[new_id] = self._owner[old_id]
        # remaining slots of perm point at the (now free) old pages, keeping
        # perm a true permutation; their content is garbage either way
        free_old = [p for p in range(1, self.num_pages)
                    if p not in self._owner]
        for i, old_id in zip(range(len(allocated) + 1, self.num_pages),
                             free_old):
            perm[i] = old_id
        self._owner = new_owner
        self._free = list(range(self.num_pages - 1, len(allocated), -1))
        return perm, old_to_new
