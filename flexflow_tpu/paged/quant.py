"""Quantized KV pages: int8 payload with per-page, per-KV-head scales.

HBM per page is the binding constraint on tokens in flight and on
prefix-cache capacity — the pool sizes admission, eviction and the LRU
dead list entirely in pages. Storing K/V as int8 with a float32 scale
sidecar quadruples the pages a fixed HBM budget holds (vs an fp32
model; 2x vs bf16) at the cost of a bounded logit error.

Layout. A quantized pool keeps, per attention node, FOUR buffers in the
caches dict instead of two::

    {"k":       (num_pages, page_size, Hkv, D)  int8,
     "v":       (num_pages, page_size, Hkv, D)  int8,
     "k_scale": (num_pages, Hkv)                float32,
     "v_scale": (num_pages, Hkv)                float32}

The scale granularity is per (page, head, K-or-V): one float per KV
head per page, symmetric around zero (stored = round(x / scale),
clipped to [-127, 127]; loaded = stored * scale). Putting the sidecar
INSIDE the caches dict is the load-bearing trick: every pool-following
operation — the COW clone's ``copy_page`` tree.map, the defrag
permutation's ``b[perm]``, the megastep while_loop carry, the spec
commit — already maps over every leaf of that dict, so scales ride
along with their pages by construction. The poolcheck scale-sidecar
invariant (analysis/pool_invariants.py) proves that discipline holds.

Quantize-on-append with rescale-on-grow. A page's scale only ever
GROWS while the page is allocated (it resets to zero on alloc): when an
append's new rows need a larger scale, the touched pages' existing int8
rows are re-quantized to the grown scale in place (a gather/scatter over
just the B*S touched pages, not the pool). Zero-initialized scales make
empty pages dequantize to exact zeros, and a page revived from the LRU
dead list keeps its scale because it keeps its content.

The tolerance story: greedy decode against an fp32 reference stays
within a small logit tolerance (tests/test_quantized_kv.py pins it) and
speculative acceptance stays above a floor; the running max observed
output delta is exported as the ``kv_quant_error`` gauge when
FF_TPU_KV_QUANT_DEBUG=1 keeps a shadow fp32 cache (docs/paged.md).
"""

from __future__ import annotations

from typing import Optional, Tuple

QMAX = 127.0  # symmetric int8 grid: round(x / scale) in [-127, 127]

# Floor for the rescale ratio's divisor: far below any real scale but
# large enough that old_scale / SCALE_EPS stays finite in f32. Shared
# with the executor's scale-aware commit copy so both grids agree.
SCALE_EPS = 1e-30

# Canonical kv_dtype knob values -> (jnp dtype name, itemsize bytes,
# quantized?). "auto" (the default everywhere) means "the model's own
# dtype, no scale sidecar" and is deliberately absent here — callers
# treat it as None. This table is pure data so the search-side pricer
# (search/cost_model.py) can price a dtype without importing jax.
KV_DTYPES = {
    "fp32": ("float32", 4, False),
    "float32": ("float32", 4, False),
    "bf16": ("bfloat16", 2, False),
    "bfloat16": ("bfloat16", 2, False),
    "fp16": ("float16", 2, False),
    "float16": ("float16", 2, False),
    "int8": ("int8", 1, True),
}

SCALE_BYTES = 4  # the sidecar is float32 per (page, head, K-or-V)


def kv_dtype_info(kv_dtype: Optional[str]) -> Optional[Tuple[str, int, bool]]:
    """(jnp dtype name, itemsize, quantized) for a kv_dtype knob value,
    or None for "auto"/None. Raises on unknown names so a typo'd knob
    fails at validation time, not as a silent fp32 pool."""
    if kv_dtype is None or kv_dtype == "auto":
        return None
    try:
        return KV_DTYPES[kv_dtype]
    except KeyError:
        raise ValueError(
            f"unknown kv_dtype {kv_dtype!r}; expected 'auto' or one of "
            f"{sorted(set(KV_DTYPES))}") from None


def resolve_kv_dtype(kv_dtype: Optional[str]):
    """The jnp dtype for a kv_dtype knob value (None for "auto")."""
    info = kv_dtype_info(kv_dtype)
    if info is None:
        return None
    import jax.numpy as jnp

    return jnp.dtype(info[0])


def is_quantized_dtype(dtype) -> bool:
    """True when a pool at this jnp dtype needs the scale sidecar."""
    import jax.numpy as jnp

    return jnp.dtype(dtype) == jnp.int8


def scale_entry_names(bufs) -> bool:
    """True when a per-node caches dict carries the scale sidecar."""
    return "k_scale" in bufs


def quantized_append(pool, scales, x, page, off, live):
    """Scatter fp rows ``x`` into an int8 ``pool`` under grow-only
    per-(page, head) ``scales``. pool: (N, P, Hkv, D) int8; scales:
    (N, Hkv) f32; x: (B, S, Hkv, D) fp; page/off/live: (B, S). Returns
    (new pool, new scales).

    Three scatters, all touching only the B*S addressed pages:
      1. grow: scatter-max each live row's needed scale (amax/127) into
         its page's sidecar entry (duplicate page indices combine
         correctly under max);
      2. rescale: re-quantize the touched pages' EXISTING rows from the
         old scale to the grown one (duplicate pages write identical
         content, so the unordered scatter is benign);
      3. write: quantize the new rows at the grown scale. Dead rows
         (live == False) are redirected to the null page by the caller
         and quantized at whatever scale page 0 has — garbage rows in
         the garbage page, same contract as the fp path. Their amax is
         excluded from step 1 so padding never inflates a real scale.
    """
    import jax.numpy as jnp

    f32 = jnp.float32
    xf = x.astype(f32)
    # typed scalar constants: a bare Python float in jnp.where/maximum
    # weak-type-promotes the whole scale pipeline (numcheck's
    # dtype-silent-promotion territory); pin them at f32
    zero = f32(0.0)
    amax = jnp.max(jnp.abs(xf), axis=-1)                     # (B, S, Hkv)
    need = jnp.where(live[..., None], amax / f32(QMAX), zero)
    new_scales = scales.at[page].max(need)
    old_t = scales[page]                                     # (B, S, Hkv)
    new_t = new_scales[page]
    ratio = jnp.where(new_t > 0, old_t / jnp.maximum(new_t, f32(SCALE_EPS)),
                      zero)
    blk = pool[page].astype(f32)                    # (B, S, P, Hkv, D)
    blk = blk * ratio[:, :, None, :, None]
    pool = pool.at[page].set(
        jnp.clip(jnp.round(blk), -QMAX, QMAX).astype(pool.dtype))
    s_rows = jnp.where(new_t > 0, new_t, f32(1.0))[..., None]  # (B,S,Hkv,1)
    qx = jnp.clip(jnp.round(xf / s_rows), -QMAX, QMAX).astype(pool.dtype)
    pool = pool.at[page, off].set(qx)
    return pool, new_scales


def dequantize_pages(pages, scales):
    """pages: (..., P, Hkv, D) int8 gathered by page; scales:
    (..., Hkv) f32 gathered the same way. Returns float32 pages."""
    import jax.numpy as jnp

    return pages.astype(jnp.float32) * scales[..., None, :, None]


def quantize_leaf(arr):
    """Per-leaf symmetric int8 fake-quantization for weight streaming
    (Executor.init_params(weight_dtype="int8")): snap every element to
    the 255-point grid scale * [-127..127] and store the result at
    bfloat16 — the matmuls downstream stay dense-float (there is no
    int8 matmul path in the executor), so this models the accuracy of
    int8 weight storage without changing any compute kernel."""
    import jax.numpy as jnp

    scale = jnp.max(jnp.abs(arr)) / QMAX
    scale = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(arr / scale), -QMAX, QMAX)
    return (q * scale).astype(jnp.bfloat16)
