"""Continuous-batching scheduler over the paged KV cache.

Replaces the dense GenerationServer's slot-only admission with admission
by FREE-PAGE BUDGET: a request is admitted when a decode slot is free
AND the pool can hold its prompt's pages; it grows one page at a time as
it decodes; page pressure preempts the youngest other request (its pages
are freed and it requeues at the FRONT of the queue with prompt +
generated prefix). EOS/max-new free pages and slot immediately. All
bookkeeping is host numpy; the jitted decode step sees only int32 page
tables and positions, so it compiles ONCE for the (slots, max_pages)
shape.

PREFIX CACHING (prefix_cache=True): admission first maps the longest
content-addressed prefix of the prompt from the pool's hash index —
full pages are SHARED by refcount, a partially filled tail page is
cloned copy-on-write — and only the uncached suffix is computed.
Completed/preempted requests leave their pages behind as dead-but-
cached LRU entries, so a preempted request's resume re-attaches its own
K/V instead of recomputing it.

CHUNKED PREFILL: the uncached suffix is computed `prefill_chunk` tokens
per tick straight into pool pages (Executor.chunked_prefill_fn — no
dense staging cache), INSIDE the decode loop: each tick advances
mid-prefill slots by one budgeted chunk and then runs the normal decode
tick for everyone else, so a long prompt never stalls in-flight decodes
for more than the one tick its chunk shares.

RAGGED WORK PACKING: every model call is the ONE ragged step
(Executor.ragged_step_fn — flexflow_tpu.paged.attention): the tick
assembles WORK ITEMS (a decode row, a window-sized piece of a prefill
chunk, a drafted tree) into a (B, S) launch whose per-item descriptor
(pos, q_len, depths, anc) says which rows are live; items padded to the
launch shape carry q_len 0 and are skipped by the kernel, with their
writes redirected to the null page. Splitting a chunk into window
pieces is sound because every item's K/V rows scatter into the pool
BEFORE attention runs at each layer, so piece i+1 sees piece i's rows
as committed (kpos < pos) — the same mechanism that lets chunks span
ticks. `ragged_pack=False` keeps the kernel but reverts to the
pre-ragged packing (one full-bucket launch per prefilling slot) — the
bench baseline the padding-waste metric is judged against.

Decode flow per tick:
  1. admit queued requests into free slots while pages last (FIFO;
     preempted requests re-enter ahead of the queue); admission maps
     prefix-cache hits and allocates the remaining pages — no model run
  2. grow: decoding slots whose next write position crosses a page
     boundary allocate a page, preempting under pressure
  3. one budgeted prefill launch packing every mid-prefill slot's chunk
     pieces (a finishing chunk samples the first token)
  4. one jitted ragged decode step for the decoding slots (idle and
     mid-prefill slots carry q_len 0: no work, writes to the null page)
  5. sample, append, publish freshly filled pages to the prefix cache,
     finish/free
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

from flexflow_tpu import obs
from flexflow_tpu.paged.pool import EMPTY_HASH, PagePool
from flexflow_tpu.serving import _GenerationServerBase, _GenRequest

# Packed prefill windows are capped at this many rows — the fp32 sublane
# tile. Exported so the tick pricer (search/servesearch.py) models the
# same ceil-to-window padding the scheduler actually launches with.
PREFILL_WINDOW_ROWS = 8


class PagedGenerationServer(_GenerationServerBase):
    """Continuous batching over the block-paged KV cache
    (serve_generation(..., paged=True)). Same public surface and sampling
    as the dense GenerationServer; HBM scales with the page pool instead
    of slots x max_len, so short sequences leave room to admit more
    concurrent work than the dense layout could hold, and shared prompt
    prefixes (system prompts, few-shot headers) are stored ONCE."""

    def __init__(self, ff, slots: int = 4, max_len: int = 512,
                 eos_id: Optional[int] = None, seed: int = 0,
                 page_size: int = 64, num_pages: Optional[int] = None,
                 preemption: bool = True, table_slack_tokens: int = 0,
                 prefix_cache: bool = True, prefill_chunk: int = 64,
                 ragged_pack: bool = True, megastep_ticks: int = 1,
                 megastep_mixed: bool = False,
                 overlap_dispatch: bool = False,
                 request_record_limit: Optional[int] = None,
                 kv_dtype: str = "auto",
                 reqlog_capacity: Optional[int] = None,
                 slo=None, slo_dump_dir: Optional[str] = None,
                 kv_quant_canary: Optional[int] = None,
                 serve_strategy=None, defer_start: bool = False,
                 host_tier=None):
        import jax

        super().__init__(ff, slots, max_len, eos_id, seed,
                         request_record_limit=request_record_limit,
                         reqlog_capacity=reqlog_capacity,
                         slo=slo, slo_dump_dir=slo_dump_dir,
                         serve_strategy=serve_strategy,
                         defer_start=defer_start)
        self.page_size = int(page_size)
        # table_slack_tokens widens every page table beyond max_len —
        # speculative verify (flexflow_tpu.spec) writes its draft tree's
        # rows past the committed head, so the table must address up to
        # max_len + max_nodes rows even though pos never exceeds max_len
        self.table_slack = int(table_slack_tokens)
        self.max_pages_per_seq = -(
            -(self.max_len + self.table_slack) // self.page_size)
        if num_pages is None:
            # default pool matches the dense layout's capacity (+ null
            # page); size it DOWN to oversubscribe slots against HBM
            num_pages = self.slots * self.max_pages_per_seq + 1
        self.pool = PagePool(num_pages, self.page_size,
                             self.max_pages_per_seq)
        self.preemption = bool(preemption)
        self.prefix_cache = bool(prefix_cache)
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.ragged_pack = bool(ragged_pack)
        # packed prefill windows are capped at this many rows (the fp32
        # sublane tile and the _bucket floor): chunks larger than it
        # split into pieces, so launch shapes stay within a small
        # (n_items, window<=8) family instead of per-chunk pow2 buckets
        self._chunk_rows = PREFILL_WINDOW_ROWS
        ex = ff.executor
        # one ragged step serves decode AND chunked prefill (and tree
        # verify in the speculative subclass): K/V writes land straight
        # in pool pages, there is no dense staging cache
        self._step = ex.ragged_step_fn()
        # megastep_ticks > 1: pure-decode ticks run up to N ticks per
        # dispatch inside one jitted while_loop (docs/paged.md "Decode
        # megasteps"); 1 keeps the per-tick host loop. Ticks with
        # mid-prefill chunks in flight always take the one-tick path, so
        # chunk completion resumes the host scheduler between ticks.
        self.megastep_ticks = int(megastep_ticks)
        if self.megastep_ticks < 1:
            raise ValueError(
                f"megastep_ticks must be >= 1, got {megastep_ticks}")
        # megastep_mixed: the UNIVERSAL megastep — mid-prefill chunk
        # rows and on-device drafted spec chains fuse into the same
        # while_loop as decode rows (docs/paged.md "Universal
        # megasteps"), so a tick with a chunk in flight no longer drops
        # to host granularity. overlap_dispatch additionally runs the
        # next tick's admission work while the fused dispatch is in
        # flight, fencing on the one device_get.
        self.megastep_mixed = bool(megastep_mixed)
        self.overlap_dispatch = bool(overlap_dispatch)
        if self.overlap_dispatch and not self.megastep_mixed:
            raise ValueError(
                "overlap_dispatch overlaps host work with the in-flight "
                "MIXED megastep dispatch; pass megastep_mixed=True")
        # kv_dtype: "auto" pools at the model's dtype; "int8" stores
        # quantized pages with the per-(page, head) scale sidecar inside
        # the same caches dict (paged/quant.py), so copy_page/defrag/
        # megastep carry all move scales with pages by construction;
        # "bf16"/"fp16"/"fp32" are plain storage casts without scales
        from flexflow_tpu.paged.quant import (
            is_quantized_dtype,
            resolve_kv_dtype,
        )

        self.kv_dtype = str(kv_dtype)
        pool_dt = resolve_kv_dtype(self.kv_dtype)  # validates the name
        self._quantized = (pool_dt is not None
                           and is_quantized_dtype(pool_dt))
        # FF_TPU_KV_QUANT_DEBUG=1 keeps a shadow fp32 cache and runs
        # every launch twice, exporting the running max abs output delta
        # as the kv_quant_error gauge (docs/observability.md). The
        # shadow must observe every tick, so megasteps fall back to the
        # one-tick loop under the flag.
        import os as _os

        self._kv_quant_debug = (
            self._quantized
            and _os.environ.get("FF_TPU_KV_QUANT_DEBUG") == "1")
        if self._kv_quant_debug and self.megastep_ticks > 1:
            import logging

            logging.getLogger(__name__).info(
                "FF_TPU_KV_QUANT_DEBUG=1: forcing megastep_ticks=1 so "
                "the fp32 shadow cache observes every tick")
            self.megastep_ticks = 1
        if self._kv_quant_debug and self.megastep_mixed:
            import logging

            logging.getLogger(__name__).info(
                "FF_TPU_KV_QUANT_DEBUG=1: forcing megastep_mixed=False "
                "so the fp32 shadow cache observes every launch")
            self.megastep_mixed = False
            self.overlap_dispatch = False
        self._megastep = (ex.paged_megastep_fn(self.megastep_ticks, eos_id)
                          if self.megastep_ticks > 1
                          and not self.megastep_mixed else None)
        # the universal megastep's fused launch window: chunk pieces are
        # capped at the packed-prefill window, drafted chains at
        # depth + 1 rows (0 on the non-speculative server)
        spec_cfg = getattr(self, "spec", None)
        self._spec_depth = int(spec_cfg.depth) if spec_cfg is not None \
            else 0
        self._mixed_window = min(self._chunk_rows, self.prefill_chunk)
        self._mixed_fn = (ex.paged_mixed_megastep_fn(
            self.megastep_ticks, eos_id, window=self._mixed_window,
            depth=self._spec_depth) if self.megastep_mixed else None)
        # device-resident (slots, Lbuf + 1) token ledger for the mixed
        # megastep (column Lbuf is the masked-scatter trash column);
        # None = dirty, rebuilt from host truth on next dispatch
        self._seq_cols = self.max_pages_per_seq * self.page_size
        self._seq_dev = None
        self._caches = ex.init_paged_kv_cache(num_pages, self.page_size,
                                              dtype=pool_dt)
        self._caches_ref = (ex.init_paged_kv_cache(
            num_pages, self.page_size, dtype=jax.numpy.float32)
            if self._kv_quant_debug else None)
        self._quant_err_dev = jax.numpy.float32(0.0)
        # kv_quant_canary=N: every Nth admitted request opens a SAMPLED
        # shadow window — _caches_ref becomes an fp32 snapshot of the
        # live pool (dequantized for int8, a cast otherwise) and every
        # launch replays against it until that request releases, feeding
        # the same kv_quant_error gauge at 1/N cost. The all-requests
        # FF_TPU_KV_QUANT_DEBUG=1 mode takes precedence over sampling.
        if kv_quant_canary is None:
            kv_quant_canary = int(
                _os.environ.get("FF_TPU_KV_QUANT_CANARY", "0") or 0)
        if kv_quant_canary < 0:
            raise ValueError(
                f"kv_quant_canary must be >= 0, got {kv_quant_canary}")
        self.kv_quant_canary = (0 if self._kv_quant_debug
                                else int(kv_quant_canary))
        self._canary_admits = 0
        self._canary_req: Optional[_GenRequest] = None
        self._c_canary = self.registry.counter(
            "kv_quant_canary_windows_total")
        if self._quantized:
            from flexflow_tpu.paged.quant import dequantize_pages

            @jax.jit
            def shadow_snapshot(caches):
                # the shadow starts COHERENT with the pool: what int8
                # storage says the cache holds, in fp32 — divergence
                # measured from here forward is pure quantization drift
                return {nk: {n: dequantize_pages(b, bufs[n + "_scale"])
                             for n, b in bufs.items()
                             if not n.endswith("_scale")}
                        for nk, bufs in caches.items()}
        else:
            @jax.jit
            def shadow_snapshot(caches):
                return jax.tree.map(
                    lambda b: b.astype(jax.numpy.float32), caches)
        self._shadow_snapshot = shadow_snapshot
        self._tables = np.zeros((self.slots, self.max_pages_per_seq),
                                np.int32)
        # device-resident descriptor mirrors (dirty-flagged, not re-
        # uploaded per tick): the page-table matrix changes only on
        # admission / growth / release / defrag, per-slot temps only on
        # admission / release, and the causal-chain depths/anc defaults
        # are pure functions of the launch shape
        self._tables_dev = None
        self._temps_dev = None
        self._chain_desc_cache = {}
        self._admit_order: List[int] = []  # live slots, oldest first
        self._requeue: List[_GenRequest] = []  # preempted, ahead of queue
        self._defrag_req = threading.Event()
        self.preemptions = 0
        self.defrags = 0
        self.peak_active = 0
        self.prefill_ticks = 0
        self._prefill_rr = 0  # rotating start slot for the chunk budget
        # idle-loop accounting (fftrace): ticks the loop slept because
        # nothing was live or admitted, and total seconds spent asleep
        self._c_idle = self.registry.counter("idle_ticks_total")
        self._c_idle_s = self.registry.counter("idle_wait_seconds_total")
        # ragged-launch accounting: how many launch rows each tick
        # shipped vs how many were padding (q_len 0 items / rows past an
        # item's q_len). The gauge holds the LAST tick's waste ratio;
        # the counters aggregate for the bench's end-to-end ratio.
        self._c_rows = self.registry.counter("launch_rows_total")
        self._c_pad = self.registry.counter("padded_rows_total")
        self._g_waste = self.registry.gauge("padding_waste_ratio")
        # megastep accounting: ticks fused per dispatch, why each
        # megastep handed control back, and host round-trips per decoded
        # token — the one-tick path counts one round-trip per tick, so
        # the N=1 vs N=8 bench A/B reads the same counters
        self._h_mega = self.registry.histogram("megastep_ticks",
                                               obs.COUNT_BUCKETS)
        self._c_rt = self.registry.counter("host_roundtrips_total")
        self._c_dtok = self.registry.counter("decode_tokens_total")
        self._g_rt_tok = self.registry.gauge("host_roundtrips_per_token")
        self._c_break = {
            r: self.registry.counter(f"megastep_break_{r}_total")
            for r in ("finish", "page", "limit", "chunk", "verify")}
        # overlap-dispatch accounting: host work done in the shadow of
        # the in-flight fused dispatch over the whole dispatch wait
        # (host work time / (host work time + fence time))
        self._g_overlap = self.registry.gauge("host_overlap_ratio")
        # one gate decision, surfaced: which attention path this server's
        # launches take (evaluated host-side at init — the gate only
        # depends on shapes/dtype/backend/env, all fixed for the server's
        # lifetime). A second server re-logs its own gate decisions.
        import os

        from flexflow_tpu.paged.attention import (
            paged_attention_available,
            reset_rejection_log,
        )

        reset_rejection_log()
        kbuf = next(iter(self._caches.values()))["k"]
        self.kernel_variant = "ragged_pallas" if paged_attention_available(
            kbuf.shape[-1], self.page_size,
            interpret=os.environ.get("FF_TPU_FLASH_INTERPRET") == "1",
            dtype=kbuf.dtype) else "ragged_gather"
        self._g_kernel = self.registry.gauge("ragged_kernel_active")
        self._g_kernel.set(1.0 if self.kernel_variant == "ragged_pallas"
                           else 0.0)
        # kv_cache_dtype holds the pool's bits per K/V element (the
        # dtype NAME rides the metrics() dict); kv_quant_error the
        # running max abs output delta vs the fp32 shadow, 0 until the
        # debug flag samples it
        self._g_kv_dtype = self.registry.gauge("kv_cache_dtype")
        self._g_kv_dtype.set(kbuf.dtype.itemsize * 8)
        self._g_qerr = self.registry.gauge("kv_quant_error")
        self._g_qerr.set(0.0)
        # the canary is a WATCHDOG, not just a gauge: its alert
        # threshold is the "kv-canary-shadow-delta" band from the
        # numerics budget catalog (analysis/num_budgets.py — numcheck's
        # budget arm errors if the band is edited out from under us);
        # the running max crossing it counts a breach and logs once
        from flexflow_tpu.analysis.num_budgets import tolerance

        self.kv_quant_threshold = float(
            tolerance("kv-canary-shadow-delta"))
        self._quant_breached = False
        self._c_qbreach = self.registry.counter(
            "kv_quant_canary_breaches_total")
        # the DECLARED numerics plan this server serves (the paged
        # entries, at the pool's kv_dtype) — the same plan numcheck's
        # HLO arm audits against the lowered modules. The /v2 model
        # block + ff_dtype_plan_ok gauge report whether the live pool
        # still matches it, closing the audited-vs-served loop.
        self._dtype_plan = ex.dtype_plan(
            entries=["paged_decode", "verify"],
            kv_dtype=None if self.kv_dtype == "auto" else self.kv_dtype)
        self._g_plan_ok = self.registry.gauge("dtype_plan_ok")
        self._g_plan_ok.set(1.0 if self._dtype_plan_ok() else 0.0)

        @jax.jit
        def copy_page(caches, src, dst):
            # copy-on-write: clone one pool page (every cache buffer) so
            # a new owner can write past a shared partial prefix — the
            # scale-sidecar entries of a quantized pool are leaves of
            # the same dict, so the clone carries the donor's scales
            return jax.tree.map(lambda b: b.at[dst].set(b[src]), caches)

        self._copy_page = copy_page

        @jax.jit
        def reset_page_scales(caches, pages):
            # page lifecycle, not a row write: pages coming OFF the free
            # list get zero scales (grow-only within a lifetime starts
            # from zero; an empty page dequantizes to exact zeros).
            # LRU-revived pages never come through here — they keep
            # content, so they keep scales. `pages` is padded with the
            # null page 0, whose scale only ever covers garbage rows.
            return {
                nk: {n: (b.at[pages].set(0.0) if n.endswith("_scale")
                         else b)
                     for n, b in bufs.items()}
                for nk, bufs in caches.items()
            }

        self._scale_reset = reset_page_scales

        # host-memory KV tier (disagg/host_tier.py): evictions spill full
        # pages' payloads (scale sidecar included — it is a leaf of the
        # same caches dict) to host RAM instead of dropping them, and
        # lookups transparently fetch spilled prefixes back. Pass a
        # HostTier INSTANCE to share one tier between servers — that
        # shared tier is the prefill/decode KV-transfer channel
        # (disagg/workers.py) — or an int capacity for a private tier.
        @jax.jit
        def read_page(caches, page):
            # one compiled program for every page id: the index is data
            return jax.tree.map(lambda b: b[page], caches)

        @jax.jit
        def write_page(caches, page, payload):
            return jax.tree.map(
                lambda b, r: b.at[page].set(
                    jax.numpy.asarray(r).astype(b.dtype)), caches, payload)

        self._page_read = read_page
        self._page_write = write_page
        self.host_tier = None
        # an int capacity of 0 disables; an EMPTY HostTier instance must
        # not (it defines __len__, so plain truthiness would skip it)
        if host_tier is not None and host_tier != 0:
            from flexflow_tpu.disagg.host_tier import HostTier

            if self._kv_quant_debug:
                raise ValueError(
                    "host_tier and FF_TPU_KV_QUANT_DEBUG=1 are mutually "
                    "exclusive: the all-ticks fp32 shadow cannot observe "
                    "pages restored behind its back")
            self.host_tier = (host_tier if isinstance(host_tier, HostTier)
                              else HostTier(int(host_tier)))
            self.pool.attach_tier(self.host_tier, self._tier_read_page,
                                  self._tier_write_page)
        # spill/fetch counters ride the registry so they land on the
        # Prometheus endpoint as ff_kv_spill_pages_total /
        # ff_kv_fetch_pages_total; occupancy + fetch latency are gauges.
        # metrics() syncs them from the pool/tier truth at scrape time.
        self._c_spill = self.registry.counter("kv_spill_pages_total")
        self._c_fetch = self.registry.counter("kv_fetch_pages_total")
        self._g_tier_occ = self.registry.gauge("host_tier_occupancy_pages")
        self._g_tier_ratio = self.registry.gauge("host_tier_occupancy_ratio")
        self._g_tier_lat = self.registry.gauge("host_tier_fetch_latency_s")
        if self.serve_strategy is None:
            # derive the strategy from the ACTUAL constructor knobs (after
            # any debug-flag adjustments) so fingerprint() always reflects
            # what this server runs, even when built without servesearch
            self.serve_strategy = self._derive_strategy()
        self._start()

    def shape_config(self) -> dict:
        """enumerate_catalog kwargs for this server's launch-shape space
        (analysis.shapecheck): the pool geometry plus every knob that
        changes which (B, W) ragged launches the scheduler can pack.
        The speculative subclass extends with its tree dimensions."""
        return {
            "slots": self.slots, "max_len": self.max_len, "paged": True,
            "page_size": self.page_size,
            "prefill_chunk": self.prefill_chunk,
            "ragged_pack": self.ragged_pack,
            "megastep_ticks": self.megastep_ticks,
            "megastep_mixed": self.megastep_mixed,
            # num_pages is fixed at pool construction; the loop thread
            # never resizes the pool
            "num_pages": self.pool.num_pages,  # fflint: lock-ok (immutable)
            "kv_dtype": self.kv_dtype,
            "window_rows": self._chunk_rows,
        }

    # -- capacity ---------------------------------------------------------

    def _peak_rows(self, prompt_len: int, max_new_tokens: int) -> int:
        """Cache rows a request touches at its deepest point (subclass
        hook: speculative verify adds its tree's scratch rows)."""
        return prompt_len + max_new_tokens

    def _check_capacity(self, prompt: np.ndarray, max_new_tokens: int):
        super()._check_capacity(prompt, max_new_tokens)
        need = self.pool.pages_for(self._peak_rows(len(prompt),
                                                   max_new_tokens))
        if need > self.pool.capacity:
            raise ValueError(
                f"request needs {need} pages at its longest "
                f"({len(prompt)}+{max_new_tokens} tokens, page_size="
                f"{self.page_size}) but the pool only holds "
                f"{self.pool.capacity}; raise num_pages")

    def metrics(self) -> dict:  # fflint: lock-ok (relaxed metrics snapshot; int/float reads are atomic, staleness is fine for scraping)
        """Aggregate serving metrics + the per-request records of the
        last MAX_REQUEST_RECORDS completed requests (queue time, TTFT,
        prefill/decode tokens, pages — see _GenerationServerBase), plus
        pool occupancy/fragmentation and the prefix-cache counters (what
        the /v2/models/<name>/metrics endpoint scrapes)."""
        m = super().metrics()
        pool = self.pool
        m.update({
            "preemptions": self.preemptions,
            "defrags": self.defrags,
            "peak_active": self.peak_active,
            "pages_in_use": pool.pages_in_use,
            "free_pages": pool.free_pages,
            "cached_pages": pool.cached_pages,
            "pool_occupancy": pool.pages_in_use / pool.capacity,
            "fragmentation": pool.fragmentation(),
            "prefill_ticks": self.prefill_ticks,
            "kernel_variant": self.kernel_variant,
            "kv_cache_dtype": self._kv_pool_dtype_name(),
            "kv_quant_error": self._kv_quant_error(),
            "kv_quant_canary": {
                "every": self.kv_quant_canary,
                "debug_mode": self._kv_quant_debug,
                "windows": int(self._c_canary.value),
                "window_open": (self._canary_req is not None
                                or self._kv_quant_debug),
                "threshold": self.kv_quant_threshold,
                "breaches": int(self._c_qbreach.value),
            },
            "model": self._model_block(),
            "launch_rows": int(self._c_rows.value),
            "padded_rows": int(self._c_pad.value),
            "padding_waste_ratio": (
                self._c_pad.value / self._c_rows.value
                if self._c_rows.value else 0.0),
            "megastep": {
                "ticks_max": self.megastep_ticks,
                "mixed": self.megastep_mixed,
                "overlap_dispatch": self.overlap_dispatch,
                "host_overlap_ratio": float(self._g_overlap.value),
                "host_roundtrips": int(self._c_rt.value),
                "decode_tokens": int(self._c_dtok.value),
                "host_roundtrips_per_token": (
                    self._c_rt.value / self._c_dtok.value
                    if self._c_dtok.value else 0.0),
                "breaks": {r: int(c.value)
                           for r, c in self._c_break.items()},
            },
            "prefix_cache": {
                "enabled": self.prefix_cache,
                "hit_tokens": pool.hit_tokens,
                "miss_tokens": pool.lookup_tokens - pool.hit_tokens,
                "lookup_tokens": pool.lookup_tokens,
                "hits": pool.hits,
                "misses": pool.misses,
                "evictions": pool.evictions,
            },
        })
        # host-tier block + registry sync: counters follow THIS pool's
        # spill/fetch truth (a shared tier's totals aggregate producers;
        # per-server counters must not double-count), gauges follow the
        # tier. Synced at scrape time — both the JSON payload and the
        # Prometheus endpoint call metrics() first.
        self._c_spill.inc(pool.spilled_pages - self._c_spill.value)
        self._c_fetch.inc(pool.fetched_pages - self._c_fetch.value)
        tier = self.host_tier
        m["host_tier"] = {"enabled": tier is not None,
                          "spilled_pages": pool.spilled_pages,
                          "fetched_pages": pool.fetched_pages}
        if tier is not None:
            tm = tier.metrics()
            m["host_tier"].update(tm)
            self._g_tier_occ.set(tm["occupancy_pages"])
            self._g_tier_ratio.set(tm["occupancy_ratio"])
            self._g_tier_lat.set(tm["fetch_latency_s_avg"])
        return m

    def _kv_pool_dtype_name(self) -> str:
        """The pool's actual storage dtype name ("int8" for a quantized
        pool) — what the kv_cache_dtype gauge reports in bits."""
        return str(next(iter(self._caches.values()))["k"].dtype)

    def _dtype_plan_ok(self) -> bool:
        """True while the live pool's storage dtype matches the declared
        plan's kv dtype — i.e. the server is serving the numerics it
        was audited against (numcheck HLO arm / --dtype-plan)."""
        from flexflow_tpu.runtime.executor import _HLO_DTYPE_NAMES

        pool = _HLO_DTYPE_NAMES.get(self._kv_pool_dtype_name())
        return pool == self._dtype_plan["paged_decode"]["kv"]

    def _model_block(self) -> dict:
        """The /v2 metrics "model" block: per-entry compute/accum/kv
        dtype names of the declared plan + whether the live pool still
        matches it (also the ff_dtype_plan_ok gauge)."""
        ok = self._dtype_plan_ok()
        self._g_plan_ok.set(1.0 if ok else 0.0)
        return {
            "dtype_plan": {e: {"compute": p["compute"],
                               "accum": p["accum"], "kv": p["kv"]}
                           for e, p in self._dtype_plan.items()},
            "dtype_plan_ok": ok,
        }

    # -- request log (obs.reqlog) ----------------------------------------

    def _prefix_chain(self, req: _GenRequest) -> tuple:
        """The pool's sha1 chain over the prompt's page-aligned blocks —
        entry i content-addresses the whole prefix through block i, so
        two records share a chain prefix iff their prompts shared those
        pages (the replay determinism tests diff these)."""
        return tuple(self.pool.chain_hashes(req.prompt))

    def _reqlog_kv_dtype(self) -> str:
        return self._kv_pool_dtype_name()

    def _reqlog_record(self, req: _GenRequest, m: dict,
                       done_t: float) -> dict:
        rec = super()._reqlog_record(req, m, done_t)
        rec["page_size"] = self.page_size
        return rec

    def _kv_quant_error(self) -> float:
        """Running max abs output delta vs the fp32 shadow cache (0.0
        unless FF_TPU_KV_QUANT_DEBUG=1 is sampling). Materialized from
        the device-resident running max only here, at scrape time, so
        the serving loop never pays a host sync for it."""
        err = float(self._quant_err_dev)
        self._g_qerr.set(err)
        if err > self.kv_quant_threshold and not self._quant_breached:
            # the running max only grows, so this fires once per
            # crossing — a breach is an alert, not a page of log spam
            self._quant_breached = True
            self._c_qbreach.inc()
            import logging

            logging.getLogger(__name__).warning(
                "kv_quant_error %.3g breached the "
                "kv-canary-shadow-delta budget %.3g "
                "(analysis/num_budgets.py): the quantized pool has "
                "drifted past its declared band vs the fp32 shadow",
                err, self.kv_quant_threshold)
        return err

    def request_defrag(self):
        """Ask the loop to compact the page pool between ticks (host
        bookkeeping + one device gather per cache buffer)."""
        self._defrag_req.set()

    # -- prefix-cache publication -----------------------------------------

    def _publish_prefix(self, req: _GenRequest, valid_rows: int):
        """Register every freshly FILLED page (all page_size rows hold
        committed K/V) under its token-prefix chain hash, so concurrent
        and future requests sharing the prefix map it instead of
        recomputing. Cheap no-op until a page boundary is crossed."""
        if not self.prefix_cache:
            return
        P = self.page_size
        target = min(valid_rows // P, len(req.pages))
        if req.hashed_blocks >= target:
            return
        seq = req.seq_tokens()
        chain = self.pool.chain_hashes(seq[:target * P])
        for b in range(req.hashed_blocks, target):
            self.pool.register_full(req.pages[b], chain[b])
        req.hashed_blocks = target

    def _publish_tail(self, req: _GenRequest):
        """On release/preemption: publish the remaining full pages and
        the partially filled tail page, so a resume (or an identical
        prompt) re-attaches these rows instead of recomputing them."""
        if not self.prefix_cache or not req.pages:
            return
        P = self.page_size
        valid = max(req.pos, req.prefill_pos)
        self._publish_prefix(req, valid)
        full = req.hashed_blocks
        tail = valid - full * P
        if tail > 0 and full < len(req.pages):
            seq = req.seq_tokens()
            chain = self.pool.chain_hashes(seq[:full * P])
            parent = chain[-1] if chain else EMPTY_HASH
            self.pool.register_partial(req.pages[full], parent,
                                       seq[full * P:valid])

    # -- slot lifecycle ---------------------------------------------------

    def _reset_prefill_state(self, req: _GenRequest):
        req.pos = 0
        req.prefill_pos = 0
        req.prefill_target = 0
        req.prefill_seq = None
        req.hashed_blocks = 0

    def _maybe_open_canary(self, req: _GenRequest):
        """Every `kv_quant_canary`-th successful admission opens a
        shadow window on that request: _caches_ref becomes an fp32
        snapshot of the CURRENT pool, so _launch's replay block measures
        divergence accrued from this admission forward. One window at a
        time; megasteps stand down while one is open (_loop_body) so the
        shadow observes every tick."""
        if not self.kv_quant_canary or self._kv_quant_debug:
            return
        self._canary_admits += 1
        if (self._canary_admits % self.kv_quant_canary == 0
                and self._caches_ref is None):
            self._caches_ref = self._shadow_snapshot(self._caches)
            self._canary_req = req
            self._c_canary.inc()

    def _close_canary(self, req: _GenRequest):
        """Drop the shadow window when its request leaves (finish,
        cancellation, or preemption — a preempted request's replay
        would resume against a stale shadow)."""
        if self._canary_req is req:
            self._canary_req = None
            self._caches_ref = None

    def _release_slot(self, slot: int, req: _GenRequest,
                      completed: bool = False):
        if not self._kv_quant_debug:
            self._close_canary(req)
        self._publish_tail(req)
        # free LEAF-first: a chain lookup stops at its first missing
        # block, so under pressure the LRU must reclaim tail pages before
        # the roots that every shared prefix runs through
        self.pool.free(list(reversed(req.pages)))
        req.pages = []
        self._tables[slot] = 0
        self._mark_tables_dirty()
        self._mark_temps_dirty()
        if slot in self._admit_order:
            self._admit_order.remove(slot)
        super()._release_slot(slot, req, completed)

    def _evict(self, slot: int):
        """Preempt: free the victim's pages and requeue it (front); its
        future stays pending. With the prefix cache on, the freed pages
        stay content-addressed on the LRU dead list, so the resume
        re-attaches them and recomputes only whatever was evicted in
        between (req.seq_tokens() — the prompt itself is never mutated,
        so repeated preemptions cannot double-fold the prefix)."""
        req = self._active[slot]
        if not self._kv_quant_debug:
            self._close_canary(req)
        self._publish_tail(req)
        self.pool.free(list(reversed(req.pages)))  # leaf-first (see above)
        req.pages = []
        self._reset_prefill_state(req)
        self._tables[slot] = 0
        self._mark_tables_dirty()
        self._mark_temps_dirty()
        self._active[slot] = None
        if slot in self._admit_order:
            self._admit_order.remove(slot)
        req.preemptions += 1
        self.preemptions += 1
        self._requeue.insert(0, req)

    def _on_prefill_complete(self, slot: int):
        """Hook: runs inside _prefill_tick right after a request finishes
        its chunked prefill (tail published, first token sampled) and
        survived _finish_if_done. The monolithic server decodes in place;
        a disagg PrefillWorker (disagg/workers.py) overrides this to
        spill the request's pages into the shared host tier and hand the
        request to the decode worker instead."""

    # -- drain-and-swap (serving_autopilot) -------------------------------

    def _derive_strategy(self):
        """Reconstruct the ServeStrategy this server actually runs —
        called by the constructor when no explicit strategy was passed,
        so reqlog stamping and autopilot window segmentation work on
        hand-built servers too. Reads the knobs AFTER any debug-flag
        adjustment (megastep forcing under FF_TPU_KV_QUANT_DEBUG), so
        the fingerprint matches observable behaviour, not the args."""
        from flexflow_tpu.search.servesearch import ServeStrategy

        spec = getattr(self, "spec", None)
        dense_pages = self.slots * self.max_pages_per_seq
        frac = (1.0 if self.pool.num_pages >= dense_pages + 1
                else max((self.pool.num_pages - 1) / dense_pages, 1e-6))
        # a page (or chunk) wider than max_len behaves identically to
        # one clamped at max_len — clamp so the derived strategy passes
        # its own validate() and can round-trip through swap_to()
        return ServeStrategy(
            page_size=min(self.page_size, self.max_len),
            prefill_chunk=min(self.prefill_chunk, self.max_len),
            spec_width=(spec.width if spec is not None else 0),
            spec_depth=(spec.depth if spec is not None else 0),
            megastep_ticks=self.megastep_ticks,
            megastep_mixed=self.megastep_mixed,
            overlap_dispatch=self.overlap_dispatch,
            ragged_pack=self.ragged_pack,
            pool_fraction=round(frac, 6),
            kv_dtype=self.kv_dtype,
        )

    def _detach_active(self) -> List[_GenRequest]:
        """Carry-over side of detach_for_swap(): pull every live request
        off its slot WITHOUT touching its future. Pages are published to
        the prefix cache first (tail included) and then freed, so when
        the successor adopts this pool its re-admission re-attaches
        whatever content survives the LRU and recomputes only the rest.
        Not a preemption — futures stay pending, counters untouched."""
        carried: List[_GenRequest] = []
        for slot in list(self._admit_order):
            req = self._active[slot]
            if req is None:
                continue
            if not self._kv_quant_debug:
                self._close_canary(req)
            self._publish_tail(req)
            self.pool.free(list(reversed(req.pages)))  # leaf-first
            req.pages = []
            self._reset_prefill_state(req)
            self._tables[slot] = 0
            self._active[slot] = None
            carried.append(req)
        self._admit_order.clear()
        self._mark_tables_dirty()
        self._mark_temps_dirty()
        carried.extend(self._requeue)
        self._requeue.clear()
        return carried

    def absorb_requests(self, reqs: List[_GenRequest]):
        """Seed this not-yet-started server (defer_start=True) with the
        requests a predecessor carried out of detach_for_swap(). They
        land at the FRONT of the admission order, ahead of anything
        submitted to this server directly, so in-flight work resumes
        first after cutover."""
        if self._thread is not None:
            raise RuntimeError(
                "absorb_requests() requires a server whose loop has not "
                "started (construct with defer_start=True)")
        self._requeue[:0] = list(reqs)

    def adopt_pool_from(self, old: "PagedGenerationServer") -> bool:
        """Take over the predecessor's PagePool and device caches when
        the pool geometry and storage dtype are identical, so content-
        addressed prefix pages survive the swap and carried requests
        re-attach instead of recomputing. Returns False on any mismatch
        (or when either side runs a debug shadow cache) and keeps the
        fresh pool — correct either way, just a colder start."""
        if self._thread is not None:
            raise RuntimeError(
                "adopt_pool_from() requires a server whose loop has not "
                "started (construct with defer_start=True)")
        # both loops are quiescent here: self raises above unless
        # defer_start, and the caller already joined the predecessor's
        # loop via detach_for_swap — nothing mutates either server
        # during the geometry comparison
        same = (self.page_size == old.page_size
                and self.pool.num_pages  # fflint: lock-ok (loops joined)
                == old.pool.num_pages
                and self.max_pages_per_seq == old.max_pages_per_seq
                and self._kv_pool_dtype_name() == old._kv_pool_dtype_name()
                and self._caches_ref is None  # fflint: lock-ok (joined)
                and old._caches_ref is None)
        if not same:
            return False
        self.pool = old.pool
        self._caches = old._caches
        return True

    # -- host-tier payload closures (disagg/host_tier.py) -------------------

    def _tier_read_page(self, page: int):
        """Snapshot one pool page to host: every cache buffer's row —
        the int8 scale-sidecar leaves live in the same dict, so scales
        travel with their page by construction. The payload keeps the
        caches dict's tree structure, so write restores it by tree_map."""
        import jax
        import jax.numpy as jnp

        return jax.device_get(
            self._page_read(self._caches, jnp.asarray(page, jnp.int32)))

    def _tier_write_page(self, page: int, payload):
        """Restore one spilled payload into a freshly allocated page
        (device_put rides the jitted scatter). A fetch rewrites pool
        content behind any open canary shadow, so the window closes —
        the probe aborts rather than report phantom divergence."""
        import jax.numpy as jnp

        t0 = time.monotonic()
        self._caches = self._page_write(
            self._caches, jnp.asarray(page, jnp.int32), payload)
        if self._caches_ref is not None and self._canary_req is not None:
            self._close_canary(self._canary_req)
        if self.host_tier is not None:
            self.host_tier.observe_fetch_seconds(time.monotonic() - t0)

    def adopt_request_pages(self, src: "PagedGenerationServer",  # fflint: lock-ok (quiescent receiver by contract — see docstring; no loop thread races these reads)
                            req: _GenRequest) -> int:
        """Per-request page adoption (the same-device KV-transfer path,
        generalizing adopt_pool_from's whole-pool swap): copy the FULL
        prefix pages `req`'s sequence has resident on `src` into this
        server's pool, registered under the same chain hashes and parked
        dead-cached, so this server's admission lookup re-attaches them.
        Direct device-to-device, for pools that share devices AND a
        quiescent receiver (this server's loop not yet started, or the
        call made from its own loop thread — _caches is loop-owned);
        the LIVE handoff path goes through a shared HostTier instead
        (disagg/workers.py), whose lock makes the transfer safe across
        worker threads. Returns pages adopted; a full pool or dtype
        mismatch adopts fewer — correct either way, the remainder
        recomputes."""
        if self._kv_pool_dtype_name() != src._kv_pool_dtype_name():
            return 0
        import jax.numpy as jnp

        adopted = 0
        seq = req.seq_tokens()
        for h in self.pool.chain_hashes(seq):
            if h in self.pool._full:  # fflint: pool-ok (resident already)
                continue
            page = src.pool._full.get(h)  # fflint: pool-ok (src quiesced at handoff)
            if page is None:
                break  # src chain broke; nothing deeper can be resident
            got = self.pool.alloc(1)
            if got is None:
                break
            self._caches = self._page_write(
                self._caches, jnp.asarray(got[0], jnp.int32),  # fflint: host-ok (one-time handoff copy, not a tick loop)
                src._tier_read_page(page))
            self.pool.register_full(got[0], h)
            self.pool.free(got)  # registered: parks on the LRU dead list
            adopted += 1
        return adopted

    def _reset_page_scales(self, pages: List[int]):
        """Zero the scale-sidecar entries of freshly ALLOCATED pages
        (no-op on unquantized pools). Called wherever pages come off the
        free list — admission's private pages and per-tick growth — so a
        page's grow-only scale lifetime starts at zero and a stale scale
        can never leak across owners. LRU revivals deliberately skip
        this: a revived page keeps its content, so it keeps its scale.
        The index vector pads with the null page to a fixed length so
        the jitted reset compiles once."""
        if not self._quantized or not pages:
            return
        import jax.numpy as jnp

        buf = np.zeros((self.max_pages_per_seq,), np.int32)
        buf[:len(pages)] = pages
        self._caches = self._scale_reset(self._caches, jnp.asarray(buf))

    def _admit(self, req: _GenRequest, slot: int) -> bool:
        """Map the longest cached prefix (shared full pages by refcount,
        copy-on-write clone of a matched partial tail), allocate private
        pages for the rest, and queue the uncached suffix for CHUNKED
        prefill. No model step runs here — prefill happens inside the
        decode loop, one budgeted chunk per tick."""
        import jax.numpy as jnp

        seq = req.seq_tokens()
        n = len(seq)
        P = self.page_size
        shared: List[int] = []
        cached = 0
        cow = None
        if self.prefix_cache:
            fetched0 = self.pool.fetched_pages
            shared, cached, cow = self.pool.lookup(seq)
            # attribute transparent host-tier fetches to THIS request
            # (reqlog `fetched_pages`; disagg handoff arrives this way)
            req.fetched_pages += self.pool.fetched_pages - fetched0
        # always recompute at least the LAST prompt token: its forward
        # pass produces the first sampled token's distribution (the
        # cache stores K/V, not logits)
        start = min(cached, n - 1)
        b0 = start // P            # first block this request writes into
        keep = shared[:b0]
        # a shared page at/after the write boundary must be cloned before
        # we write into it: the partial-tail donor, or — page-aligned
        # full-prompt hit — the last matched full page
        cow_src = cow if cow is not None else (
            shared[b0] if b0 < len(shared) else None)
        # start >= len(shared)*P - 1, so b0 >= len(shared) - 1: lookup
        # can never return full pages past the write boundary
        assert not shared[b0 + 1:], (shared, b0, cached, n)
        total = self.pool.pages_for(n)
        fresh = self.pool.alloc(total - b0)
        if fresh is None:
            # transient shortfall (LRU revival vs the conservative gate):
            # drop every cache hit and retry as a full recompute, and
            # roll the pool's hit counters back — these tokens end up
            # recomputed, not served from cache
            self.pool.free(keep + ([cow_src] if cow_src is not None
                                   else []))
            if cached > 0:
                self.pool.hit_tokens -= cached
                self.pool.hits -= 1
                self.pool.misses += 1
            shared, keep, cached, cow_src = [], [], 0, None
            start, b0 = 0, 0
            fresh = self.pool.alloc(total)
            if fresh is None:
                self._push_back(req)
                return False
        if cached > start:
            # full-prompt hit: the clamped last prompt token is
            # recomputed for its logits, not served — keep the pool's
            # hit_tokens in step with the per-request counters
            self.pool.hit_tokens -= cached - start
        pages = keep + fresh
        req.pages = pages
        req.peak_pages = max(req.peak_pages, len(pages))
        # fresh pages start a new scale lifetime BEFORE any COW clone,
        # so the clone's copied scale is not wiped
        self._reset_page_scales(fresh)
        self._tables[slot] = 0
        self._tables[slot, :len(pages)] = pages
        self._mark_tables_dirty()
        self._mark_temps_dirty()
        if cow_src is not None:
            self._caches = self._copy_page(
                self._caches, jnp.asarray(cow_src, jnp.int32),
                jnp.asarray(pages[b0], jnp.int32))
            if self._caches_ref is not None:
                self._caches_ref = self._copy_page(
                    self._caches_ref, jnp.asarray(cow_src, jnp.int32),
                    jnp.asarray(pages[b0], jnp.int32))
            self.pool.free([cow_src])
        req.prefill_seq = seq
        req.prefill_pos = start
        req.prefill_target = n
        req.pos = 0
        req.hashed_blocks = min(b0, n // P)
        req.cached_prefill_tokens += start
        req.admit_t = time.monotonic()
        self._active[slot] = req
        self._admit_order.append(slot)
        self._maybe_open_canary(req)
        return True

    def _pop_next(self) -> Optional[_GenRequest]:
        if self._requeue:
            return self._requeue.pop(0)
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None

    def _push_back(self, req: _GenRequest):
        self._requeue.insert(0, req)

    # -- page growth / preemption ----------------------------------------

    def _pages_target(self, req: _GenRequest) -> int:
        """Pages a live slot must hold BEFORE the next tick (subclass
        hook: speculative verify needs its whole tree's rows covered, not
        just the next write position). Mid-prefill slots already hold
        their prompt's pages (pos is 0 until prefill completes)."""
        return min(self.pool.pages_for(req.pos + 1), self.max_pages_per_seq)

    def _ensure_pages(self):
        """Before a tick, every live slot grows to its _pages_target
        (base: the page holding the next write position); pool pressure
        preempts the youngest OTHER live request (`preemption=False`
        requeues the starved request itself — a stall, never a wrong
        answer)."""
        for slot in list(self._admit_order):
            req = self._active[slot]
            if req is None:
                continue
            target = self._pages_target(req)
            while req is self._active[slot] and len(req.pages) < target:
                got = self.pool.alloc(1)
                if got is not None:
                    self._reset_page_scales(got)
                    req.pages.append(got[0])
                    req.peak_pages = max(req.peak_pages, len(req.pages))
                    self._tables[slot, len(req.pages) - 1] = got[0]
                    self._mark_tables_dirty()
                    continue
                victims = [s for s in self._admit_order if s != slot]
                if self.preemption and victims:
                    self._evict(victims[-1])  # youngest other request
                else:
                    self._evict(slot)  # stall self until pages free up
                    break

    def _apply_defrag(self):
        import jax

        perm, old_to_new = self.pool.defrag()
        # the gather covers every leaf of each node's dict — a quantized
        # pool's (num_pages, Hkv) scale sidecar permutes on the same
        # axis 0 as its pages, so scales follow pages through compaction
        self._caches = {
            key: jax.tree.map(lambda b: b[perm], bufs)
            for key, bufs in self._caches.items()
        }
        if self._caches_ref is not None:
            self._caches_ref = {
                key: jax.tree.map(lambda b: b[perm], bufs)
                for key, bufs in self._caches_ref.items()
            }
        # EVERY owner's table: the (slots, max_pages) matrix rewrite
        # covers every live slot (decoding and mid-prefill alike); shared
        # pages get the same new id in every owner's row because
        # old_to_new is one global map. The pool rewrote the hash index
        # and LRU inside defrag().
        self._tables = old_to_new[self._tables]
        self._mark_tables_dirty()
        for s in self._admit_order:
            req = self._active[s]
            if req is not None:
                req.pages = [int(old_to_new[p]) for p in req.pages]
        self.defrags += 1

    # -- scheduler loop ----------------------------------------------------

    def _admission_pages(self, req: _GenRequest) -> int:
        """Free pages required before admitting `req`: the prompt's rows
        PLUS the first decode tick's write row (an exact-page-multiple
        prompt would otherwise admit and immediately preempt for its
        first tick's page). Conservative: prefix-cache hits can only
        reduce what admission actually allocates. Subclass hook:
        speculative verify instead requires the whole first verify tree
        to fit."""
        return self.pool.pages_for(len(req.seq_tokens()) + 1)

    def _outstanding_growth(self) -> int:
        """Pages the already-live slots still need to reach their
        _pages_target — admission must not hand them out (a slot admitted
        this tick would otherwise trigger a first-tick preemption when
        _ensure_pages collects the debt)."""
        debt = 0
        for s in self._admit_order:
            req = self._active[s]
            if req is not None:
                debt += max(0, self._pages_target(req) - len(req.pages))
        return debt

    def _admit_pending(self) -> bool:
        """Admission: free slot + the request's page budget available
        (net of pages live slots are still owed), FIFO (a too-big head
        request blocks later ones — no starvation). Returns whether
        anything was admitted."""
        admitted = False
        for slot in range(self.slots):
            if self._active[slot] is not None:
                continue
            req = self._pop_next()
            if req is None:
                break
            if (self._admission_pages(req) + self._outstanding_growth()
                    > self.pool.free_pages):
                self._push_back(req)
                break
            if not self._admit(req, slot):
                break
            admitted = True
        return admitted

    def _live(self) -> List[int]:
        return [s for s in range(self.slots) if self._active[s] is not None]

    def _mid_prefill(self, slot: int) -> bool:
        req = self._active[slot]
        return req is not None and req.prefill_pos < req.prefill_target

    # -- device-resident descriptor mirrors --------------------------------

    def _mark_tables_dirty(self):
        """Every `self._tables` write funnels through a call to this:
        the device mirror re-uploads on next use, never per tick."""
        self._tables_dev = None

    def _mark_temps_dirty(self):
        self._temps_dev = None
        # slot occupancy changed -> the mixed-megastep token ledger no
        # longer matches host truth; rebuilt on next dispatch. (Page
        # growth/defrag only move PAGES, never tokens, so the tables
        # dirty flag does not imply a seq rebuild.)
        self._seq_dev = None

    def _seq_device(self):
        """The (slots, Lbuf + 1) committed-token ledger on device for
        the mixed megastep: row s holds slot s's prompt + generated
        tokens (the FULL prompt for a mid-prefill slot, so chunk rows
        gather from it), column Lbuf is the masked-scatter trash
        column. Between dispatches the megastep's own seq output is
        reused; any admission/release/eviction rebuilds from host
        truth."""
        import jax.numpy as jnp

        if self._seq_dev is None:
            seq = np.zeros((self.slots, self._seq_cols + 1), np.int32)
            for s in range(self.slots):
                req = self._active[s]
                if req is None:
                    continue
                toks = (req.prefill_seq if self._mid_prefill(s)
                        else req.seq_tokens())
                seq[s, :len(toks)] = toks
            self._seq_dev = jnp.asarray(seq)
        return self._seq_dev

    def _tables_device(self):
        """The (slots, max_pages) page-table matrix on device, uploaded
        only when admission/growth/release/defrag dirtied it."""
        import jax.numpy as jnp

        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self._tables)
        return self._tables_dev

    def _temps_device(self):
        """Per-slot sampling temperatures on device (0.0 = greedy,
        also the empty-slot filler), uploaded only when slot occupancy
        changed."""
        import jax.numpy as jnp

        if self._temps_dev is None:
            self._temps_dev = jnp.asarray(np.array(
                [self._active[s].temperature if self._active[s] else 0.0
                 for s in range(self.slots)], np.float32))
        return self._temps_dev

    def _chain_descriptor_device(self, B, window):
        """Cached device copies of the default causal-chain descriptor
        for a (B, window) launch: depths 0..window-1 and the lower-
        triangular ancestor relation, identical every tick of the same
        shape — only tree launches (speculative verify) override them."""
        import jax.numpy as jnp

        key = (B, window)
        hit = self._chain_desc_cache.get(key)
        if hit is None:
            deps = np.tile(np.arange(window, dtype=np.int32), (B, 1))
            anc = np.tile(np.tril(np.ones((window, window), np.bool_)),
                          (B, 1, 1))
            hit = (jnp.asarray(deps), jnp.asarray(anc))
            self._chain_desc_cache[key] = hit
        return hit

    def _launch(self, items, window, tr, ntr):
        """Run ONE ragged step over packed work items. Each item is
        (slot, pos, tokens, depths, anc): `tokens` the item's q_len <=
        window live token ids, depths/anc None for the causal-chain
        default (decode rows, chunk pieces) or the (window,) node depths
        and (window, window) ancestor relation of a drafted tree. Rows
        past an item's q_len are padding: the kernel skips them and the
        entry point redirects their K/V writes to the null page — an
        item NEVER needs its table row nulled, so mid-prefill and idle
        slots simply aren't packed. Returns (probs, padded, total) with
        probs (len(items), window, vocab); padding is also rolled into
        the launch counters and the per-tick waste gauge."""
        import jax.numpy as jnp

        B = len(items)
        ids = np.zeros((B, window), np.int32)
        pos = np.zeros((B,), np.int32)
        qls = np.zeros((B,), np.int32)
        slot_idx = np.zeros((B,), np.int32)
        # the causal-chain default (decode rows, chunk pieces) is a pure
        # function of the launch shape — reuse its device copy instead of
        # re-uploading it every tick; only drafted trees override it
        chain = all(d is None and a is None for (_s, _p, _t, d, a) in items)
        if chain:
            deps_d, anc_d = self._chain_descriptor_device(B, window)
        else:
            deps = np.tile(np.arange(window, dtype=np.int32), (B, 1))
            anc = np.tile(np.tril(np.ones((window, window), np.bool_)),
                          (B, 1, 1))
        for i, (slot, p, toks, d, a) in enumerate(items):
            ql = len(toks)
            ids[i, :ql] = toks
            pos[i] = p
            qls[i] = ql
            slot_idx[i] = slot
            if d is not None:
                deps[i] = d
            if a is not None:
                anc[i] = a
        if not chain:
            deps_d, anc_d = jnp.asarray(deps), jnp.asarray(anc)
        # page tables ride the dirty-flagged device mirror: the canonical
        # one-item-per-slot decode launch uses it as-is, packed launches
        # gather their rows on device from a (B,) index upload
        tbl = self._tables_device()
        if B != self.slots or not np.array_equal(
                slot_idx, np.arange(self.slots, dtype=np.int32)):
            tbl = jnp.take(tbl, jnp.asarray(slot_idx), axis=0)
        probs, upd = self._step(
            tr, ntr, self._caches, tbl,
            jnp.asarray(pos), jnp.asarray(qls), deps_d, anc_d,
            jnp.asarray(ids))
        self._caches = upd
        if self._caches_ref is not None:
            # quant-error sampling (FF_TPU_KV_QUANT_DEBUG=1): the same
            # launch against the fp32 shadow cache; the running max abs
            # output delta over LIVE rows stays on device — metrics()
            # materializes it into the kv_quant_error gauge on scrape
            probs_ref, upd_ref = self._step(
                tr, ntr, self._caches_ref, tbl,
                jnp.asarray(pos), jnp.asarray(qls), deps_d, anc_d,
                jnp.asarray(ids))
            self._caches_ref = upd_ref
            live_rows = jnp.asarray(
                np.arange(window)[None, :] < qls[:, None])
            delta = jnp.max(jnp.abs(probs - probs_ref)
                            * live_rows[:, :, None])
            self._quant_err_dev = jnp.maximum(self._quant_err_dev, delta)
        total = B * window
        padded = total - int(qls.sum())
        self._c_rows.inc(total)
        self._c_pad.inc(padded)
        return probs, padded, total

    def _tick_prep(self) -> Optional[List[int]]:
        """Shared tick prologue (base and speculative loops): defrag if
        requested, admit, grow pages. Returns the live slots (decoding
        AND mid-prefill), or None when this tick should be skipped
        (nothing live; sleeps briefly when nothing was admitted
        either)."""
        with obs.span("tick_prep") as sp:
            if self._defrag_req.is_set():
                self._defrag_req.clear()
                with obs.span("defrag"):
                    self._apply_defrag()
            with obs.span("admit_pending"):
                admitted = self._admit_pending()
            live = self._live()
            self.peak_active = max(self.peak_active, len(live))
            if sp:
                sp.set(live=len(live),
                       mid_prefill=sum(1 for s in live
                                       if self._mid_prefill(s)),
                       pages_in_use=self.pool.pages_in_use,
                       admitted=admitted)
            if not live:
                if not admitted:
                    # idle/busy-wait time is charged to its own span so a
                    # trace separates "waiting for work" from real prep
                    t0 = time.monotonic()
                    with obs.span("idle_wait"):
                        time.sleep(0.001)
                    self._c_idle.inc()
                    self._c_idle_s.inc(time.monotonic() - t0)
                return None
            self._ensure_pages()  # may preempt: recompute live after
            return self._live() or None

    def _split_live(self, live):
        """(mid-prefill slots, decoding slots) for this tick."""
        pre = [s for s in live if self._mid_prefill(s)]
        dec = [s for s in live if not self._mid_prefill(s)]
        return pre, dec

    def _prefill_tick(self, slots, tr, ntr):
        """Advance mid-prefill slots by chunks, at most `prefill_chunk`
        tokens ACROSS the tick (a shared Sarathi-style token budget —
        it bounds the tick's prefill FLOPs, protecting decode latency),
        writing K/V straight into their pool pages. The start slot
        rotates tick to tick so a long prompt cannot starve a later
        slot's prefill out of the budget indefinitely. The chunk
        finishing a prompt samples the request's first token from its
        own last-row logits — the same rng/_pick discipline as the
        dense server's admission prefill.

        With ragged_pack every slot's chunk is split into window-sized
        pieces and the whole tick rides ONE packed launch (piece i+1
        sees piece i's rows as committed because K/V scatter precedes
        attention at each layer); ragged_pack=False reverts to one
        full-bucket launch per slot — the rotating-chunk baseline whose
        padding the packed path is measured against."""
        budget = self.prefill_chunk
        self.prefill_ticks += 1
        rot = self._prefill_rr % len(slots)
        self._prefill_rr += 1
        slots = slots[rot:] + slots[:rot]
        t0 = time.monotonic()
        sp = obs.span("prefill_tick").__enter__()
        padded = total = 0
        # plan the tick's chunks first (budget in rotated order), then
        # launch, then publish/sample per slot in the SAME rotated order
        # the per-slot launches used — the rng split sequence of a
        # finishing chunk is packing-invariant
        plan = []  # (slot, req, start, take)
        for s in slots:
            if budget <= 0:
                break
            req = self._active[s]
            take = min(budget, req.prefill_target - req.prefill_pos)
            plan.append((s, req, req.prefill_pos, take))
            budget -= take
        if self.ragged_pack:
            items = []
            ends = []  # index+row of each chunk's last piece in `items`
            # window = the tick's largest chunk, capped at _chunk_rows:
            # small chunks never pad past their own length (the legacy
            # buckets floor at 8) and big chunks split into pieces
            # instead of rounding up to the next power-of-two bucket
            W = min(self._chunk_rows, max(take for _, _, _, take in plan))
            for s, req, start, take in plan:
                for off in range(0, take, W):
                    piece = min(W, take - off)
                    items.append((s, start + off,
                                  req.prefill_seq[start + off:
                                                  start + off + piece],
                                  None, None))
                ends.append((len(items) - 1, (take - 1) % W))
            probs, padded, total = self._launch(items, W, tr, ntr)
            rows = [probs[i:i + 1, r, :] for i, r in ends]
        else:
            rows = []
            for s, req, start, take in plan:
                bucket = self._bucket(take)
                p, pad, tot = self._launch(
                    [(s, start, req.prefill_seq[start:start + take],
                      None, None)], bucket, tr, ntr)
                rows.append(p[0:1, take - 1, :])
                padded += pad
                total += tot
        for (s, req, start, take), row in zip(plan, rows):
            req.prefill_pos = start + take
            req.prefill_tokens += take
            self._publish_prefix(req, req.prefill_pos)
            if req.prefill_pos >= req.prefill_target:
                # publish the PROMPT's partial tail now, before decode
                # appends rows to the same page: the entry only names
                # rows [0, tail) and those are immutable, so an
                # identical or extending prompt can COW-clone this page
                # while this request keeps decoding into it (the first
                # token is appended below, so seq_tokens() still equals
                # prefill_seq here)
                self._publish_tail(req)
                self._sample_first_token(s, req, row)
                self._finish_if_done(s)
                if self._active[s] is not None:
                    # disagg hook: a PrefillWorker hands the request off
                    # to its decode worker here instead of decoding it
                    self._on_prefill_complete(s)
        chunked = self.prefill_chunk - budget
        self._g_waste.set(padded / total if total else 0.0)
        if sp:
            sp.set(slots=len(slots), chunk_tokens=chunked,
                   padded_rows=padded, total_rows=total)
        sp.__exit__(None, None, None)
        dt = time.monotonic() - t0
        self._h_prefill.observe(dt)
        led = obs.ledger()
        if led is not None:
            led.record("prefill", dt, batch=len(slots), chunk=chunked)

    def _decode_tick(self, live, tr, ntr):
        """One plain single-token decode tick for the decoding slots
        (also dispatched by the speculative server when no live slot can
        use a tree — all-sampled ticks skip the tree-verify FLOPs).
        Mid-prefill slots ride along with nulled table rows (fixed-shape
        program) and count the tick as decode/prefill overlap."""
        import jax

        t0 = time.monotonic()
        sp = obs.span("decode_tick").__enter__()
        if sp:
            sp.set(live=len(live), pages_in_use=self.pool.pages_in_use)
        # one item per slot — q_len 1 for the decoding slots, 0 for idle
        # and mid-prefill ones (no work, writes to the null page), so the
        # launch compiles once for (slots, 1) and probs stays
        # slot-indexed for the one shared _pick split
        dec = set(live)
        items = [(s, self._active[s].pos if s in dec else 0,
                  [int(self._tokens[s])] if s in dec else [],
                  None, None)
                 for s in range(self.slots)]
        probs, padded, total = self._launch(items, 1, tr, ntr)
        self._g_waste.set(padded / total if total else 0.0)
        if sp:
            sp.set(padded_rows=padded, total_rows=total)
        self._rng, sub = jax.random.split(self._rng)
        toks = np.asarray(self._pick(probs[:, -1, :],
                                     self._temps_device(), sub))
        self._steps += 1
        # one host round-trip bought len(live) tokens — the same
        # counters the megastep path feeds, so N=1 vs N>1 compare
        self._c_rt.inc()
        self._c_dtok.inc(len(live))
        if self._c_dtok.value:
            self._g_rt_tok.set(self._c_rt.value / self._c_dtok.value)
        for s in self._admit_order:
            if self._mid_prefill(s):
                self._active[s].decode_overlap_ticks += 1
        for s in live:
            req = self._active[s]
            req.pos += 1
            req.tokens.append(int(toks[s]))
            self._tokens[s] = toks[s]
            self._publish_prefix(req, req.pos)
            self._finish_if_done(s)
        sp.__exit__(None, None, None)
        dt = time.monotonic() - t0
        self._h_tick.observe(dt)
        self._h_tokens.observe(len(live))
        led = obs.ledger()
        if led is not None:
            led.record("decode", dt, batch=len(live))

    def _decode_megastep(self, live, tr, ntr):
        """Up to `megastep_ticks` decode ticks in ONE jitted dispatch
        (Executor.paged_megastep_fn): positions, page-table tail
        capacity, finish flags, temps, the rng chain and the sampled-
        token buffer all live on device inside a `jax.lax.while_loop`;
        the host consumes the whole (ticks, slots) buffer in a single
        transfer, then replays its bookkeeping (append, prefix
        publication, finish) token by token in the one-tick order.

        The device loop breaks BEFORE any tick it cannot run alone:
        after a slot finishes (length, or eos mid-megastep) or when a
        slot's next write row would cross its allocated pages — so page
        growth, admission, eviction and defrag stay host-side exactly
        where poolcheck models them, and the prefix cache sees the same
        page-boundary publications the one-tick loop produces. Only
        dispatched on pure-decode ticks: mid-prefill chunks keep host
        granularity (_loop_body), so a finishing chunk always resumes
        the host. Greedy AND fixed-seed sampled output are token-
        identical to the one-tick loop — the rng advances by the same
        split chain, one split per tick."""
        import jax
        import jax.numpy as jnp

        if self._caches_ref is not None:
            # DYNAMIC stand-down, not a construction-time choice: a
            # kv_quant_canary window can open on any admission mid-serve
            # and the fp32 shadow must observe every launch — delegate
            # this dispatch to the one-tick path (which replays against
            # the shadow) no matter which call site asked for a megastep
            return self._decode_tick(live, tr, ntr)
        t0 = time.monotonic()
        sp = obs.span("megastep").__enter__()
        if sp:
            sp.set(live=len(live), pages_in_use=self.pool.pages_in_use)
        P = self.page_size
        pos = np.zeros((self.slots,), np.int32)
        rem = np.zeros((self.slots,), np.int32)
        cap = np.zeros((self.slots,), np.int32)
        act = np.zeros((self.slots,), np.bool_)
        for s in live:
            req = self._active[s]
            pos[s] = req.pos
            rem[s] = req.max_new - len(req.tokens)
            cap[s] = len(req.pages) * P
            act[s] = True
        caches, out, done, rng, ticks = self._megastep(
            tr, ntr, self._caches, self._tables_device(),
            jnp.asarray(pos), jnp.asarray(self._tokens),
            self._temps_device(), jnp.asarray(rem), jnp.asarray(cap),
            jnp.asarray(act), self._rng)
        self._caches = caches
        self._rng = rng
        # the ONE host sync of the megastep: token buffer + finish
        # flags + tick count in a single transfer
        out_np, done_np, n = jax.device_get((out, done, ticks))
        n = int(n)
        if done_np.any():
            reason = "finish"
        elif n < self.megastep_ticks:
            reason = "page"
        else:
            reason = "limit"
        # replay host bookkeeping tick by tick in the one-tick order:
        # every executed tick emitted a token for every live slot (the
        # loop breaks before the tick AFTER a finish, so finishes only
        # ever land on the last replayed tick)
        for t in range(n):
            self._steps += 1
            for s in live:
                req = self._active[s]
                tok = int(out_np[t, s])
                req.pos += 1
                req.tokens.append(tok)
                self._tokens[s] = tok
                self._publish_prefix(req, req.pos)
                self._finish_if_done(s)
        self._on_megastep_resume()
        rows, padded = n * self.slots, n * (self.slots - len(live))
        self._c_rows.inc(rows)
        self._c_pad.inc(padded)
        self._g_waste.set(padded / rows if rows else 0.0)
        self._c_rt.inc()
        self._c_dtok.inc(n * len(live))
        if self._c_dtok.value:
            self._g_rt_tok.set(self._c_rt.value / self._c_dtok.value)
        self._h_mega.observe(n)
        self._c_break[reason].inc()
        if sp:
            sp.set(ticks=n, break_reason=reason, fused_rows=n * len(live))
        sp.__exit__(None, None, None)
        dt = time.monotonic() - t0
        # per-tick effective latency: the histogram stays comparable
        # across megastep widths (the A/B's p50/p95 read)
        self._h_tick.observe(dt / max(n, 1))
        self._h_tokens.observe(len(live))
        led = obs.ledger()
        if led is not None:
            led.record("decode", dt, batch=len(live), width=max(n, 1))

    # -- universal (mixed) megastep ---------------------------------------

    def _mixed_spec_slot(self, req) -> bool:
        """Whether a decoding slot drafts an on-device speculative chain
        inside the mixed megastep. Base server: never (no SpecConfig);
        the speculative subclass drafts on greedy slots."""
        return False

    def _on_mixed_spec_tick(self, req, emitted: int):
        """Hook: one drafting slot's tick committed `emitted` tokens
        (accepted prefix + bonus). The speculative subclass feeds its
        acceptance counters; the base server never drafts."""

    def _overlap_window(self):
        """Host work run in the SHADOW of the in-flight mixed dispatch
        (overlap_dispatch=True), against a one-deep staged snapshot of
        scheduler state: admission of pending requests. Admission is
        structurally safe here — it only touches FREE slots and FREE
        pages (never a live slot's table row, so no bookkeeping runs
        against a page table the in-flight dispatch is using), it never
        preempts, and its device work (COW clone, scale reset, tier
        fetches, canary snapshot) chains on the in-flight arrays by
        data dependency. Page growth, eviction and defrag stay strictly
        AFTER the fence (the next _tick_prep) — the racecheck `dispatch`
        protocol model explores exactly this ownership discipline."""
        with obs.span("overlap_admit"):
            self._admit_pending()

    def _mixed_dispatch(self, live, tr, ntr) -> bool:
        """Dispatch this tick as ONE universal megastep when the mode is
        on and no canary shadow window is open (the shadow must observe
        every launch, so an open window stands the fused path down
        dynamically — same discipline as _decode_megastep's guard).
        Returns True when the tick was handled."""
        if self._mixed_fn is None or self._caches_ref is not None:
            return False
        self._mixed_megastep(live, tr, ntr)
        return True

    def _mixed_megastep(self, live, tr, ntr):
        """Up to `megastep_ticks` MIXED ticks in one jitted dispatch
        (Executor.paged_mixed_megastep_fn): decode rows, mid-prefill
        chunk rows and on-device drafted spec chains ride the same
        while_loop carry, and the host consumes one (ticks, slots, E)
        token buffer per dispatch. With overlap_dispatch the host runs
        the next tick's admission work while the device computes and
        only then blocks on the fence (the single device_get), exporting
        host_overlap_ratio.

        Break reasons extend the decode megastep's: `chunk` hands
        control back after a prefill chunk COMPLETES (page publication
        + first-token bookkeeping are host work — poolcheck's model),
        `verify` when a drafting slot's next chain would cross its
        allocated pages; `finish`/`page`/`limit` mean what they mean on
        the pure-decode path. The first token of a completing prefill
        is sampled ON DEVICE with the tick's shared rng split, so the
        sampled stream is megastep-width invariant (N vs 1) by the same
        one-split-per-tick argument as the decode megastep."""
        import jax
        import jax.numpy as jnp

        t0 = time.monotonic()
        sp = obs.span("megastep").__enter__()
        if sp:
            sp.set(live=len(live), pages_in_use=self.pool.pages_in_use)
        P = self.page_size
        W = self._mixed_window
        D = self._spec_depth
        pos = np.zeros((self.slots,), np.int32)
        pfp = np.zeros((self.slots,), np.int32)
        pft = np.zeros((self.slots,), np.int32)
        rem = np.zeros((self.slots,), np.int32)
        cap = np.zeros((self.slots,), np.int32)
        dec_act = np.zeros((self.slots,), np.bool_)
        pf_act = np.zeros((self.slots,), np.bool_)
        spec_m = np.zeros((self.slots,), np.bool_)
        for s in live:
            req = self._active[s]
            cap[s] = len(req.pages) * P
            if self._mid_prefill(s):
                pf_act[s] = True
                pfp[s] = req.prefill_pos
                pft[s] = req.prefill_target
                rem[s] = req.max_new  # the first token counts
            else:
                dec_act[s] = True
                pos[s] = req.pos
                rem[s] = req.max_new - len(req.tokens)
                spec_m[s] = self._mixed_spec_slot(req)
        caches, seq_d, out, cnt, done, pf_fin, rng, ticks = \
            self._mixed_fn(
                tr, ntr, self._caches, self._tables_device(),
                self._seq_device(), jnp.asarray(pos), jnp.asarray(pfp),
                jnp.asarray(pft), self._temps_device(),
                jnp.asarray(rem), jnp.asarray(cap),
                jnp.asarray(dec_act), jnp.asarray(pf_act),
                jnp.asarray(spec_m), self._rng)
        # hand the carry forward immediately (async dispatch): the next
        # tick's inputs chain on these by data dependency
        self._caches = caches
        self._rng = rng
        self._seq_dev = seq_d
        host_s = 0.0
        if self.overlap_dispatch:
            h0 = time.monotonic()
            self._overlap_window()
            host_s = time.monotonic() - h0
        f0 = time.monotonic()
        # the ONE host sync of the dispatch — the fence. Everything that
        # reads the token buffer (the bookkeeping replay below) runs
        # strictly after it: single token-buffer owner.
        out_np, cnt_np, done_np, pf_np, n = jax.device_get(
            (out, cnt, done, pf_fin, ticks))
        fence_s = time.monotonic() - f0
        if self.overlap_dispatch:
            wait = host_s + fence_s
            self._g_overlap.set(host_s / wait if wait > 0 else 0.0)
        n = int(n)
        if n == 0:
            # defensive only: the device refused the first tick (a
            # capacity race _ensure_pages should have prevented). Run
            # one legacy host-granularity tick so the loop always makes
            # progress; no rng split was consumed by the empty dispatch.
            sp.__exit__(None, None, None)
            pre, dec = self._split_live(live)
            if pre:
                self._prefill_tick(pre, tr, ntr)
            if dec:
                self._decode_tick(dec, tr, ntr)
            return
        pf_slots = [s for s in live if pf_act[s]]
        dec_slots = [s for s in live if dec_act[s]]
        if pf_slots:
            self.prefill_ticks += 1
            if dec_slots:
                for s in pf_slots:
                    self._active[s].decode_overlap_ticks += n
        # replay host bookkeeping tick by tick in the one-tick order;
        # chunk completions and finishes only land on the last executed
        # tick (the loop breaks on them), so slot release can never race
        # an earlier tick's replay
        fused = 0
        dtok = 0
        for t in range(n):
            self._steps += 1
            last = t == n - 1
            for s in pf_slots:
                req = self._active[s]
                if req is None or req.prefill_pos >= req.prefill_target:
                    continue
                take = min(W, req.prefill_target - req.prefill_pos)
                fused += take
                req.prefill_pos += take
                req.prefill_tokens += take
                self._publish_prefix(req, req.prefill_pos)
                if pf_np[s] and last:
                    # mirror _prefill_tick's completion sequence — tail
                    # published while seq_tokens() still equals
                    # prefill_seq, THEN the device-sampled first token
                    self._publish_tail(req)
                    self._first_token_from_device(
                        s, req, int(out_np[t, s, 0]))
                    self._finish_if_done(s)
                    if self._active[s] is not None:
                        self._on_prefill_complete(s)
            for s in dec_slots:
                req = self._active[s]
                if req is None:
                    continue
                fused += (D + 1) if spec_m[s] else 1
                c = int(cnt_np[t, s])
                for j in range(c):
                    tok = int(out_np[t, s, j])
                    req.pos += 1
                    req.tokens.append(tok)
                    self._tokens[s] = tok
                dtok += c
                if spec_m[s]:
                    self._on_mixed_spec_tick(req, c)
                if c:
                    self._publish_prefix(req, req.pos)
                    self._finish_if_done(s)
        self._on_megastep_resume()
        if done_np.any():
            reason = "finish"
        elif pf_np.any():
            reason = "chunk"
        elif n < self.megastep_ticks:
            # the blocking slot needs page growth: a drafting slot that
            # cannot fit its next chain is a verify break, a plain
            # decode row crossing its pages a page break (cap is the
            # dispatch-time capacity — the same value the device cond
            # tested against the advanced positions)
            blocked_spec = any(
                spec_m[s] and self._active[s] is not None
                and self._active[s].pos + D + 1 > cap[s]
                for s in dec_slots)
            reason = "verify" if blocked_spec else "page"
        else:
            reason = "limit"
        Wl = max(W, D + 1)
        rows = n * self.slots * Wl
        padded = rows - fused
        self._c_rows.inc(rows)
        self._c_pad.inc(padded)
        self._g_waste.set(padded / rows if rows else 0.0)
        self._c_rt.inc()
        self._c_dtok.inc(dtok)
        if self._c_dtok.value:
            self._g_rt_tok.set(self._c_rt.value / self._c_dtok.value)
        self._h_mega.observe(n)
        self._c_break[reason].inc()
        if sp:
            sp.set(ticks=n, break_reason=reason, fused_rows=fused,
                   pf_slots=len(pf_slots), dec_slots=len(dec_slots))
        sp.__exit__(None, None, None)
        dt = time.monotonic() - t0
        self._h_tick.observe(dt / max(n, 1))
        self._h_tokens.observe(len(live))
        led = obs.ledger()
        if led is not None:
            led.record("decode", dt, batch=len(live), width=max(n, 1))

    def _on_megastep_resume(self):
        """Hook fired after a megastep's host bookkeeping replay, before
        its metrics are recorded — the host-resume point. Tests override
        it to assert pool invariants after every resume; the base server
        does nothing (check_invariants is too hot for the serving
        loop)."""

    def _loop_body(self, tr, ntr):
        while not self._stop.is_set():
            live = self._tick_prep()
            if live is None:
                continue
            if self._mixed_dispatch(live, tr, ntr):
                continue
            pre, dec = self._split_live(live)
            if pre:
                self._prefill_tick(pre, tr, ntr)
            if dec:
                if self._megastep is not None and not pre:
                    # _decode_megastep stands down by itself while a
                    # canary window is open (the fp32 shadow must
                    # observe every launch)
                    self._decode_megastep(dec, tr, ntr)
                else:
                    self._decode_tick(dec, tr, ntr)

    def _drain(self):
        super()._drain()
        for req in self._requeue:
            if not req.future.done():
                req.future.cancel()
        self._requeue.clear()
